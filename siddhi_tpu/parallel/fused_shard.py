"""Fused chains sharded over the device mesh: fuse + shard composed.

The annotation-era gates treated fusion and mesh sharding as rivals —
`@app:fuse` chains always ran single-device.  For all-filter chains
(stateless elementwise stages, no dense tail) the two compose exactly:
every stage's step is a per-row map with no cross-row state, so
splitting the BATCH axis over the mesh and psum-ing the emit count is
bit-identical to the single-device chain — each row's output depends
only on that row's lanes, and the emit materialization path already
orders rows by their batch position.

Stateful stages (running / sliding / dense tails) do NOT compose this
way: their state update order couples rows across the batch, and the
per-kind shard layouts of ``device_shard.py`` (group axis, replicated
ring) have no fused-chain formulation yet.  The cost model enumerates
those compositions and rejects them with a counted reason
(planner/costmodel.py), and the fusion planner falls back to the plain
single-device fused engine with a counted ``shardedFallbackReason``.

``ShardedFusedGraphEngine`` is a subclass, not a proxy: the runtime
(core/fused_graph.py FusedChainRuntime) and the deferred-emit path read
``graph.stages`` / ``graph.dense`` / ``graph.output_names`` / per-stage
snapshots directly, and filter stages carry EMPTY state dicts — so the
only seams are ``make_step`` (wrap the raw fused step in shard_map over
the batch axis) and ``_pad_batch`` (round the chunk width up to a
shard-count multiple).
"""

from __future__ import annotations

from typing import Callable, List

import numpy as np

from siddhi_tpu.core.exceptions import SiddhiAppCreationError
from siddhi_tpu.ops.device_query import _pow2
from siddhi_tpu.ops.fused_graph import FusedGraphEngine


class ShardedFusedGraphEngine(FusedGraphEngine):
    """An all-filter fused chain with its batch axis split over an
    N-device mesh; emit counts psum to one replicated scalar so the
    async-emit count gate is unchanged."""

    #: cycle-tracer span label (engine_kind of the single-device chain
    #: is implicit 'fused'; sharded dispatches must be distinguishable)
    engine_kind = "fused_shard"

    def __init__(self, stages: List, mesh, axis_name: str = "p"):
        for eng in stages:
            if eng.kind != "filter":
                raise SiddhiAppCreationError(
                    f"fuse+shard covers all-filter chains (stateless "
                    f"elementwise stages); stage kind '{eng.kind}' "
                    "couples rows through window state — single-device "
                    "fused engine used")
        super().__init__(stages, None, None)
        self.mesh = mesh
        self.axis_name = axis_name
        self.n_shards = int(np.prod(mesh.devices.shape))

    def _pad_batch(self, n: int) -> int:
        B = _pow2(n)
        if B % self.n_shards:
            B = -(-B // self.n_shards) * self.n_shards
        return B

    def make_step(self) -> Callable:
        if self._fused_step is not None:
            return self._fused_step
        import jax
        from jax.sharding import PartitionSpec as P

        from siddhi_tpu.parallel.mesh import get_shard_map

        shard_map = get_shard_map()
        raw = self._build_fused()
        a = self.axis_name

        def sharded(states, cols, rels, grp, valid):
            states2, emitmask, out, fwd, n_local = raw(
                states, cols, rels, grp, valid)
            # one replicated count scalar for the async-emit gate
            total = jax.lax.psum(n_local, axis_name=a)
            return states2, emitmask, out, fwd, total

        # pytree-prefix specs: filter stages hold EMPTY state dicts
        # (nothing to place), every lane/mask shards along the batch
        # axis, and the count comes back replicated
        self._fused_step = jax.jit(shard_map(
            sharded,
            mesh=self.mesh,
            in_specs=(P(), P(a), P(a), P(a), P(a)),
            out_specs=(P(), P(a), P(a), P(a), P()),
        ), donate_argnums=(0,))
        return self._fused_step
