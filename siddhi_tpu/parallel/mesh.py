"""Mesh construction, sharded pattern-engine wrapper, event routing."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from siddhi_tpu.core.exceptions import SiddhiAppCreationError


def distributed_initialize(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None):
    """Multi-host bring-up: one JAX process per host, ICI within a slice,
    DCN across slices (the reference has no analog — its clustering is
    an external k8s operator).  Call once per process before any other
    JAX call.

    On CPU platforms (tests, local multi-process validation) the gloo
    cross-process collective backend is selected automatically — without
    it, collectives over a multi-process CPU mesh fail at dispatch.
    Exercised by tests/test_distributed.py with a real 2-process mesh.
    """
    import os

    import jax

    # CPU detection must not touch a backend (distributed.initialize
    # must run first), so check the two explicit selection channels;
    # a no-accelerator implicit CPU fallback isn't detectable here —
    # set JAX_PLATFORMS=cpu explicitly in that case
    plat = (os.environ.get("JAX_PLATFORMS", "")
            or str(getattr(jax.config, "jax_platforms", None) or ""))
    if plat.startswith("cpu"):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # older jax: option absent; collectives may
            pass           # still work via the default implementation
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def ensure_virtual_devices(n_devices: int) -> bool:
    """Best-effort bootstrap of >=n virtual CPU devices for mesh testing.

    Must run before the CPU backend initializes (jax.config rejects the
    update afterwards).  Returns True if >=n CPU devices are configured
    or already available; False (with a warning) if the update was
    rejected because backends initialized first — callers then see the
    real device count and can raise a clear error."""
    import warnings

    import jax

    try:
        if int(jax.config.jax_num_cpu_devices or 0) < n_devices:
            jax.config.update("jax_num_cpu_devices", n_devices)
        return True
    except Exception as e:
        # older jax (< jax_num_cpu_devices): the XLA flag serves the
        # same purpose and is likewise read lazily at CPU-backend init.
        # Must run before the jax.devices() probe below — the probe
        # itself initializes the CPU backend.
        try:
            import os

            from jax._src import xla_bridge as _xb

            if not _xb.backends_are_initialized():
                flags = os.environ.get("XLA_FLAGS", "")
                if "xla_force_host_platform_device_count" not in flags:
                    os.environ["XLA_FLAGS"] = (
                        f"{flags} --xla_force_host_platform_device_count="
                        f"{n_devices}").strip()
                return True
        except Exception as xe:
            # internal-module probe (jax._src.xla_bridge) is version-
            # fragile by design; fall through to the device-count probe
            import logging

            logging.getLogger("siddhi_tpu.mesh").debug(
                "XLA_FLAGS virtual-device probe unavailable: %s", xe)
        try:
            if len(jax.devices("cpu")) >= n_devices:
                return True
        except RuntimeError:
            pass
        warnings.warn(
            f"could not configure {n_devices} virtual CPU devices "
            f"(backends already initialized?): {e}", RuntimeWarning)
        return False


def get_shard_map():
    """``jax.shard_map`` moved out of ``jax.experimental`` only in newer
    jax releases — resolve whichever spelling this jax provides."""
    import jax

    try:
        return jax.shard_map
    except AttributeError:
        from jax.experimental.shard_map import shard_map

        return shard_map


def make_mesh(n_devices: Optional[int] = None, axis_name: str = "p",
              devices=None):
    """1-D device mesh over the partition axis.

    Falls back to virtual CPU devices when the default platform is short
    (e.g. a single real TPU chip): sharding semantics are identical, so
    the multi-chip path stays testable everywhere.  The fallback must
    configure the CPU device count BEFORE any backend initializes, so it
    is attempted before the default jax.devices() lookup."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        if n_devices is not None:
            ensure_virtual_devices(n_devices)
        devices = jax.devices()
        if n_devices is not None and len(devices) < n_devices:
            try:
                devices = jax.devices("cpu")
            except RuntimeError:
                pass
    if n_devices is not None:
        if len(devices) < n_devices:
            raise SiddhiAppCreationError(
                f"need {n_devices} devices, have {len(devices)} "
                "(set JAX_NUM_CPU_DEVICES / "
                "XLA_FLAGS=--xla_force_host_platform_device_count for CPU testing)"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), axis_names=(axis_name,))


def _pow2(n: int, floor: int = 16) -> int:
    return max(1 << (max(n, 1) - 1).bit_length(), floor)


def route_to_shards(n_shards: int, parts_per_shard: int,
                    part: np.ndarray, cols: Dict[str, np.ndarray],
                    ts: np.ndarray,
                    batch_per_shard: Optional[int] = None
                    ) -> Tuple[np.ndarray, Dict[str, np.ndarray], np.ndarray,
                               np.ndarray, np.ndarray]:
    """Host-side event routing: bucket a batch by owning shard
    (``global_part // parts_per_shard``, shard-major layout), rewrite
    partition ids to shard-local indices, and pad every shard's bucket
    to the same pow-2 length (bounding jit recompilation, as the
    unsharded wrapper does) so the result concatenates into one array
    whose equal slices are the per-device inputs of a shard_map step.

    Padded rows carry local index ``parts_per_shard`` — each shard's
    dedicated scratch row — so their scatter-back can never collide
    with a real partition's update.

    Returns ``(local_part, cols, ts, valid, pos)`` where ``pos[i]`` is
    the padded-slot index of input event ``i`` (for mapping per-event
    emit/out rows back to inputs).  Callers must not route two events of
    the same partition in one call (gather/scatter would race); use
    :meth:`ShardedPatternEngine.process`, which splits collision rounds.
    """
    part = np.asarray(part)
    owner = part // parts_per_shard
    if len(part) and (owner.max() >= n_shards or owner.min() < 0):
        raise SiddhiAppCreationError(
            f"partition id out of range for {n_shards} x {parts_per_shard} layout")
    counts = np.bincount(owner, minlength=n_shards)
    max_count = int(counts.max()) if len(part) else 0
    B = int(batch_per_shard) if batch_per_shard is not None else _pow2(max_count)
    if max_count > B:
        raise SiddhiAppCreationError(
            f"shard bucket overflow: {max_count} events for one shard "
            f"> batch_per_shard={B}")
    n = n_shards * B
    # scratch slot: local index parts_per_shard (one reserved row/shard)
    local_part = np.full(n, parts_per_shard, dtype=np.int32)
    out_ts = np.zeros(n, dtype=np.asarray(ts).dtype)
    valid = np.zeros(n, dtype=bool)
    out_cols = {k: np.zeros(n, dtype=np.asarray(v).dtype) for k, v in cols.items()}
    # vectorized within-bucket rank (cumcount over stably-sorted owners)
    order = np.argsort(owner, kind="stable")
    sorted_owner = owner[order]
    starts = np.searchsorted(sorted_owner, np.arange(n_shards), side="left")
    rank_sorted = np.arange(len(part)) - starts[sorted_owner]
    pos = np.empty(len(part), dtype=np.int64)
    pos[order] = sorted_owner * B + rank_sorted
    local_part[pos] = (part % parts_per_shard).astype(np.int32)
    out_ts[pos] = np.asarray(ts)
    valid[pos] = True
    for k, v in cols.items():
        out_cols[k][pos] = np.asarray(v)
    return local_part, out_cols, out_ts, valid, pos


class ShardedPatternEngine:
    """A dense NFA engine sharded over a mesh's partition axis.

    Wraps ``siddhi_tpu.ops.dense_nfa.compile_pattern``'s engine: state
    rows are laid out shard-major with one scratch row per shard
    (absorbing padded lanes), device_put with a ``P('p', ...)``
    sharding, and the step runs under ``shard_map`` (shard-local state
    access, psum'd global match count).

    Use :meth:`process` for the safe high-level path (collision-round
    splitting, relative-timestamp normalization, per-event output
    mapping); ``route``/``step`` are the raw building blocks whose
    callers must uphold those contracts themselves.
    """

    def __init__(self, engine, mesh, axis_name: str = "p",
                 stream_key: Optional[str] = None):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.engine = engine
        self.mesh = mesh
        self.axis_name = axis_name
        self.n_shards = int(np.prod(mesh.devices.shape))
        if engine.n_partitions % self.n_shards:
            raise SiddhiAppCreationError(
                f"{engine.n_partitions} partitions not divisible by "
                f"{self.n_shards} shards")
        # usable partitions per shard; +1 scratch row per shard
        self.parts_per_shard = engine.n_partitions // self.n_shards
        self.rows_per_shard = self.parts_per_shard + 1

        self.stream_key = stream_key or engine.default_stream
        self.col_keys = engine.device_col_keys(self.stream_key)
        step = engine.make_step(self.stream_key, jit=False)
        jnp = engine.jnp
        a = axis_name

        # row-sharded on this wrapper's axis name; trailing
        # node/instance/register dims replicated.  Ranks come from the
        # engine's own pspecs (len == ndim) — no throwaway host
        # allocation of the full state just to read shapes.
        self.state_specs = {
            k: P(a, *([None] * (len(spec) - 1)))
            for k, spec in engine.state_pspecs().items()
        }
        specs = self.state_specs

        def sharded_step(state, part, cols, ts, valid):
            new_state, emit, outs, anchor, local = step(state, part, cols,
                                                        ts, valid)
            total = jax.lax.psum(local, axis_name=a)
            return new_state, emit, outs, anchor, total

        # donate the state pytree: at 1M+ partitions the rows dominate
        # HBM and double-buffering them would halve capacity
        self._step = jax.jit(get_shard_map()(
            sharded_step,
            mesh=mesh,
            in_specs=(specs, P(a), {k: P(a) for k in self.col_keys},
                      P(a), P(a)),
            out_specs=(specs, P(a, None),
                       {"f": P(a, None, None), "i": P(a, None, None)},
                       P(a, None), P()),
        ), donate_argnums=(0,))
        self._P = P
        self._NamedSharding = NamedSharding
        self._jax = jax

    # -- state ---------------------------------------------------------------

    def _put(self, x, spec):
        return self._jax.device_put(
            x, self._NamedSharding(self.mesh, spec))

    def init_state(self):
        """Zero state with shard-major layout: each shard owns
        ``parts_per_shard`` partition rows plus one trailing scratch
        row (same per-row init values as the unsharded engine).

        Built from the engine's NUMPY init (init_state_host) — calling
        the device init here would allocate on the default backend,
        which may be a TPU the caller never intends to touch (the
        round-2 dryrun crash)."""
        host = self.engine.init_state_host()
        n_rows = self.n_shards * self.rows_per_shard
        state = {}
        for k, v in host.items():
            arr = np.zeros((n_rows,) + v.shape[1:], dtype=v.dtype)
            # replicate the engine's per-row init (row 0 of the host
            # state — all rows are initialized identically)
            arr[...] = v[0]
            state[k] = self._put(arr, self.state_specs[k])
        return state

    # -- stepping ------------------------------------------------------------

    def route(self, part, cols, ts, batch_per_shard=None):
        """Host arrays -> device arrays routed/padded per shard; also
        returns the input->slot map.  Caller contract: at most one event
        per partition per call, timestamps already relative int32, cols
        already device-lane columns (engine.prepare_cols: float32 floats
        + int32 hi/lo pairs)."""
        P = self._P
        a = self.axis_name
        lp, rc, rts, valid, pos = route_to_shards(
            self.n_shards, self.parts_per_shard, part, cols, ts,
            batch_per_shard)
        return (
            self._put(lp, P(a)),
            {k: self._put(np.asarray(v), P(a)) for k, v in rc.items()},
            self._put(np.asarray(rts, dtype=np.int32), P(a)),
            self._put(valid, P(a)),
        ), pos

    def step(self, state, part, cols, ts, valid):
        """One sharded step: ``(state', emit[B, 2I], out_vals[B, 2I, O],
        emit_anchor[B, 2I], global_matches)``.

        The input ``state`` is DONATED (its device buffers are consumed
        on real hardware — snapshot it before stepping if needed; always
        rebind to the returned state).  CPU meshes ignore donation, so
        only device runs surface misuse."""
        return self._step(state, part, cols, ts, valid)

    def process(self, state, part: np.ndarray, cols: Dict[str, np.ndarray],
                ts: np.ndarray):
        """Safe batch entry point mirroring DensePatternEngine.process:
        splits rounds so each partition appears at most once per step,
        normalizes timestamps, and flattens per-instance matches back to
        input order.  Returns ``(state, match_ev_idx[m], out[m, n_out],
        total_matches)`` with same-event matches ordered by arming age."""
        state, pending = self.process_deferred(state, part, cols, ts)
        total = pending.resolve() if pending is not None else 0
        if total == 0:
            from siddhi_tpu.ops.dense_nfa import flatten_match_parts

            ev, out = flatten_match_parts(
                [], [], [], max(len(self.engine.out_spec), 1))
            return state, ev, out, total
        from siddhi_tpu.core.emit_queue import fetch_coalesced

        ev, out = pending.materialize(fetch_coalesced(
            pending.device_arrays()))
        return state, ev, out, total

    def process_deferred(self, state, part: np.ndarray,
                         cols: Dict[str, np.ndarray], ts: np.ndarray):
        """Async-emit variant of :meth:`process`: every round's match
        outputs stay device-resident in a :class:`DeferredDenseEmit`
        (None only for empty input).  Nothing crosses device->host here:
        the psum'd per-round count gate stays a device scalar until
        ``pending.resolve()`` — the ingest stage (core/ingest_stage.py)
        defers that fetch past the next batch's dispatch.  Returns
        ``(state, pending_or_None)``."""
        from siddhi_tpu.ops.dense_nfa import (
            DeferredDenseEmit,
            _collision_rounds,
        )

        part = np.asarray(part)
        rel64 = self.engine.rel_ts64(np.asarray(ts, dtype=np.int64))
        state, rel64 = self.engine.maybe_re_anchor(
            state, rel64,
            to_device=lambda k, v: self._put(v, self.state_specs[k]))
        rel = rel64.astype(np.int32)
        prepared = self.engine.prepare_cols(self.stream_key, cols)
        pending = DeferredDenseEmit(self.engine)
        faults = getattr(self.engine, "faults", None)
        if faults is not None:
            faults.check("step.shard")
        for ridx in _collision_rounds(part):
            args, pos = self.route(
                part[ridx],
                {k: v[ridx] for k, v in prepared.items()},
                rel[ridx],
            )
            state, emit, outs, anchor, round_total = self.step(state, *args)
            pending.chunks.append({
                "emit": emit, "f": outs["f"], "i": outs["i"],
                "anchor": anchor, "sel": pos, "ridx": ridx,
                "count": round_total,
            })
        return state, (pending if pending.chunks else None)
