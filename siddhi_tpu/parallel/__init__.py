"""Scale-out: device meshes, sharded NFA state, event routing.

The reference is a single-JVM library with no distributed backend
(SURVEY.md §2.3); its scale axis is key-partitioned parallelism
(``partition with (key of S)`` — per-key cloned state behind
ThreadLocals, partition/PartitionStreamReceiver.java:82-118).  The
TPU-native equivalent implemented here:

- the **partition axis is sharded over a ``jax.sharding.Mesh``** —
  per-key NFA/window/aggregator state rows live in HBM, each device
  owning a contiguous range of keys;
- the compiled step runs under ``jax.shard_map``: shard-local gathers/
  scatters (a shard owns its keys, so the hot path needs **no
  cross-device collectives**), with ``psum``/``all_gather`` only for
  global match counts / global emission;
- events are **routed host-side to their owning shard** (the DCN-ingest
  analog: multi-host deployments feed each host the key range it owns);
- multi-host initialization wraps ``jax.distributed`` (ICI within a
  slice, DCN across hosts).
"""

from siddhi_tpu.parallel.device_shard import ShardedDeviceQueryEngine
from siddhi_tpu.parallel.mesh import (
    ShardedPatternEngine,
    distributed_initialize,
    ensure_virtual_devices,
    make_mesh,
    route_to_shards,
)

__all__ = [
    "ShardedDeviceQueryEngine",
    "ShardedPatternEngine",
    "distributed_initialize",
    "ensure_virtual_devices",
    "make_mesh",
    "route_to_shards",
]
