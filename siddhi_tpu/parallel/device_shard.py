"""Device-query engine sharded over a mesh's group axis.

``ShardedDeviceQueryEngine`` wraps a running-kind
:class:`siddhi_tpu.ops.device_query.DeviceQueryEngine`: per-group
aggregation state rows ([G, A] sum/cnt/min/... arrays) are laid out
shard-major with one scratch row per shard, device_put with a
``P('p')`` row sharding, and the per-event step runs under
``jax.shard_map`` — shard-local scatters only, no collectives on the
hot path (a group's rows live on exactly one shard, the same contract
as the dense NFA's partition axis, mesh.py).

Group ids intern host-side exactly as in the unsharded engine; a
round-robin bijection (``gid -> (gid % n_shards) * per_shard +
gid // n_shards``) spreads sequentially-allocated ids across shards so
early groups don't pile onto shard 0.  Events route host-side to their
owning shard (:func:`route_to_shards`) — same-group rows keep their
relative order inside one shard bucket, so the step's within-batch
same-group prefix matmul is unaffected.

The wrapper exposes the engine's host surface (``process_batch``,
snapshots, purge, introspection) so ``DeviceQueryRuntime`` holds it
exactly like an unsharded engine.

No reference analog: the reference scales group-by state with
ThreadLocal-keyed maps on one JVM (config/SiddhiAppContext.java:55-109).
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

import numpy as np

from siddhi_tpu.core.exceptions import (
    SiddhiAppCreationError,
    SiddhiAppRuntimeError,
)
from siddhi_tpu.core.ingest_stage import staged_put
from siddhi_tpu.parallel.mesh import route_to_shards

log = logging.getLogger("siddhi_tpu.shard")


class ShardedDeviceQueryEngine:
    """A running-kind DeviceQueryEngine with its group axis sharded."""

    def __init__(self, engine, mesh, axis_name: str = "p"):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if engine.kind != "running":
            raise SiddhiAppCreationError(
                f"mesh sharding of the device query engine covers the "
                f"running (per-group accumulator) kind; kind="
                f"'{engine.kind}' runs single-device")
        self.engine = engine
        self.mesh = mesh
        self.axis_name = axis_name
        self.n_shards = int(np.prod(mesh.devices.shape))
        if engine.n_groups % self.n_shards:
            # unreachable via @app:execution (the annotation parser
            # enforces partitions % devices == 0 at app creation);
            # guards direct-API construction
            raise SiddhiAppCreationError(
                f"{engine.n_groups} groups not divisible by "
                f"{self.n_shards} shards")
        self.per_shard = engine.n_groups // self.n_shards
        self.rows_per_shard = self.per_shard + 1  # +1 scratch row

        jnp = engine.jnp
        a = axis_name
        raw = engine.make_step(jit=False)
        host = engine.init_state_host()
        self.state_specs = {
            k: P(a, *([None] * (v.ndim - 1))) for k, v in host.items()
        }
        specs = self.state_specs
        col_keys = list(engine.host_lane_cols({}, 0))

        def sharded_step(state, cols, ts, grp, valid):
            wgrp = jnp.zeros_like(grp)  # running kind ignores wgrp
            new_state, ov, out, n_local = raw(state, cols, ts, grp, wgrp,
                                              valid)
            # count gate for the async emit pipeline: one replicated
            # scalar the host can fetch without touching the columns
            total = jax.lax.psum(n_local, axis_name=a)
            return new_state, ov, out, total

        out_names = [nm for kind, _v, nm in engine.out_spec
                     if kind == "expr"]
        from siddhi_tpu.parallel.mesh import get_shard_map

        self._step = jax.jit(get_shard_map()(
            sharded_step,
            mesh=mesh,
            in_specs=(specs, {k: P(a) for k in col_keys}, P(a), P(a), P(a)),
            out_specs=(specs, P(a), {nm: P(a) for nm in out_names}, P()),
        ), donate_argnums=(0,))
        self._P = P
        self._NamedSharding = NamedSharding
        self._jax = jax

    # -- engine-surface proxy (host bookkeeping, snapshots, purge) ----------

    def __getattr__(self, name):
        return getattr(self.engine, name)

    # -- sharded state -------------------------------------------------------

    def _put(self, x, spec):
        # the shared staged_put owns the ingest.put fault site + the
        # bounded retry-with-backoff ladder (core/ingest_stage.py)
        return staged_put(
            x, self._NamedSharding(self.mesh, spec),
            faults=getattr(self, "faults", None),
            stats=getattr(self, "ingest_stats", None))

    def init_state(self):
        host = self.engine.init_state_host()
        n_rows = self.n_shards * self.rows_per_shard
        state = {}
        for k, v in host.items():
            arr = np.zeros((n_rows,) + v.shape[1:], dtype=v.dtype)
            arr[...] = v[0] if len(v) else 0  # per-row init is uniform
            state[k] = self._put(arr, self.state_specs[k])
        return state

    def put_state(self, host_state: Dict[str, np.ndarray]):
        """Numpy state (a snapshot) -> sharded device arrays.  The
        snapshot must carry THIS layout's row count — a snapshot taken
        under a different device count has a different shard-major
        bijection, and restoring it silently cross-wires groups."""
        n_rows = self.n_shards * self.rows_per_shard
        for k, v in host_state.items():
            v = np.asarray(v)
            if v.shape[0] != n_rows:
                raise SiddhiAppCreationError(
                    f"sharded device-query snapshot '{k}' has "
                    f"{v.shape[0]} rows; this {self.n_shards}-device "
                    f"layout needs {n_rows} — persist and restore must "
                    "use the same @app:execution devices count")
        return {
            k: self._put(np.asarray(v), self.state_specs[k])
            for k, v in host_state.items()
        }

    def _remap(self, gid: np.ndarray) -> np.ndarray:
        """Sequential gid -> shard-major row id, round-robin across
        shards WITH the per-shard scratch row accounted for."""
        owner = gid % self.n_shards
        local = gid // self.n_shards
        return owner * self.rows_per_shard + local

    # -- host entry point (mirrors DeviceQueryEngine.process_batch) ---------

    def process_batch(self, state, cols: Dict[str, np.ndarray],
                      ts: np.ndarray,
                      part_keys: Optional[np.ndarray] = None):
        """Synchronous wrapper over the deferred path — one count-gated,
        coalesced fetch per call (mirrors DeviceQueryEngine)."""
        eng = self.engine
        state, pending = self.process_batch_deferred(state, cols, ts,
                                                     part_keys)
        if pending is not None and pending.resolve() == 0:
            pending = None
        if pending is None:
            eng.last_group_keys = (
                [] if eng.group_exprs and not eng.partition_mode else None)
            return state, eng._empty_cols(), np.empty(0, dtype=np.int64)
        from siddhi_tpu.core.emit_queue import fetch_coalesced

        out_cols, out_ts, keys = pending.materialize(
            fetch_coalesced(pending.device_arrays()))
        eng.last_group_keys = keys
        return state, out_cols, out_ts

    def process_batch_deferred(self, state, cols: Dict[str, np.ndarray],
                               ts: np.ndarray,
                               part_keys: Optional[np.ndarray] = None):
        """Async-emit entry point: the psum'd match count is the only
        scalar fetched here; match columns stay sharded on device until
        the pending-emit queue drains them (core/emit_queue.py)."""
        from siddhi_tpu.ops.device_query import (
            MAX_DEVICE_BATCH,
            DeferredDeviceEmit,
        )

        eng = self.engine
        ts = np.asarray(ts, dtype=np.int64)
        n = len(ts)
        if n == 0:
            return state, None
        pk_all = np.asarray(part_keys) if part_keys is not None else None
        pending = DeferredDeviceEmit(eng)
        # same chunk bound as the unsharded engine: the running step
        # builds [B, B] same-group masks per shard
        for i in range(0, n, MAX_DEVICE_BATCH):
            sl = slice(i, i + MAX_DEVICE_BATCH)
            state = self._deferred_chunk(
                state, {k: np.asarray(v)[sl] for k, v in cols.items()},
                ts[sl], pk_all[sl] if pk_all is not None else None, pending)
        return state, (pending if pending.chunks else None)

    def _deferred_chunk(self, state, cols, ts, pk, pending):
        eng = self.engine
        n = len(ts)
        if eng.base_ts is None:
            eng.base_ts = int(ts[0]) - 1
        rel64 = ts - eng.base_ts
        if int(rel64.max()) >= eng._REL_LIMIT:
            # the engine's re-anchor: running kind has no timestamp
            # state, but the representability guard (one batch spanning
            # the whole int32 range) must still apply
            state, rel64 = eng._re_anchor(state, rel64)
        rel = rel64.astype(np.int32)
        now = int(ts.max())
        if eng.partition_mode:
            if pk is None:
                raise SiddhiAppRuntimeError(
                    "partitioned device query needs per-row partition keys")
            # wgroup interning runs unconditionally: _wgrp_last drives
            # the idle-key purge even when composed groups carry state
            wgrp = eng._intern_wgroups(pk, now)
            grp = (eng._intern_groups(cols, ts, n, pk=pk, now=now)
                   if eng.group_exprs else wgrp)
        else:
            grp = eng._intern_groups(cols, ts, n)
        lanes = eng.host_lane_cols(cols, n)
        local, rcols, rts, valid, pos = route_to_shards(
            self.n_shards, self.per_shard, self._route_part(grp),
            lanes, rel)
        P, a = self._P, self.axis_name
        args = (
            {k: self._put(v, P(a)) for k, v in rcols.items()},
            self._put(rts.astype(np.int32), P(a)),
            self._put(local, P(a)),
            self._put(valid, P(a)),
        )
        fi = getattr(eng, "faults", None)
        if fi is not None:
            fi.check("step.shard")
        state, ov, out, total = self._step(state, *args)
        # count gate deferred: the psum'd scalar stays on device until
        # DeferredDeviceEmit.resolve() (driven by the ingest stage);
        # group ids are kept host-side so resolve can capture key values
        # before any gid could be recycled (purges flush the stage first)
        pending.chunks.append({
            "kind": "device", "ov": ov, "out": dict(out),
            "names": list(out), "n": n, "pos": pos, "count": total,
            "gids": (grp.copy() if eng.group_exprs else None),
            "ts": ts, "cols": {k: np.asarray(v) for k, v in cols.items()},
        })
        return state

    def _route_part(self, gid: np.ndarray) -> np.ndarray:
        """Global gid -> the 'global partition id' route_to_shards
        expects (owner * parts_per_shard + local), with parts_per_shard
        = per_shard usable rows (scratch handled by route_to_shards
        itself)."""
        owner = gid % self.n_shards
        local = gid // self.n_shards
        return owner * self.per_shard + local

    def process(self, state, cols, ts, part_keys=None):
        state, out_cols, out_ts = self.process_batch(state, cols, ts,
                                                     part_keys)
        names = self.engine.output_names
        rows = [
            {nm: out_cols[nm][i] for nm in names}
            for i in range(len(out_ts))
        ]
        return state, rows

    def purge_idle_keys(self, state, now: int, idle_ms):
        """Partition-mode purge: the engine's own purge with dead
        logical group ids remapped to this layout's shard-major rows."""
        return self.engine.purge_idle_keys(state, now, idle_ms,
                                           remap=self._remap)
