"""Device-query engine sharded over a mesh.

``ShardedDeviceQueryEngine`` wraps a stateful
:class:`siddhi_tpu.ops.device_query.DeviceQueryEngine` of any kind:

- ``running`` — per-group accumulator rows ([G, A] sum/cnt/min/...)
  laid out shard-major along the group axis with one scratch row per
  shard; events route host-side to their owning shard.
- ``tumbling`` (lengthBatch/timeBatch) — the same group-axis layout for
  the pane accumulators; pane open/close bookkeeping (``_pane_end``,
  lengthBatch fill counts) stays host-side on the base engine and is
  kept consistent by psum-ing the per-shard passing counts at every
  accumulate step, so both paths place boundaries identically.  Pane
  flushes run a shard-local flush step and ride the count-gated async
  emit queue as "flush" chunks — a zero-match pane transfers nothing.
- ``sliding`` (length/time) — the GLOBAL ring buffer cannot shard by
  key, so the window state is replicated and the batch axis is sharded
  instead: every shard advances the ring identically (cheap, O(B + W))
  while computing the O(B·W) window gather/reduction only for its
  contiguous block of output rows.
- ``keyed_sliding`` (partitioned length/time) — per-key [W] ring rows
  shard along the window-group (partition-key) axis, same shard-major
  bijection as the group axis.  minForever/maxForever accumulate per
  composed (key, group) id, which does not co-locate with the key
  axis, so that combination is rejected (the planner falls back to a
  single device and reports it).

Group/window-group ids intern host-side exactly as in the unsharded
engine; a round-robin bijection (``gid -> (gid % n_shards) *
rows_per_shard + gid // n_shards``) spreads sequentially-allocated ids
across shards so early ids don't pile onto shard 0.  Events route
host-side to their owning shard (:func:`route_to_shards`) — same-group
rows keep their relative order inside one shard bucket, so the step's
within-batch same-group masks are unaffected.

The wrapper exposes the engine's host surface (``process_batch``,
snapshots, purge, introspection) so ``DeviceQueryRuntime`` holds it
exactly like an unsharded engine, and every emission path is
bit-identical to the single-device engine's.

No reference analog: the reference scales group-by state with
ThreadLocal-keyed maps on one JVM (config/SiddhiAppContext.java:55-109).
"""

from __future__ import annotations

import logging
import math
from typing import Dict, Optional

import numpy as np

from siddhi_tpu.core.exceptions import (
    SiddhiAppCreationError,
    SiddhiAppRuntimeError,
)
from siddhi_tpu.core.ingest_stage import staged_put
from siddhi_tpu.parallel.mesh import _pow2, route_to_shards

log = logging.getLogger("siddhi_tpu.shard")

#: kinds the wrapper accepts ('filter' is stateless — there is nothing
#: to shard, and a single device already saturates on H2D transfer)
SHARDED_KINDS = ("running", "tumbling", "sliding", "keyed_sliding")


class ShardedDeviceQueryEngine:
    """A stateful DeviceQueryEngine with its windowed state sharded
    across the mesh (group axis, key axis, or batch axis — see the
    module docstring for the per-kind layout)."""

    #: cycle-tracer span label: sharded dispatches trace as 'shard' so
    #: mesh overlap is distinguishable from single-device cycles
    engine_kind = "shard"

    def __init__(self, engine, mesh, axis_name: str = "p"):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if engine.kind not in SHARDED_KINDS:
            raise SiddhiAppCreationError(
                f"mesh sharding of the device query engine covers the "
                f"{'/'.join(SHARDED_KINDS)} kinds; kind="
                f"'{engine.kind}' is stateless and runs single-device")
        host = engine.init_state_host()
        if engine.kind == "keyed_sliding" and (
                "acc_minf" in host or "acc_maxf" in host):
            raise SiddhiAppCreationError(
                "sharded keyed_sliding: minForever/maxForever accumulate "
                "per composed (key, group) id, which does not co-locate "
                "with the partition-key shard axis; runs single-device")
        self.engine = engine
        self.mesh = mesh
        self.axis_name = axis_name
        self.n_shards = int(np.prod(mesh.devices.shape))
        # the sharded axis: window groups for keyed_sliding, groups for
        # running/tumbling; sliding replicates its global ring and
        # shards the batch axis instead (per_shard stays 0)
        if engine.kind == "keyed_sliding":
            axis_len, axis_what = engine.n_wgroups, "window groups"
        else:
            axis_len, axis_what = engine.n_groups, "groups"
        if engine.kind != "sliding" and axis_len % self.n_shards:
            # unreachable via @app:execution (the annotation parser
            # enforces partitions % devices == 0 at app creation);
            # guards direct-API construction
            raise SiddhiAppCreationError(
                f"{axis_len} {axis_what} not divisible by "
                f"{self.n_shards} shards")
        if engine.kind == "sliding":
            self.per_shard = 0
            self.rows_per_shard = 0
        else:
            self.per_shard = axis_len // self.n_shards
            self.rows_per_shard = self.per_shard + 1  # +1 scratch row
        # hot-pane flush batching: empty tumbling panes skip the
        # shard-mapped flush dispatch entirely (a zero-fill pane's
        # accumulators are already at their reset values, so the step
        # would be a state no-op emitting nothing) — a batch that jumps
        # K pane boundaries costs ONE dispatch, not K
        self.flush_skips = 0

        jnp = engine.jnp
        a = axis_name
        if engine.kind == "sliding":
            # replicated ring: every shard holds (and identically
            # advances) the full window state
            self.state_specs = {k: P() for k in host}
        else:
            self.state_specs = {
                k: P(a, *([None] * (v.ndim - 1))) for k, v in host.items()
            }
        specs = self.state_specs
        col_keys = list(engine.host_lane_cols({}, 0))
        out_names = [nm for kind, _v, nm in engine.out_spec
                     if kind == "expr"]
        from siddhi_tpu.parallel.mesh import get_shard_map

        shard_map = get_shard_map()
        self._P = P
        self._NamedSharding = NamedSharding
        self._jax = jax

        if engine.kind == "running":
            raw = engine.make_step(jit=False)

            def sharded_step(state, cols, ts, grp, valid):
                wgrp = jnp.zeros_like(grp)  # running kind ignores wgrp
                new_state, ov, out, n_local = raw(state, cols, ts, grp,
                                                  wgrp, valid)
                # count gate for the async emit pipeline: one replicated
                # scalar the host can fetch without touching the columns
                total = jax.lax.psum(n_local, axis_name=a)
                return new_state, ov, out, total

            self._step = jax.jit(shard_map(
                sharded_step,
                mesh=mesh,
                in_specs=(specs, {k: P(a) for k in col_keys},
                          P(a), P(a), P(a)),
                out_specs=(specs, P(a), {nm: P(a) for nm in out_names},
                           P()),
            ), donate_argnums=(0,))
        elif engine.kind == "keyed_sliding":
            raw = engine.make_step(jit=False)

            def sharded_kstep(state, cols, ts, grp, wgrp, valid):
                # wgrp is the routed LOCAL ring-row index; grp keeps the
                # global composed id (the step only ever compares grp
                # values for equality, never indexes state with them)
                new_state, ov, out, n_local = raw(state, cols, ts, grp,
                                                  wgrp, valid)
                total = jax.lax.psum(n_local, axis_name=a)
                return new_state, ov, out, total

            self._step = jax.jit(shard_map(
                sharded_kstep,
                mesh=mesh,
                in_specs=(specs, {k: P(a) for k in col_keys},
                          P(a), P(a), P(a), P(a)),
                out_specs=(specs, P(a), {nm: P(a) for nm in out_names},
                           P()),
            ), donate_argnums=(0,))
        elif engine.kind == "sliding":

            def sharded_sliding(state, cols, ts, grp, valid):
                # replicated inputs; each shard owns the contiguous
                # output-row block [r0, r0 + b_loc) of the O(B·W)
                # window reduction while the ring advance (replicated,
                # O(B + W)) is recomputed identically everywhere
                B = ts.shape[0]
                b_loc = B // self.n_shards  # host pads to a multiple
                env = engine._base_env(cols, ts, B)
                fmask = engine._filter_mask(env, valid)
                r0 = jax.lax.axis_index(a) * b_loc
                new_state, ov, out = engine._sliding_step(
                    state, env, fmask, ts, grp, B, r0=r0, nb=b_loc)
                n_local = jnp.sum((ov.astype(bool)).astype(jnp.int32))
                total = jax.lax.psum(n_local, axis_name=a)
                return new_state, ov, out, total

            self._step = jax.jit(shard_map(
                sharded_sliding,
                mesh=mesh,
                in_specs=(specs, {k: P() for k in col_keys},
                          P(), P(), P()),
                out_specs=(specs, P(a), {nm: P(a) for nm in out_names},
                           P()),
            ), donate_argnums=(0,))
        else:  # tumbling
            acc_raw = engine.make_acc_step(jit=False)

            def sharded_acc(state, cols, ts, grp, gkv, valid):
                new_state, n_pass = acc_raw(state, cols, ts, grp, gkv,
                                            valid)
                # the all-reduce that keeps host pane bookkeeping
                # (lengthBatch fill counts) consistent: every shard
                # contributes its local passing count
                total = jax.lax.psum(n_pass, axis_name=a)
                return new_state, total

            self._acc = jax.jit(shard_map(
                sharded_acc,
                mesh=mesh,
                in_specs=(specs, {k: P(a) for k in col_keys},
                          P(a), P(a), P(a), P(a)),
                out_specs=(specs, P()),
            ), donate_argnums=(0,))
            flush_raw = engine.make_flush_step(
                jit=False, n_rows=self.rows_per_shard)

            def sharded_flush(state):
                new_state, ov, out, n_match = flush_raw(state)
                total = jax.lax.psum(n_match, axis_name=a)
                return new_state, ov, out, total

            self._flush = jax.jit(shard_map(
                sharded_flush,
                mesh=mesh,
                in_specs=(specs,),
                out_specs=(specs, P(a), {nm: P(a) for nm in out_names},
                           P()),
            ), donate_argnums=(0,))

    # -- engine-surface proxy (host bookkeeping, snapshots, purge) ----------

    def __getattr__(self, name):
        return getattr(self.engine, name)

    # -- sharded state -------------------------------------------------------

    def _put(self, x, spec):
        # the shared staged_put owns the ingest.put fault site + the
        # bounded retry-with-backoff ladder (core/ingest_stage.py)
        return staged_put(
            x, self._NamedSharding(self.mesh, spec),
            faults=getattr(self, "faults", None),
            stats=getattr(self, "ingest_stats", None))

    def init_state(self):
        host = self.engine.init_state_host()
        if self.engine.kind == "sliding":
            return {k: self._put(np.asarray(v), self.state_specs[k])
                    for k, v in host.items()}
        n_rows = self.n_shards * self.rows_per_shard
        state = {}
        for k, v in host.items():
            arr = np.zeros((n_rows,) + v.shape[1:], dtype=v.dtype)
            arr[...] = v[0] if len(v) else 0  # per-row init is uniform
            state[k] = self._put(arr, self.state_specs[k])
        return state

    def put_state(self, host_state: Dict[str, np.ndarray]):
        """Numpy state (a snapshot) -> sharded device arrays.  For
        axis-sharded kinds the snapshot must carry THIS layout's row
        count — a snapshot taken under a different device count has a
        different shard-major bijection, and restoring it silently
        cross-wires groups.  The sliding kind's replicated state keeps
        the single-device layout and restores under any device count."""
        if self.engine.kind == "sliding":
            expect = {k: v.shape
                      for k, v in self.engine.init_state_host().items()}
            for k, v in host_state.items():
                shape = np.asarray(v).shape
                if k in expect and shape != expect[k]:
                    raise SiddhiAppCreationError(
                        f"sliding device-query snapshot '{k}' has shape "
                        f"{shape}; this query needs {expect[k]}")
        else:
            n_rows = self.n_shards * self.rows_per_shard
            for k, v in host_state.items():
                v = np.asarray(v)
                if v.shape[0] != n_rows:
                    raise SiddhiAppCreationError(
                        f"sharded device-query snapshot '{k}' has "
                        f"{v.shape[0]} rows; this {self.n_shards}-device "
                        f"layout needs {n_rows} — persist and restore "
                        "must use the same @app:execution devices count")
        return {
            k: self._put(np.asarray(v), self.state_specs[k])
            for k, v in host_state.items()
        }

    def _remap(self, gid: np.ndarray) -> np.ndarray:
        """Sequential id -> shard-major row id, round-robin across
        shards WITH the per-shard scratch row accounted for."""
        owner = gid % self.n_shards
        local = gid // self.n_shards
        return owner * self.rows_per_shard + local

    # -- host entry point (mirrors DeviceQueryEngine.process_batch) ---------

    def process_batch(self, state, cols: Dict[str, np.ndarray],
                      ts: np.ndarray,
                      part_keys: Optional[np.ndarray] = None):
        """Synchronous wrapper over the deferred path — one count-gated,
        coalesced fetch per call (mirrors DeviceQueryEngine)."""
        eng = self.engine
        state, pending = self.process_batch_deferred(state, cols, ts,
                                                     part_keys)
        if pending is not None and pending.resolve() == 0:
            pending = None
        if pending is None:
            eng.last_group_keys = (
                [] if eng.group_exprs and not eng.partition_mode else None)
            return state, eng._empty_cols(), np.empty(0, dtype=np.int64)
        from siddhi_tpu.core.emit_queue import fetch_coalesced

        out_cols, out_ts, keys = pending.materialize(
            fetch_coalesced(pending.device_arrays()))
        eng.last_group_keys = keys
        return state, out_cols, out_ts

    def process_batch_deferred(self, state, cols: Dict[str, np.ndarray],
                               ts: np.ndarray,
                               part_keys: Optional[np.ndarray] = None):
        """Async-emit entry point: the psum'd match count is the only
        scalar fetched here; match columns stay sharded on device until
        the pending-emit queue drains them (core/emit_queue.py)."""
        from siddhi_tpu.ops.device_query import (
            MAX_DEVICE_BATCH,
            DeferredDeviceEmit,
        )

        eng = self.engine
        ts = np.asarray(ts, dtype=np.int64)
        n = len(ts)
        if n == 0:
            return state, None
        pk_all = np.asarray(part_keys) if part_keys is not None else None
        pending = DeferredDeviceEmit(eng)
        if eng.kind == "tumbling":
            # no [B, B] batch masks: pane sweeps segment the batch
            # themselves (same contract as the unsharded engine)
            state = self._deferred_chunk(state, cols, ts, pk_all, pending)
            return state, (pending if pending.chunks else None)
        # same chunk bound as the unsharded engine: the per-event steps
        # build [B, B] same-group masks per shard
        for i in range(0, n, MAX_DEVICE_BATCH):
            sl = slice(i, i + MAX_DEVICE_BATCH)
            state = self._deferred_chunk(
                state, {k: np.asarray(v)[sl] for k, v in cols.items()},
                ts[sl], pk_all[sl] if pk_all is not None else None, pending)
        return state, (pending if pending.chunks else None)

    def _deferred_chunk(self, state, cols, ts, pk, pending):
        eng = self.engine
        n = len(ts)
        if eng.base_ts is None:
            eng.base_ts = int(ts[0]) - 1
        rel64 = ts - eng.base_ts
        if int(rel64.max()) >= eng._REL_LIMIT:
            # the engine's re-anchor: shifts live window entries / the
            # open pane boundary with the new anchor (replicated or
            # row-sharded arrays shift elementwise either way)
            state, rel64 = eng._re_anchor(state, rel64)
        rel = rel64.astype(np.int32)
        now = int(ts.max())
        if eng.partition_mode:
            if pk is None:
                raise SiddhiAppRuntimeError(
                    "partitioned device query needs per-row partition keys")
            # wgroup interning runs unconditionally: _wgrp_last drives
            # the idle-key purge even when composed groups carry state
            wgrp = eng._intern_wgroups(pk, now)
            grp = (eng._intern_groups(cols, ts, n, pk=pk, now=now)
                   if eng.group_exprs else wgrp)
        else:
            wgrp = None
            grp = eng._intern_groups(cols, ts, n)
        fi = getattr(eng, "faults", None)
        if eng.kind == "tumbling":
            return self._tumbling_chunk(state, cols, rel, grp, n, pending)
        if eng.kind == "sliding":
            return self._sliding_chunk(state, cols, rel, grp, n, ts,
                                       pending, fi)
        lanes = eng.host_lane_cols(cols, n)
        P, a = self._P, self.axis_name
        if eng.kind == "keyed_sliding":
            # route by the OWNING ring row (the partition key); the
            # composed group id rides along as a pseudo-lane column
            lanes["__grp"] = grp.astype(np.int32)
            local, rcols, rts, valid, pos = route_to_shards(
                self.n_shards, self.per_shard, self._route_part(wgrp),
                lanes, rel)
            rgrp = rcols.pop("__grp")
            args = (
                {k: self._put(v, P(a)) for k, v in rcols.items()},
                self._put(rts.astype(np.int32), P(a)),
                self._put(rgrp, P(a)),
                self._put(local, P(a)),
                self._put(valid, P(a)),
            )
        else:
            local, rcols, rts, valid, pos = route_to_shards(
                self.n_shards, self.per_shard, self._route_part(grp),
                lanes, rel)
            args = (
                {k: self._put(v, P(a)) for k, v in rcols.items()},
                self._put(rts.astype(np.int32), P(a)),
                self._put(local, P(a)),
                self._put(valid, P(a)),
            )
        if fi is not None:
            fi.check("step.shard")
        state, ov, out, total = self._step(state, *args)
        # count gate deferred: the psum'd scalar stays on device until
        # DeferredDeviceEmit.resolve() (driven by the ingest stage);
        # group ids are kept host-side so resolve can capture key values
        # before any gid could be recycled (purges flush the stage first)
        pending.chunks.append({
            "kind": "device", "ov": ov, "out": dict(out),
            "names": list(out), "n": n, "pos": pos, "count": total,
            "gids": (grp.copy() if eng.group_exprs else None),
            "ts": ts, "cols": {k: np.asarray(v) for k, v in cols.items()},
        })
        return state

    def _sliding_chunk(self, state, cols, rel, grp, n, ts, pending, fi):
        """Batch-axis sharded sliding step: pad the batch (pow-2, then
        to a shard-count multiple) and replicate it; the step returns
        ov/out as the concatenation of per-shard row blocks — the
        original row order, so no slot map is needed."""
        eng = self.engine
        B = _pow2(n)
        B *= self.n_shards // math.gcd(B, self.n_shards)
        valid = np.zeros(B, dtype=bool)
        valid[:n] = True
        lanes = eng.host_lane_cols(cols, n)
        c = {}
        for k, v in lanes.items():
            col = np.zeros(B, dtype=v.dtype)
            col[:n] = v
            c[k] = col
        t = np.zeros(B, dtype=np.int32)
        t[:n] = rel[:n]
        g = np.zeros(B, dtype=np.int32)
        g[:n] = grp[:n]
        P = self._P
        args = (
            {k: self._put(v, P()) for k, v in c.items()},
            self._put(t, P()),
            self._put(g, P()),
            self._put(valid, P()),
        )
        if fi is not None:
            fi.check("step.shard")
        state, ov, out, total = self._step(state, *args)
        pending.chunks.append({
            "kind": "device", "ov": ov, "out": dict(out),
            "names": list(out), "n": n, "count": total,
            "gids": (grp[:n].copy() if eng.group_exprs else None),
            "ts": ts, "cols": {k: np.asarray(v) for k, v in cols.items()},
        })
        return state

    # -- tumbling panes ------------------------------------------------------

    def _tumbling_chunk(self, state, cols, rel, grp, n, pending):
        """Drive the base engine's pane sweep (host ``_pane_end`` /
        fill-count bookkeeping, shared code) with the sharded
        accumulate/flush steps; closed panes become deferred "flush"
        chunks on the async emit queue."""
        eng = self.engine

        def flush_pane(st, when):
            return self._flush_pane_chunk(st, when, pending)

        return eng._pane_sweep(state, cols, rel, grp, n,
                               self._acc_segment, flush_pane)

    def _acc_segment(self, state, cols, rel, grp, idx):
        """Sharded analog of the engine's ``_acc_segment``: route the
        segment's events (and their numeric group-key values, as
        pseudo-lane columns) to the owning shards, run the shard-local
        accumulate step, and return the PSUM'd passing count — the
        all-reduce that keeps lengthBatch pane fills consistent."""
        eng = self.engine
        n = len(idx)
        lanes = eng.host_lane_cols(
            {k: np.asarray(v)[idx] for k, v in cols.items()}, n)
        K = max(len(eng._numeric_group_keys), 1)
        gkv = eng._gk_vals(grp[idx], n)  # [n, K] float32
        for ki in range(K):
            lanes[f"__gk{ki}"] = gkv[:, ki]
        local, rcols, rts, valid, pos = route_to_shards(
            self.n_shards, self.per_shard, self._route_part(grp[idx]),
            lanes, rel[idx])
        gkv_r = np.stack([rcols.pop(f"__gk{ki}") for ki in range(K)],
                         axis=1)
        P, a = self._P, self.axis_name
        args = (
            {k: self._put(v, P(a)) for k, v in rcols.items()},
            self._put(rts.astype(np.int32), P(a)),
            self._put(local, P(a)),
            self._put(np.ascontiguousarray(gkv_r, dtype=np.float32),
                      P(a)),
            self._put(valid, P(a)),
        )
        fi = getattr(eng, "faults", None)
        if fi is not None:
            fi.check("step.shard")
        state, total = self._acc(state, *args)
        # blocking count fetch — the same synchronization point the
        # single-device _acc_segment has (pane placement needs it);
        # explicit device_get so transfer_guard('disallow') stays happy
        import jax

        return state, int(jax.device_get(total))

    def _flush_pane_chunk(self, state, when, pending):
        """Close the open pane: shard-local flush step, result deferred
        as a "flush" chunk (count-gated — an all-empty pane's columns
        are never transferred)."""
        eng = self.engine
        if eng.window_name == "timeBatch" and not eng._pane_fill:
            # no passing event touched this pane: every accumulator is
            # already at its reset value and the flush would emit zero
            # rows — skip the device dispatch, keep host bookkeeping.
            # timeBatch only: its fill count is final when the pane
            # closes, while lengthBatch increments AFTER the closing
            # flush (and only ever closes full panes anyway)
            self.flush_skips += 1
            return state
        fi = getattr(eng, "faults", None)
        if fi is not None:
            fi.check("step.shard")
        state, ov, out, total = self._flush(state)
        pending.chunks.append({
            "kind": "flush", "ov": ov, "out": dict(out),
            "names": list(out), "count": total, "stamp": int(when),
            "rows_per_shard": self.rows_per_shard,
            "n_shards": self.n_shards,
        })
        return state

    def flush_due(self, state, now: int):
        """Timer-driven pane flush: close every pane whose boundary <=
        now with the shard-local flush step (the base engine's loop
        would trace the full-G flush over shard-major rows).  Resolves
        synchronously — the runtime's ``fire`` emits the result
        immediately."""
        eng = self.engine
        if eng.kind != "tumbling":
            return self.engine.flush_due(state, now)
        from siddhi_tpu.ops.device_query import DeferredDeviceEmit

        pending = DeferredDeviceEmit(eng)
        while True:
            w = eng.pane_wakeup()
            if w is None or w > now:
                break
            state = self._flush_pane_chunk(state, w, pending)
            eng._advance_pane()
        if not pending.chunks or pending.resolve() == 0:
            eng.last_group_keys = [] if eng.group_exprs else None
            return state, eng._empty_cols(), np.empty(0, dtype=np.int64)
        from siddhi_tpu.core.emit_queue import fetch_coalesced

        out_cols, out_ts, keys = pending.materialize(
            fetch_coalesced(pending.device_arrays()))
        eng.last_group_keys = keys
        return state, out_cols, out_ts

    def _route_part(self, gid: np.ndarray) -> np.ndarray:
        """Global id -> the 'global partition id' route_to_shards
        expects (owner * parts_per_shard + local), with parts_per_shard
        = per_shard usable rows (scratch handled by route_to_shards
        itself)."""
        owner = gid % self.n_shards
        local = gid // self.n_shards
        return owner * self.per_shard + local

    def process(self, state, cols, ts, part_keys=None):
        state, out_cols, out_ts = self.process_batch(state, cols, ts,
                                                     part_keys)
        names = self.engine.output_names
        rows = [
            {nm: out_cols[nm][i] for nm in names}
            for i in range(len(out_ts))
        ]
        return state, rows

    def purge_idle_keys(self, state, now: int, idle_ms):
        """Partition-mode purge: the engine's own purge with dead
        logical ids remapped to this layout's shard-major rows (group
        rows and keyed_sliding ring rows shard independently, so both
        remaps apply)."""
        return self.engine.purge_idle_keys(state, now, idle_ms,
                                           remap=self._remap,
                                           wremap=self._remap)
