"""Black-box flight recorder: the last N cycle traces, dumped on death.

The recorder owns the span ring the tracer appends into.  Spans are
fixed-size tuples ``(cycle, stage, engine, t_start, t_end, n_events)``
held in a ``collections.deque(maxlen=...)`` — appends are GIL-atomic,
so the emit-drain, ingest and checkpoint-writer threads all record
without a lock, and the ring self-evicts to the newest N cycles' worth
of spans.

On a terminal event (poison quarantine, @OnError isolation, crash
restore, fault-injector kill) ``dump(reason)`` freezes the ring into a
JSON payload: kept in memory as ``last_dump`` (served by
``GET /siddhi-trace/<app>``) and written best-effort to the dump
directory so a post-mortem survives the process.  ``chrome_trace()``
renders the same spans as Chrome ``chrome://tracing`` complete events.
"""

from __future__ import annotations

import collections
import itertools
import json
import logging
import os
import re
import tempfile
import time
from typing import Dict, List, Optional, Tuple

log = logging.getLogger("siddhi_tpu.observability")

#: span tuple layout — index names for readers of the raw ring
CYCLE, STAGE, ENGINE, T_START, T_END, N_EVENTS = range(6)

Span = Tuple[int, str, str, float, float, int]


def default_dump_dir() -> str:
    """``$SIDDHI_TPU_TRACE_DIR`` or ``<tmp>/siddhi_tpu_traces``."""
    return os.environ.get("SIDDHI_TPU_TRACE_DIR") or os.path.join(
        tempfile.gettempdir(), "siddhi_tpu_traces")


class FlightRecorder:
    """Span ring + dump machinery for one app runtime."""

    #: ring capacity per kept cycle: ingest + step + emit leaves head
    #: room for persist spans interleaving with batch cycles
    SPANS_PER_CYCLE = 4

    #: file-write cap per recorder — a chaos run triggering hundreds of
    #: isolation dumps must not litter the dump dir unboundedly (the
    #: in-memory ``last_dump`` keeps updating past the cap)
    MAX_DUMP_FILES = 32

    def __init__(self, app_name: str, cycles: int = 64,
                 dump_dir: Optional[str] = None):
        self.app_name = app_name
        self.cycles = max(1, int(cycles))
        self.ring: collections.deque = collections.deque(
            maxlen=self.cycles * self.SPANS_PER_CYCLE)
        self.dump_dir = dump_dir if dump_dir is not None else default_dump_dir()
        self.last_dump: Optional[dict] = None
        self.dumps = 0
        self.dump_files_written = 0

    # -- recording -----------------------------------------------------------

    def record(self, span: Span) -> None:
        self.ring.append(span)

    def spans(self) -> List[Span]:
        return list(self.ring)

    def cycle_groups(self) -> "collections.OrderedDict":
        """cycle id -> [span, ...] in ring (append) order, cycles in
        first-seen order — ring order IS chronological per cycle."""
        groups: "collections.OrderedDict" = collections.OrderedDict()
        for span in list(self.ring):
            groups.setdefault(span[CYCLE], []).append(span)
        return groups

    # -- dumping -------------------------------------------------------------

    @staticmethod
    def _span_dict(span: Span) -> dict:
        return {
            "cycle": span[CYCLE],
            "stage": span[STAGE],
            "engine": span[ENGINE],
            "t_start": span[T_START],
            "t_end": span[T_END],
            "n_events": span[N_EVENTS],
        }

    def payload(self, reason: str) -> dict:
        return {
            "app": self.app_name,
            "reason": reason,
            "unix_time": time.time(),
            "spans": [self._span_dict(s) for s in self.spans()],
        }

    def dump(self, reason: str) -> dict:
        """Freeze the ring: keep it in memory, write it best-effort.

        The dump path must never add a failure mode to the fault paths
        that call it — an unwritable dump dir logs and moves on."""
        payload = self.payload(reason)
        self.last_dump = payload
        self.dumps += 1
        if self.dump_files_written >= self.MAX_DUMP_FILES:
            return payload
        slug = re.sub(r"[^A-Za-z0-9_.-]+", "-", reason)[:64]
        fname = f"{self.app_name}-{self.dumps:04d}-{slug}.json"
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            path = os.path.join(self.dump_dir, fname)
            with open(path, "w", encoding="utf-8") as f:
                json.dump(payload, f)
            self.dump_files_written += 1
            log.warning("flight recorder: app '%s' dumped %d span(s) to "
                        "%s (reason: %s)", self.app_name,
                        len(payload["spans"]), path, reason)
        except OSError as e:
            log.error("flight recorder: app '%s' could not write dump "
                      "(%s); trace kept in memory only", self.app_name, e)
        return payload

    # -- chrome://tracing export ---------------------------------------------

    def chrome_trace(self) -> dict:
        """Complete ("X") events, one per span; ts/dur in microseconds.

        Stages map to tids so chrome renders the pipeline as stacked
        tracks; the cycle id and engine kind ride in ``args`` for the
        flow inspector."""
        tids: Dict[str, int] = {}
        events = []
        for span in self.spans():
            stage = span[STAGE]
            tid = tids.setdefault(stage, len(tids) + 1)
            events.append({
                "name": f"{stage} c{span[CYCLE]}",
                "cat": span[ENGINE],
                "ph": "X",
                "ts": span[T_START] * 1e6,
                "dur": max(0.0, (span[T_END] - span[T_START]) * 1e6),
                "pid": 1,
                "tid": tid,
                "args": {"cycle": span[CYCLE], "engine": span[ENGINE],
                         "n_events": span[N_EVENTS]},
            })
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"app": self.app_name},
            "metadata": {"thread_names": {v: k for k, v in tids.items()}},
        }
