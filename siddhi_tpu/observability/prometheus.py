"""Prometheus text-exposition rendering of the statistics feed.

``GET /metrics`` renders every deployed app's StatisticsManager
counters/gauges plus the histogram families (per-query latency, per
pipeline stage) in text exposition format 0.0.4.  The dotted reference
metric names

    io.siddhi.SiddhiApps.<app>.Siddhi.<kind>.<name>.<metric>

map to ``siddhi_<kind>_<metric>{app="...",name="..."}`` — the app and
element move into labels so one family aggregates across apps and
queries, which is what makes the exposition scrapable (a family's
``# TYPE`` header must appear exactly once, with all its samples
grouped under it).  String-valued feed entries (engine placement,
fallback reasons) become ``*_info`` gauges with the text in a
``value`` label, the textfile-collector idiom for non-numeric facts.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional, Tuple

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_CAMEL = re.compile(r"([a-z0-9])([A-Z])")
_BAD_METRIC = re.compile(r"[^a-zA-Z0-9_]")


def _snake(name: str) -> str:
    return _BAD_METRIC.sub("_", _CAMEL.sub(r"\1_\2", name).lower())


def _escape(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _labels(pairs: Dict[str, str]) -> str:
    return ",".join(f'{k}="{_escape(v)}"' for k, v in pairs.items())


def _num(v) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return format(f, "g")


def _parse_key(app: str, key: str) -> Optional[Tuple[str, str, str]]:
    """Dotted feed key -> (kind, element name, metric); None for a key
    outside the reference convention (rendered under a catch-all)."""
    prefix = f"io.siddhi.SiddhiApps.{app}.Siddhi."
    if not key.startswith(prefix):
        return None
    parts = key[len(prefix):].split(".")
    if len(parts) < 2:
        return None
    return parts[0], ".".join(parts[1:-1]), parts[-1]


def render_prometheus(apps: Iterable[Tuple[str, Dict[str, object], list]]) -> str:
    """Render the exposition for ``apps`` — an iterable of
    ``(app_name, flat_stats_dict, histogram_entries)`` where each
    histogram entry is ``(family, labels_dict, LatencyHistogram)``.

    Scalar samples and histograms are grouped per family across apps
    so every ``# TYPE`` appears once."""
    gauges: Dict[str, List[Tuple[str, str]]] = {}
    hists: Dict[str, List[Tuple[str, object]]] = {}
    for app, stats, histogram_entries in apps:
        for key, value in sorted(stats.items()):
            parsed = _parse_key(app, key)
            if parsed is None:
                family = "siddhi_metric"
                labels = {"app": app, "key": key}
            else:
                kind, name, metric = parsed
                family = f"siddhi_{_snake(kind)}_{_snake(metric)}"
                labels = {"app": app, "name": name}
            if isinstance(value, str):
                labels["value"] = value
                gauges.setdefault(family + "_info", []).append(
                    (_labels(labels), "1"))
            else:
                gauges.setdefault(family, []).append(
                    (_labels(labels), _num(value)))
        for family, labels, hist in histogram_entries:
            hists.setdefault(family, []).append((_labels(labels), hist))

    lines: List[str] = []
    for family in sorted(gauges):
        lines.append(f"# TYPE {family} gauge")
        for labels, value in gauges[family]:
            lines.append(f"{family}{{{labels}}} {value}")
    for family in sorted(hists):
        lines.append(f"# TYPE {family} histogram")
        for labels, hist in hists[family]:
            bounds, counts, sum_ms, count = hist.snapshot()
            cum = 0
            for bound, c in zip(bounds, counts):
                cum += c
                lines.append(
                    f'{family}_bucket{{{labels},le="{format(bound, "g")}"}}'
                    f" {cum}")
            lines.append(f'{family}_bucket{{{labels},le="+Inf"}} {count}')
            lines.append(f"{family}_sum{{{labels}}} {_num(sum_ms)}")
            lines.append(f"{family}_count{{{labels}}} {count}")
    return "\n".join(lines) + "\n" if lines else "\n"


def app_histogram_entries(app: str, statistics_manager) -> list:
    """Histogram families of one app: per-query latency ladders from
    the LatencyTrackers plus per-stage span ladders from a registered
    tracer."""
    entries = []
    for tracker in list(statistics_manager.latency.values()):
        hist = getattr(tracker, "hist", None)
        if hist is not None and hist.count:
            entries.append(("siddhi_query_latency_ms",
                            {"app": app, "name": tracker.name}, hist))
    tracer = getattr(statistics_manager, "tracer", None)
    if tracer is not None:
        for stage, hist in tracer.histograms():
            entries.append(("siddhi_stage_duration_ms",
                            {"app": app, "stage": stage}, hist))
    return entries
