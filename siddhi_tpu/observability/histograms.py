"""Fixed-bucket latency histograms with p50/p95/p99 read-out.

One histogram is a flat list of counters over a fixed exponential
millisecond bucket ladder — recording is one ``bisect`` plus three
scalar updates, so the per-batch cost matches the existing tracker
style of ``util/statistics.py`` (host ints, no locks, no allocation on
the hot path).  Quantiles interpolate linearly inside the landing
bucket, the same estimate Prometheus' ``histogram_quantile`` computes
from the exposed ``_bucket`` series, so the REST feed and a scraping
dashboard agree on the tails.
"""

from __future__ import annotations

from bisect import bisect_left
from typing import List, Tuple


class LatencyHistogram:
    """Fixed exponential ms buckets; lock-light (GIL-sized races lose a
    count at worst, never corrupt the ladder)."""

    #: upper bounds in ms; everything past the last bound lands in the
    #: +Inf overflow bucket.  50 µs .. 5 s covers a host callback tick
    #: through a tunneled checkpoint write.
    BOUNDS_MS: Tuple[float, ...] = (
        0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
        100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0,
    )

    __slots__ = ("counts", "count", "sum_ms", "max_ms")

    def __init__(self) -> None:
        self.counts: List[int] = [0] * (len(self.BOUNDS_MS) + 1)
        self.count = 0
        self.sum_ms = 0.0
        self.max_ms = 0.0

    def record_ms(self, ms: float) -> None:
        self.counts[bisect_left(self.BOUNDS_MS, ms)] += 1
        self.count += 1
        self.sum_ms += ms
        if ms > self.max_ms:
            self.max_ms = ms

    def record_s(self, seconds: float) -> None:
        self.record_ms(seconds * 1000.0)

    def quantile_ms(self, q: float) -> float:
        """Estimate the q-quantile (0 < q <= 1) by linear interpolation
        inside the landing bucket; the overflow bucket reports the
        observed max (the only honest upper bound it has)."""
        if self.count == 0:
            return 0.0
        rank = q * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            prev = cum
            cum += c
            if cum >= rank:
                if i >= len(self.BOUNDS_MS):
                    return self.max_ms
                lo = self.BOUNDS_MS[i - 1] if i > 0 else 0.0
                hi = self.BOUNDS_MS[i]
                return lo + (hi - lo) * ((rank - prev) / c)
        return self.max_ms

    def p50_ms(self) -> float:
        return self.quantile_ms(0.50)

    def p95_ms(self) -> float:
        return self.quantile_ms(0.95)

    def p99_ms(self) -> float:
        return self.quantile_ms(0.99)

    def snapshot(self) -> Tuple[Tuple[float, ...], Tuple[int, ...], float, int]:
        """(bounds_ms, per-bucket counts incl. overflow, sum_ms, count)
        — the exact series a Prometheus histogram family exposes."""
        return self.BOUNDS_MS, tuple(self.counts), self.sum_ms, self.count

    def reset(self) -> None:
        self.counts = [0] * (len(self.BOUNDS_MS) + 1)
        self.count = 0
        self.sum_ms = 0.0
        self.max_ms = 0.0
