"""Cycle-correlated tracing and telemetry for the device batch pipeline.

The runtime overlaps one batch's life across four threads — H2D ingest
staging, the jitted step, the count-gated emit drain, and the async
checkpoint writer — and this package is the layer that makes that
overlap visible without touching the device hot path:

- ``trace``: a monotonic cycle id per device-engine batch, threaded
  through IngestStage/EmitQueue; each stage appends a fixed-size span
  record to a lock-light per-runtime ring (pure host bookkeeping,
  outside jit).
- ``recorder``: the black-box flight recorder over that ring — the last
  N complete cycle traces, dumped as JSON on poison quarantine, @OnError
  isolation, crash restore and fault-injector kills, exportable as
  Chrome ``chrome://tracing`` JSON.
- ``histograms``: fixed-bucket latency histograms (p50/p95/p99) shared
  by the per-stage span feed and ``util/statistics.py``'s per-query
  LatencyTracker.
- ``prometheus``: text-exposition rendering of every StatisticsManager
  counter/gauge/histogram for ``GET /metrics``.
"""

from .histograms import LatencyHistogram
from .prometheus import render_prometheus
from .recorder import FlightRecorder
from .trace import CycleToken, Tracer

__all__ = [
    "CycleToken",
    "FlightRecorder",
    "LatencyHistogram",
    "Tracer",
    "render_prometheus",
]
