"""Cycle-correlated span tracing for the device batch pipeline.

One :class:`Tracer` per app runtime hands out a monotonically
increasing cycle id per device-engine batch (``begin_cycle``).  The id
rides a :class:`CycleToken` through the existing async machinery:

    runtime ``process_stream_batch``          -> begin_cycle (t0)
    IngestStage.submit (put + step dispatched) -> tok.dispatched()  [ingest span]
    runtime ``_finish`` (count gate resolved)  -> tok.step_done(n)  [step span]
    EmitQueue.drain (batch materialized)       -> tok.emitted(t0)   [emit span]

plus free-running ``persist.capture`` / ``persist.write`` spans from
the checkpoint path (``record_span``), which draw ids from the same
counter so a capture and its async write stay ordered against the
batch cycles around them.

Everything here is host-side bookkeeping OUTSIDE jit: a span is a
six-tuple appended to the flight recorder's deque (GIL-atomic) plus a
histogram bucket increment — no device arrays are touched, fetched or
materialized, which is what keeps the ``jit-purity`` and
``host-sync-hazard`` analysis rules clean with zero allowlist entries.

Sampling (``@app:trace(sample='1/64')``) gates token creation: an
unsampled cycle pays one ``itertools.count`` tick and a modulo, and
every downstream hook short-circuits on ``token is None`` — that is
the whole default-on cost.
"""

from __future__ import annotations

import itertools
import time
from typing import Dict, Optional

from .histograms import LatencyHistogram
from .recorder import FlightRecorder

#: batch-cycle stages in pipeline order
STAGE_INGEST = "ingest"
STAGE_STEP = "step"
STAGE_EMIT = "emit"
#: checkpoint-path stages (free-running, engine kind 'persist')
STAGE_PERSIST_CAPTURE = "persist.capture"
STAGE_PERSIST_WRITE = "persist.write"
#: device-table stages (devtable/): join-probe dispatch and the
#: mutation scatter step
STAGE_TABLE_PROBE = "table.probe"
STAGE_TABLE_UPSERT = "table.upsert"
#: watchdog self-heal (robustness/watchdog.py): one span per trip,
#: covering the replan-driven restore-and-replay — recovery time is a
#: latency distribution like any other stage
STAGE_WATCHDOG_HEAL = "watchdog.heal"

_STAGES = (STAGE_INGEST, STAGE_STEP, STAGE_EMIT,
           STAGE_PERSIST_CAPTURE, STAGE_PERSIST_WRITE,
           STAGE_TABLE_PROBE, STAGE_TABLE_UPSERT,
           STAGE_WATCHDOG_HEAL)


class CycleToken:
    """One sampled batch cycle's identity + in-flight timestamps.

    Created by ``Tracer.begin_cycle`` and threaded through
    ``IngestStage.submit`` and ``PendingEmit`` — each hook records its
    span and stamps the start of the next."""

    __slots__ = ("tracer", "cycle", "engine", "n_events", "n_emit",
                 "t0", "t_dispatch")

    def __init__(self, tracer: "Tracer", cycle: int, engine: str,
                 n_events: int, t0: float):
        self.tracer = tracer
        self.cycle = cycle
        self.engine = engine
        self.n_events = n_events
        self.n_emit = 0
        self.t0 = t0
        self.t_dispatch = t0

    def dispatched(self) -> None:
        """Receive-time work done: conversion + H2D put + jitted step
        dispatch are all queued.  Ends the ingest span."""
        now = self.tracer.clock()
        self.tracer.record(self.cycle, STAGE_INGEST, self.engine,
                           self.t0, now, self.n_events)
        self.t_dispatch = now

    def step_done(self, n_emit: int) -> None:
        """Count gate resolved: the jitted step (and the H2D transfer
        it waited on) finished on device.  Ends the step span."""
        now = self.tracer.clock()
        self.n_emit = n_emit
        self.tracer.record(self.cycle, STAGE_STEP, self.engine,
                           self.t_dispatch, now, self.n_events)

    def emitted(self, t_fetch_start: float) -> None:
        """This cycle's batch materialized on the host (post coalesced
        fetch + callback).  Ends the emit span."""
        self.tracer.record(self.cycle, STAGE_EMIT, self.engine,
                           t_fetch_start, self.tracer.clock(), self.n_emit)

    def aborted(self, stage: str) -> None:
        """The cycle died inside ``stage`` (isolated fault): leave a
        zero-width tombstone span so the flight recorder shows where
        the batch was lost instead of a silent gap."""
        now = self.tracer.clock()
        self.tracer.record(self.cycle, f"{stage}.aborted", self.engine,
                           now, now, self.n_events)


class Tracer:
    """Per-app cycle-id source, span sink and flight-recorder owner."""

    #: default: record every 64th cycle (≤5%-throughput contract)
    DEFAULT_SAMPLE = 64
    #: default flight-recorder depth in cycles
    DEFAULT_CYCLES = 64

    def __init__(self, app_name: str, sample: int = DEFAULT_SAMPLE,
                 cycles: int = DEFAULT_CYCLES,
                 dump_dir: Optional[str] = None):
        self.app_name = app_name
        # 0 = tracing off; 1 = every cycle; N = every Nth cycle
        self.sample = max(0, int(sample))
        self.recorder = FlightRecorder(app_name, cycles=cycles,
                                       dump_dir=dump_dir)
        self.clock = time.perf_counter
        self._ids = itertools.count(1)
        # pre-created so hot-path record() never mutates the dict
        self.stage_hist: Dict[str, LatencyHistogram] = {
            stage: LatencyHistogram() for stage in _STAGES}

    # -- cycle ids -----------------------------------------------------------

    def next_id(self) -> int:
        return next(self._ids)

    def begin_cycle(self, engine: str, n_events: int) -> Optional[CycleToken]:
        """Start one batch cycle; None when this cycle is unsampled
        (every downstream hook no-ops on a None token)."""
        if not self.sample:
            return None
        cid = next(self._ids)
        if self.sample > 1 and cid % self.sample:
            return None
        return CycleToken(self, cid, engine, n_events, self.clock())

    # -- span sink -----------------------------------------------------------

    def record(self, cycle: int, stage: str, engine: str,
               t_start: float, t_end: float, n_events: int) -> None:
        self.recorder.record((cycle, stage, engine, t_start, t_end,
                              n_events))
        hist = self.stage_hist.get(stage)
        if hist is not None:
            hist.record_s(t_end - t_start)

    def record_span(self, stage: str, engine: str, t_start: float,
                    t_end: float, n_events: int = 0,
                    cycle: Optional[int] = None) -> int:
        """Free-running span (persist path): allocates its own cycle id
        from the shared counter unless the caller correlates one."""
        cid = cycle if cycle is not None else next(self._ids)
        self.record(cid, stage, engine, t_start, t_end, n_events)
        return cid

    # -- read-out ------------------------------------------------------------

    def stage_stats(self) -> Dict[str, Dict[str, float]]:
        """stage -> quantile read-out, only for stages that recorded
        (an app with no device engines reports nothing)."""
        out: Dict[str, Dict[str, float]] = {}
        for stage, hist in self.stage_hist.items():
            if hist.count == 0:
                continue
            out[stage] = {
                "spans": hist.count,
                "p50Ms": hist.p50_ms(),
                "p95Ms": hist.p95_ms(),
                "p99Ms": hist.p99_ms(),
                "maxMs": hist.max_ms,
            }
        return out

    def histograms(self):
        """(stage, LatencyHistogram) pairs with data — the Prometheus
        exposition's histogram families."""
        return [(stage, hist) for stage, hist in self.stage_hist.items()
                if hist.count]

    def dump(self, reason: str) -> dict:
        return self.recorder.dump(reason)

    def reset(self) -> None:
        for hist in self.stage_hist.values():
            hist.reset()
