"""Crash-consistent asynchronous durability pipeline.

No analog in the reference engine (its persistence is a synchronous
stop-the-world snapshot + unchecked store write).  This package makes
``persist()`` cheap enough to take continuously and crash-safe at every
intermediate step:

``capture.py``   in-barrier state capture: immutable device-array
                 references + cheap host copies (freeze), with a counted
                 per-element pickle fallback for unfreezable state.
``writer.py``    background checkpoint writer: single-in-flight with
                 coalescing backpressure, retry-with-backoff on store
                 faults, crash containment.
``store.py``     ``DurableFileSystemPersistenceStore``: per-element blob
                 files + a checksummed manifest committed last via
                 fsync + atomic rename; journal-segment storage.
``spill.py``     journal overflow spill: cold input-journal segments
                 move to the persistence store instead of being dropped.
"""

from siddhi_tpu.durability.capture import StateCapture, UnfreezableStateError, freeze
from siddhi_tpu.durability.spill import JournalSpillSink
from siddhi_tpu.durability.store import DurableFileSystemPersistenceStore
from siddhi_tpu.durability.writer import AsyncCheckpointWriter, DurabilityStats

__all__ = [
    "AsyncCheckpointWriter",
    "DurabilityStats",
    "DurableFileSystemPersistenceStore",
    "JournalSpillSink",
    "StateCapture",
    "UnfreezableStateError",
    "freeze",
]
