"""Durable persistence store: blob-per-element + checksummed manifest.

Revision layout (under ``<base>/<app>/``)::

    <revision>.ckpt/
        0000.blob ... NNNN.blob     per-element pickles, fsynced
        MANIFEST.json               committed LAST: tmp + fsync + rename

The manifest carries a SHA-256 per blob plus a self-checksum over its
canonical JSON, so ``load`` detects torn blobs, bit flips, and partial
manifests — a revision without a valid manifest simply does not exist
(``revisions()`` skips it) and ``restore_last_revision()`` walks back to
the previous one.  Crash at ANY point mid-save therefore leaves either
the previous or the new revision fully restorable.

``save_tree`` threads an optional ``checker(site)`` callable (the fault
injector's ``check``) through the commit sequence so the crash-point
matrix can kill the writer between every durability step:
``persist.post_blob`` / ``persist.pre_manifest`` / ``persist.mid_manifest``
(tmp manifest durable, rename pending).

Journal spill segments live beside the revisions under
``<base>/<app>/journal/`` (util/persistence.py FileJournalSegmentMixin).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import shutil
import threading
from typing import Callable, Dict, List, Optional, Tuple

from siddhi_tpu.util.persistence import (
    FileJournalSegmentMixin,
    PersistenceStore,
    fsync_dir,
)

log = logging.getLogger("siddhi_tpu.durability")

MANIFEST_NAME = "MANIFEST.json"
MANIFEST_FORMAT = 1
_SUFFIX = ".ckpt"
# monolithic fallback: PersistenceStore.save bytes wrapped as one blob
_TREE_KIND = "__tree__"


def _manifest_checksum(manifest: Dict) -> str:
    body = {k: v for k, v in manifest.items() if k != "checksum"}
    canon = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode("utf-8")).hexdigest()


class DurableFileSystemPersistenceStore(FileJournalSegmentMixin,
                                        PersistenceStore):
    """Crash-consistent filesystem store (one directory per revision)."""

    def __init__(self, base_dir: str, revisions_to_keep: int = 3):
        self.base_dir = base_dir
        self.revisions_to_keep = revisions_to_keep
        self._lock = threading.Lock()

    def _app_dir(self, app_name: str) -> str:
        return os.path.join(self.base_dir, app_name)

    def _rev_dir(self, app_name: str, revision: str) -> str:
        return os.path.join(self._app_dir(app_name), revision + _SUFFIX)

    # -- save ---------------------------------------------------------------

    def save_tree(self, app_name: str, revision: str,
                  blobs: List[Tuple[str, str, bytes]],
                  checker: Optional[Callable[[str], None]] = None,
                  version: int = 1):
        """Write per-element ``blobs`` [(kind, name, bytes)] and commit
        the revision by atomically publishing its manifest.  Idempotent:
        a retry after a partial failure overwrites and re-commits."""
        with self._lock:
            rev_dir = self._rev_dir(app_name, revision)
            os.makedirs(rev_dir, exist_ok=True)
            elements = []
            for idx, (kind, name, data) in enumerate(blobs):
                fname = f"{idx:04d}.blob"
                path = os.path.join(rev_dir, fname)
                with open(path, "wb") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
                elements.append({
                    "kind": kind, "name": name, "file": fname,
                    "sha256": hashlib.sha256(data).hexdigest(),
                    "size": len(data),
                })
            if checker is not None:
                checker("persist.post_blob")
            manifest = {"format": MANIFEST_FORMAT, "app": app_name,
                        "revision": revision, "version": version,
                        "elements": elements}
            manifest["checksum"] = _manifest_checksum(manifest)
            if checker is not None:
                checker("persist.pre_manifest")
            tmp = os.path.join(rev_dir, MANIFEST_NAME + ".tmp")
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())
            if checker is not None:
                # tmp manifest durable, rename pending: the one crash
                # point where the revision exists but is not committed
                checker("persist.mid_manifest")
            os.replace(tmp, os.path.join(rev_dir, MANIFEST_NAME))
            fsync_dir(rev_dir)
            fsync_dir(self._app_dir(app_name))
            self._evict_locked(app_name)

    def save(self, app_name: str, revision: str, snapshot: bytes):
        """PersistenceStore SPI: monolithic bytes become one blob."""
        self.save_tree(app_name, revision,
                       [(_TREE_KIND, _TREE_KIND, snapshot)])

    def _evict_locked(self, app_name: str):
        committed = self._committed_locked(app_name)
        app_dir = self._app_dir(app_name)
        for old in committed[: max(0, len(committed)
                                   - self.revisions_to_keep)]:
            shutil.rmtree(self._rev_dir(app_name, old), ignore_errors=True)
        # garbage-collect torn dirs (no valid manifest) older than the
        # newest committed revision — crash leftovers, never restorable
        if not committed:
            return
        newest_ts = int(committed[-1].split("_", 1)[0])
        try:
            names = os.listdir(app_dir)
        except OSError:
            return
        live = {r + _SUFFIX for r in committed}
        for d in names:
            if not d.endswith(_SUFFIX) or d in live:
                continue
            rev = d[: -len(_SUFFIX)]
            try:
                ts = int(rev.split("_", 1)[0])
            except ValueError:
                continue
            if ts < newest_ts:
                log.warning("durability: removing torn revision %r of "
                            "app %r (no valid manifest)", rev, app_name)
                shutil.rmtree(os.path.join(app_dir, d), ignore_errors=True)

    # -- load ---------------------------------------------------------------

    def _read_manifest(self, app_name: str, revision: str) -> Optional[Dict]:
        path = os.path.join(self._rev_dir(app_name, revision), MANIFEST_NAME)
        try:
            with open(path, "r", encoding="utf-8") as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            log.warning("durability: revision %r of app %r has no "
                        "readable manifest (%s)", revision, app_name, e)
            return None
        if manifest.get("checksum") != _manifest_checksum(manifest):
            log.warning("durability: manifest checksum mismatch on "
                        "revision %r of app %r", revision, app_name)
            return None
        return manifest

    def _read_blobs(self, app_name: str,
                    revision: str) -> Optional[List[Tuple[str, str, bytes]]]:
        manifest = self._read_manifest(app_name, revision)
        if manifest is None:
            return None
        rev_dir = self._rev_dir(app_name, revision)
        out = []
        for el in manifest.get("elements", []):
            try:
                with open(os.path.join(rev_dir, el["file"]), "rb") as f:
                    data = f.read()
            except OSError as e:
                log.warning("durability: blob %r missing from revision "
                            "%r of app %r (%s)", el.get("file"), revision,
                            app_name, e)
                return None
            if hashlib.sha256(data).hexdigest() != el.get("sha256"):
                log.warning("durability: blob %r of revision %r of app "
                            "%r fails its checksum", el.get("file"),
                            revision, app_name)
                return None
            out.append((el["kind"], el["name"], data))
        return out

    def load(self, app_name: str, revision: str) -> Optional[bytes]:
        """Checksum-validated revision bytes, reassembled into the
        monolithic tree pickle ``SnapshotService.restore`` expects.
        ``None`` on any corruption — the restore walk falls back."""
        blobs = self._read_blobs(app_name, revision)
        if blobs is None:
            return None
        if len(blobs) == 1 and blobs[0][0] == _TREE_KIND:
            return blobs[0][2]
        tree: Dict = {"queries": {}, "tables": {}, "named_windows": {},
                      "partitions": {}, "aggregations": {}}
        try:
            for kind, name, data in blobs:
                tree[kind][name] = pickle.loads(data)
        except Exception as e:
            log.warning("durability: revision %r of app %r holds an "
                        "unreadable element (%s)", revision, app_name, e)
            return None
        manifest = self._read_manifest(app_name, revision)
        tree["version"] = manifest.get("version", 1) if manifest else 1
        tree["app"] = app_name
        return pickle.dumps(tree, protocol=pickle.HIGHEST_PROTOCOL)

    # -- revisions ----------------------------------------------------------

    def _committed_locked(self, app_name: str) -> List[str]:
        """Revisions with a manifest file present, oldest first (manifest
        VALIDITY is checked at load; presence defines existence)."""
        d = self._app_dir(app_name)
        try:
            names = os.listdir(d)
        except OSError:
            return []
        revs = []
        for f in names:
            if not f.endswith(_SUFFIX):
                continue
            rev = f[: -len(_SUFFIX)]
            try:
                int(rev.split("_", 1)[0])
            except ValueError:
                continue
            if os.path.isfile(os.path.join(d, f, MANIFEST_NAME)):
                revs.append(rev)
        return sorted(revs, key=lambda r: int(r.split("_", 1)[0]))

    def get_last_revision(self, app_name: str) -> Optional[str]:
        with self._lock:
            revs = self._committed_locked(app_name)
            return revs[-1] if revs else None

    def revisions(self, app_name: str) -> List[str]:
        with self._lock:
            return self._committed_locked(app_name)

    def clear_all_revisions(self, app_name: str):
        with self._lock:
            d = self._app_dir(app_name)
            try:
                names = os.listdir(d)
            except OSError:
                return
            for f in names:
                if f.endswith(_SUFFIX):
                    shutil.rmtree(os.path.join(d, f), ignore_errors=True)
