"""Journal spill sink: overflowed input-journal segments go to the store.

Before this existed, a full :class:`~siddhi_tpu.util.faults.InputJournal`
dropped its oldest entry and poisoned replay — long checkpoint intervals
forfeited crash recovery.  The sink gives the journal a second tier: on
overflow it pickles the coldest segment of entries and hands it to the
persistence store's journal-segment API
(``save_journal_segment`` / ``load_journal_segments`` /
``prune_journal_segments``); replay stitches spilled + in-memory
segments back into one contiguous sequence.

The sink resolves the store lazily from the manager context (a store
configured after app creation still works) and degrades cleanly: no
store, or a store without the segment API, means ``spill`` returns
False and the journal falls back to the old drop-and-gap behavior.

Store writes go through the ``journal.spill`` fault choke point with the
same bounded retry ladder as checkpoint writes; ``journal.spill.mid``
fires AFTER the segment is durable but BEFORE the journal trims memory —
the mid-spill crash point of the matrix (recovery then sees the segment
and the untrimmed entries overlap; stitching dedups by sequence number).
"""

from __future__ import annotations

import logging
import pickle
import time
from typing import Any, List, Optional, Tuple

from siddhi_tpu.core.exceptions import (
    ConnectionUnavailableError,
    TransferFaultError,
)
from siddhi_tpu.util.faults import (
    DEFAULT_TRANSFER_RETRY_ATTEMPTS,
    DEFAULT_TRANSFER_RETRY_SCALE,
)

log = logging.getLogger("siddhi_tpu.durability")

_RETRYABLE = (TransferFaultError, ConnectionUnavailableError, OSError)


class JournalSpillSink:
    """Bridges one app's InputJournal to the persistence store."""

    def __init__(self, siddhi_context, app_name: str, app_context=None):
        self.siddhi_context = siddhi_context
        self.app_name = app_name
        # carries the CURRENT runtime's fault injector; the planner
        # re-attaches a fresh sink on every (re)build so a post-crash
        # replacement runtime's chaos config applies
        self.app_context = app_context

    def _store(self):
        store = getattr(self.siddhi_context, "persistence_store", None)
        if store is None or not hasattr(store, "save_journal_segment"):
            return None
        return store

    def supported(self) -> bool:
        return self._store() is not None

    def _injector(self):
        return getattr(self.app_context, "fault_injector", None) \
            if self.app_context is not None else None

    def spill(self, seq0: int, seq1: int,
              entries: List[Tuple[int, str, Any]], stats=None) -> bool:
        """Persist ``entries`` (seqs ``seq0..seq1``) as one segment.
        True on success; False when unsupported or the store keeps
        faulting (the journal then falls back to dropping).  A ``crash``
        fault propagates — mid-spill kills are the point."""
        store = self._store()
        if store is None:
            return False
        payload = pickle.dumps(entries, protocol=pickle.HIGHEST_PROTOCOL)
        fi = self._injector()
        attempts = (fi.transfer_retry_attempts if fi is not None
                    else DEFAULT_TRANSFER_RETRY_ATTEMPTS)
        scale = (fi.transfer_retry_scale if fi is not None
                 else DEFAULT_TRANSFER_RETRY_SCALE)
        last: Optional[Exception] = None
        for attempt in range(max(1, attempts)):
            try:
                if fi is not None:
                    fi.check("journal.spill")
                store.save_journal_segment(self.app_name, seq0, seq1,
                                           payload)
                if fi is not None:
                    # segment durable, journal memory not yet trimmed:
                    # the matrix's mid-spill crash point
                    fi.check("journal.spill.mid")
                return True
            except _RETRYABLE as e:
                last = e
                if stats is not None:
                    stats.spill_retries += 1
                if fi is not None:
                    fi.notify(e)
                if attempt + 1 < max(1, attempts):
                    time.sleep(scale * (2 ** attempt))
        log.warning("durability: app '%s' journal spill of seqs %d..%d "
                    "failed after retries (%s); falling back to drop",
                    self.app_name, seq0, seq1, last)
        return False

    def load_segments(self) -> Optional[List[Tuple[int, int, List]]]:
        """[(seq0, seq1, entries)] oldest first; None when the segments
        cannot be read (replay is then refused rather than gapped)."""
        store = self._store()
        if store is None:
            return None
        try:
            raw = store.load_journal_segments(self.app_name)
        except Exception as e:
            log.warning("durability: app '%s' journal segments are "
                        "unreadable (%s)", self.app_name, e)
            return None
        out = []
        for seq0, seq1, payload in raw:
            try:
                out.append((seq0, seq1, pickle.loads(payload)))
            except Exception as e:
                log.warning("durability: app '%s' journal segment "
                            "%d..%d is corrupt (%s)", self.app_name,
                            seq0, seq1, e)
                return None
        return out

    def prune(self, upto_seq: int):
        store = self._store()
        if store is not None:
            store.prune_journal_segments(self.app_name, upto_seq)

    def clear(self):
        store = self._store()
        if store is not None:
            store.clear_journal(self.app_name)
