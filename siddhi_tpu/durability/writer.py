"""Background checkpoint writer: single-in-flight, coalescing, retrying.

``persist(mode='async')`` captures state under the barrier and hands the
writer a *job* (a closure that materializes blobs, writes the store, and
commits the journal mark).  The batch loop resumes immediately; the
writer thread runs the job with a bounded retry ladder on retryable
store faults (``persist.write`` choke point), mirroring the emit-queue's
transfer hardening.

Backpressure is single-in-flight with coalescing: while one checkpoint
is writing, at most ONE newer persist queues; a third supersedes the
queued one (its journal mark is dropped via ``on_abandon`` and the
coalesce is counted) — checkpoints are idempotent full states, so the
newest always wins and the writer can never build an unbounded backlog.

A :class:`~siddhi_tpu.core.exceptions.SimulatedCrashError` (BaseException
— the crash-matrix kill signal) tears the writer down mid-job exactly
like a real SIGKILL: the thread records the crash and stops, journal
marks stay put, and recovery goes through
``restore_last_revision()``'s checksum walk.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from siddhi_tpu.core.exceptions import (
    ConnectionUnavailableError,
    SimulatedCrashError,
    TransferFaultError,
)
from siddhi_tpu.util.faults import (
    DEFAULT_TRANSFER_RETRY_ATTEMPTS,
    DEFAULT_TRANSFER_RETRY_SCALE,
)

log = logging.getLogger("siddhi_tpu.durability")

#: store faults worth a backoff-retry (everything else fails the persist)
_RETRYABLE = (TransferFaultError, ConnectionUnavailableError, OSError)

#: terminal statuses a submitted revision can reach
_DONE = ("committed", "failed", "superseded", "crashed")


class DurabilityStats:
    """Checkpoint-pipeline counters (thin-gauge surfaced through
    ``StatisticsManager.durability_tracker``, model: FaultStats)."""

    __slots__ = (
        "persists_sync",
        "persists_async",
        "persists_coalesced",
        "persist_retries",
        "persist_failures",
        "persist_commits",
        "capture_fallback_elements",
        "blobs_written",
        "bytes_written",
    )

    def __init__(self) -> None:
        for name in self.__slots__:
            setattr(self, name, 0)

    def as_dict(self) -> Dict[str, int]:
        return {name: getattr(self, name) for name in self.__slots__}


class AsyncCheckpointWriter:
    """One daemon writer thread per app runtime (started lazily)."""

    def __init__(self, app_name: str, stats: Optional[DurabilityStats] = None,
                 fault_injector=None,
                 listeners: Optional[List[Any]] = None, tracer=None):
        self.app_name = app_name
        self.stats = stats or DurabilityStats()
        self.fault_injector = fault_injector
        self.listeners = listeners if listeners is not None else []
        # cycle tracer (observability/trace.py): the writer thread spans
        # each store write so checkpoint I/O shows up in the flight
        # recorder interleaved with the batch cycles it overlaps
        self.tracer = tracer
        # condition over the writer lock: every mutable writer field
        # below is read/written only while holding it
        self._lock = threading.Condition(threading.Lock())
        # (revision, job, on_abandon) | None — the ONE queued persist
        self._pending: Optional[Tuple[str, Callable, Optional[Callable]]] = None
        self._inflight: Optional[str] = None
        self._results: Dict[str, str] = {}
        self._stop = False
        self._crashed: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None

    # -- submission ---------------------------------------------------------

    def submit(self, revision: str, job: Callable[[], None],
               on_abandon: Optional[Callable[[str], None]] = None) -> str:
        """Queue a checkpoint job.  Returns the revision.  A queued (not
        yet in-flight) older persist is superseded: its ``on_abandon``
        runs (dropping its journal mark) and the coalesce is counted."""
        abandoned: Optional[Tuple[str, Optional[Callable]]] = None
        with self._lock:
            if self._crashed is not None:
                # writer is dead (simulated crash): the submit itself
                # must not hide that — callers treat it like a crashed
                # process would
                raise SimulatedCrashError(
                    f"checkpoint writer of app '{self.app_name}' crashed")
            if self._pending is not None:
                old_rev, _old_job, old_abandon = self._pending
                self._results[old_rev] = "superseded"
                self.stats.persists_coalesced += 1
                abandoned = (old_rev, old_abandon)
            self._pending = (revision, job, on_abandon)
            self.stats.persists_async += 1
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name=f"ckpt-writer-{self.app_name}",
                    daemon=True)
                self._thread.start()
            self._lock.notify_all()
        if abandoned is not None:
            rev, cb = abandoned
            log.info("durability: app '%s' persist %s coalesced into %s",
                     self.app_name, rev, revision)
            if cb is not None:
                cb(rev)
        return revision

    # -- introspection / barriers -------------------------------------------

    def status(self, revision: str) -> Optional[str]:
        with self._lock:
            if self._pending is not None and self._pending[0] == revision:
                return "pending"
            if self._inflight == revision:
                return "inflight"
            return self._results.get(revision)

    @property
    def crashed(self) -> Optional[BaseException]:
        with self._lock:
            return self._crashed

    def wait(self, revision: Optional[str] = None,
             timeout: Optional[float] = None) -> Optional[str]:
        """Block until ``revision`` reaches a terminal status (or, with
        no revision, until nothing is pending/in-flight).  Returns the
        status (None on timeout / unknown revision)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                if self._crashed is not None:
                    return self._results.get(revision, "crashed") \
                        if revision else "crashed"
                if revision is None:
                    if self._pending is None and self._inflight is None:
                        return "idle"
                else:
                    st = self._results.get(revision)
                    if st in _DONE:
                        return st
                    if (st is None and self._inflight != revision
                            and not (self._pending is not None
                                     and self._pending[0] == revision)):
                        return None  # never submitted
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                self._lock.wait(remaining)

    def shutdown(self, timeout: float = 10.0):
        """Flush outstanding work (bounded) and stop the thread."""
        self.wait(timeout=timeout)
        with self._lock:
            self._stop = True
            self._lock.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout=2)

    # -- writer thread ------------------------------------------------------

    def _run(self):
        while True:
            with self._lock:
                while self._pending is None and not self._stop:
                    self._lock.wait()
                if self._stop and self._pending is None:
                    return
                revision, job, on_abandon = self._pending
                self._pending = None
                self._inflight = revision
                self._lock.notify_all()
            try:
                self._write(revision, job, on_abandon)
            except SimulatedCrashError as e:
                # crash-matrix kill: die like the process would —
                # nothing after the crash point runs
                log.warning("durability: app '%s' checkpoint writer "
                            "crashed at revision %s: %s", self.app_name,
                            revision, e)
                with self._lock:
                    self._results[revision] = "crashed"
                    self._inflight = None
                    self._crashed = e
                    self._lock.notify_all()
                return
            with self._lock:
                self._inflight = None
                self._lock.notify_all()

    def _write(self, revision: str, job: Callable[[], None],
               on_abandon: Optional[Callable[[str], None]]):
        fi = self.fault_injector
        attempts = (fi.transfer_retry_attempts if fi is not None
                    else DEFAULT_TRANSFER_RETRY_ATTEMPTS)
        scale = (fi.transfer_retry_scale if fi is not None
                 else DEFAULT_TRANSFER_RETRY_SCALE)
        last: Optional[Exception] = None
        tracer = self.tracer
        for attempt in range(max(1, attempts)):
            try:
                if fi is not None:
                    fi.check("persist.write")
                t_job = tracer.clock() if tracer is not None else 0.0
                job()
                if tracer is not None:
                    # one span per successful store write — retries that
                    # failed are visible as the counters, not as spans
                    tracer.record_span("persist.write", "persist",
                                       t_job, tracer.clock())
                with self._lock:
                    self._results[revision] = "committed"
                    self.stats.persist_commits += 1
                return
            except _RETRYABLE as e:
                last = e
                with self._lock:
                    self.stats.persist_retries += 1
                if fi is not None:
                    fi.notify(e)
                if attempt + 1 < max(1, attempts):
                    time.sleep(scale * (2 ** attempt))
            except SimulatedCrashError:
                raise
            except Exception as e:
                last = e
                break  # non-retryable store/serialization failure
        log.error("durability: app '%s' checkpoint %s failed after "
                  "retries: %s", self.app_name, revision, last)
        with self._lock:
            self._results[revision] = "failed"
            self.stats.persist_failures += 1
        for ln in list(self.listeners):
            try:
                ln(last)
            except Exception:  # pragma: no cover - listener bug
                log.exception("durability: exception listener failed")
        if on_abandon is not None:
            on_abandon(revision)
