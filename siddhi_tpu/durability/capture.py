"""Non-blocking checkpoint capture: freeze state under the barrier.

The synchronous persist path pickles the whole state tree while sources
are paused — the batch loop stalls for the full serialize+write.  The
async path instead calls :func:`freeze` per element under the barrier:

* device arrays (jax) are kept **by reference** — they are immutable, so
  the D2H fetch can happen later on the writer thread;
* host containers (dicts/lists/EventBatch/numpy) are **shallow-cheap
  copied** so post-barrier mutation cannot race the background pickle;
* anything freeze does not understand makes that ELEMENT fall back to an
  in-barrier ``pickle.dumps`` (``prepickled``), counted through
  ``persistFallbackReason`` — degradation, never corruption.

Materialization (D2H via ``util.faults.host_copy``, the sanctioned
materializer — this module is in the host-sync-hazard scan set and must
not call ``np.asarray``/``np.array`` itself) and per-element pickling
happen in :meth:`StateCapture.materialize_blobs` on the writer thread.
"""

from __future__ import annotations

import pickle
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from siddhi_tpu.core.event import Event, EventBatch
from siddhi_tpu.util.faults import host_copy


class UnfreezableStateError(Exception):
    """An element's state holds a type freeze cannot safely copy."""


_SCALARS = (type(None), bool, int, float, complex, str, bytes)


def _is_device_array(obj: Any) -> bool:
    """Array-like that is NOT numpy: a jax device array (immutable, so a
    reference is a valid capture — fetched to host later, off-barrier)."""
    return (not isinstance(obj, (np.ndarray, np.generic))
            and hasattr(obj, "shape") and hasattr(obj, "dtype"))


def freeze(obj: Any) -> Any:
    """Cheap race-free copy of one element's snapshot state.

    Raises :class:`UnfreezableStateError` on any type whose aliasing
    semantics are unknown — the caller then pre-pickles that element
    under the barrier instead."""
    if isinstance(obj, _SCALARS):
        return obj
    if isinstance(obj, np.generic):
        return obj
    if isinstance(obj, np.ndarray):
        return obj.copy()
    if _is_device_array(obj):
        return obj  # immutable device value: capture by reference
    if isinstance(obj, dict):
        return {k: freeze(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [freeze(v) for v in obj]
    if isinstance(obj, tuple):
        return tuple(freeze(v) for v in obj)
    if isinstance(obj, (set, frozenset)):
        return set(obj) if isinstance(obj, set) else obj
    if isinstance(obj, deque):
        return deque((freeze(v) for v in obj), maxlen=obj.maxlen)
    if isinstance(obj, EventBatch):
        out = EventBatch(
            obj.stream_id,
            list(obj.attribute_names),
            {k: freeze(v) for k, v in obj.columns.items()},
            obj.timestamps.copy(),
            obj.types.copy(),
        )
        out.aux = {k: freeze(v) for k, v in obj.aux.items()}
        return out
    if isinstance(obj, Event):
        return Event(obj.timestamp, [freeze(v) for v in obj.data],
                     obj.is_expired)
    raise UnfreezableStateError(type(obj).__name__)


def _materialize(obj: Any) -> Any:
    """Fetch captured-by-reference device arrays to host.  Runs OFF the
    barrier (writer thread); only called on ``freeze`` output, whose
    containers are private copies."""
    if _is_device_array(obj):
        return host_copy(obj)
    if isinstance(obj, dict):
        return {k: _materialize(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_materialize(v) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_materialize(v) for v in obj)
    if isinstance(obj, deque):
        return deque((_materialize(v) for v in obj), maxlen=obj.maxlen)
    return obj


class CapturedElement:
    """One state-tree element: frozen state OR an in-barrier pickle."""

    __slots__ = ("kind", "name", "state", "prepickled")

    def __init__(self, kind: str, name: str, state: Any = None,
                 prepickled: Optional[bytes] = None):
        self.kind = kind
        self.name = name
        self.state = state
        self.prepickled = prepickled


class StateCapture:
    """Everything ``persist()`` collects under the barrier.

    ``elements`` preserve the snapshot tree's (kind, name) addressing so
    the writer can emit per-element blobs (durable store) or reassemble
    the monolithic tree-pickle (plain stores) — both restore through the
    unchanged ``SnapshotService.restore`` path."""

    __slots__ = ("app", "version", "elements", "fallbacks")

    def __init__(self, app: str, version: int,
                 elements: List[CapturedElement],
                 fallbacks: List[Tuple[str, str]]):
        self.app = app
        self.version = version
        self.elements = elements
        # [(element key, reason)] for elements that took the in-barrier
        # pickle fallback — surfaced as persistFallbackReason
        self.fallbacks = fallbacks

    def materialize_blobs(self) -> List[Tuple[str, str, bytes]]:
        """[(kind, name, pickled bytes)] — D2H fetch + pickle, off-barrier."""
        out: List[Tuple[str, str, bytes]] = []
        for el in self.elements:
            if el.prepickled is not None:
                out.append((el.kind, el.name, el.prepickled))
            else:
                out.append((el.kind, el.name, pickle.dumps(
                    _materialize(el.state),
                    protocol=pickle.HIGHEST_PROTOCOL)))
        return out

    def tree_bytes(self) -> bytes:
        """Monolithic tree pickle, bit-compatible with
        ``SnapshotService.full_snapshot`` (for stores without a
        per-element blob layout)."""
        return pickle.dumps(self.tree(), protocol=pickle.HIGHEST_PROTOCOL)

    def tree(self) -> Dict:
        tree: Dict = {"version": self.version, "app": self.app,
                      "queries": {}, "tables": {}, "named_windows": {},
                      "partitions": {}, "aggregations": {}}
        for el in self.elements:
            if el.prepickled is not None:
                tree[el.kind][el.name] = pickle.loads(el.prepickled)
            else:
                tree[el.kind][el.name] = _materialize(el.state)
        return tree


def capture_elements(app: str, version: int, tree: Dict,
                     element_kinds: Tuple[str, ...],
                     on_fallback: Optional[Callable[[str, str], None]] = None,
                     ) -> StateCapture:
    """Freeze a just-built state tree into a :class:`StateCapture`.

    Caller holds the barrier (process lock, sources paused, emits
    drained).  An element freeze cannot copy is pickled here, in-barrier
    — the per-element sync degradation path — and reported through
    ``on_fallback(element_key, reason)``."""
    elements: List[CapturedElement] = []
    fallbacks: List[Tuple[str, str]] = []
    for kind in element_kinds:
        for name, state in tree.get(kind, {}).items():
            try:
                elements.append(CapturedElement(kind, name,
                                                state=freeze(state)))
            except UnfreezableStateError as e:
                reason = f"unfreezable:{e}"
                fallbacks.append((f"{kind}:{name}", reason))
                if on_fallback is not None:
                    on_fallback(f"{kind}:{name}", reason)
                elements.append(CapturedElement(
                    kind, name,
                    prepickled=pickle.dumps(
                        state, protocol=pickle.HIGHEST_PROTOCOL)))
    return StateCapture(app, version, elements, fallbacks)
