"""Deterministic fault-injection harness + crash-recovery journal.

No analog in the reference engine: this is the TPU build's chaos-testing
and recovery surface.  PR 1 made the product path asynchronous (matched
outputs sit device-resident in a bounded pending-emit queue before a
coalesced device->host drain), which means a transfer failure or a
process crash can silently lose committed matches.  This module supplies

* :class:`FaultInjector` — a seeded, site-addressed fault registry
  installed on ``SiddhiAppContext`` and consulted at every runtime choke
  point (emit-queue drains, jitted step invocations, sharded ingest
  ``device_put``, sink/source connect-and-publish, scheduler timer
  fires, ingest under the process lock).  Faults are reproducible:
  identical seed + identical event sequence => identical injections.

* :class:`InputJournal` — a bounded in-memory journal of post-checkpoint
  input batches keyed to ``SnapshotService`` revisions, so
  ``restore_last_revision()`` becomes restore-and-replay, plus an output
  ledger that deduplicates already-delivered callback/sink events so the
  recovered callback sequence is bit-identical to an uninterrupted run.

* Poison helpers (``host_copy`` / ``poison_state`` / ``state_has_poison``)
  used by the device runtimes for NaN/Inf quarantine.  They live here —
  not in the device modules — because tests/test_emit_guard.py AST-scans
  the device modules for stray synchronous materializations.

Injection sites (strings, by convention ``layer.point``):

====================  ====================================================
``emit.drain``        coalesced device->host fetch in EmitQueue.drain
``ingest.put``        sharded ``device_put`` on the ingest path
``ingest``            InputHandler.send/send_batch under the process lock
``step.device``       jitted step in ops/device_query.py
``step.dense``        jitted step in ops/dense_nfa.py
``step.shard``        jitted step in parallel/device_shard.py
``sink.publish``      Sink.publish_with_reconnect
``sink.connect``      sink (re)connect attempts
``source.connect``    source (re)connect attempts
``timer``             scheduler advance (``stall`` kind: clock stall)
``timer.fire``        individual scheduled-task fires
``callback``          stream-junction callback dispatch
``state.poison``      device-state poisoning (``poison`` kind)
``persist.write``     checkpoint store write (retryable; durability/)
``persist.post_blob``     crash point: element blobs durable, no manifest
``persist.pre_manifest``  crash point: before the manifest tmp write
``persist.mid_manifest``  crash point: manifest tmp durable, rename pending
``persist.post_manifest`` crash point: revision committed, journal mark not
``journal.spill``     journal-segment store write (retryable)
``journal.spill.mid`` crash point: segment durable, journal not yet trimmed
``replan.reseat``     crash point: replacement engines built, old not torn
``admission.shed``    @app:limits admission controller sheds events
``watchdog.trip``     watchdog detected a stall, before the self-heal
``breaker.open``      a transport circuit breaker transitions to OPEN
====================  ====================================================

Fault kinds:

``transient``  raises :class:`TransferFaultError` (retryable)
``sticky``     raises :class:`DeviceLostError` forever once armed
``error``      raises :class:`InjectedFaultError` (callback/sink failure)
``conn``       raises :class:`ConnectionUnavailableError`
``crash``      raises :class:`SimulatedCrashError` (a BaseException)
``stall``      consumed via :meth:`FaultInjector.stalled` (clock stall)
``poison``     consumed via :meth:`FaultInjector.poisoned` (NaN poison)
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.exceptions import (
    ConnectionUnavailableError,
    DeviceLostError,
    InjectedFaultError,
    SimulatedCrashError,
    TransferFaultError,
)

log = logging.getLogger("siddhi_tpu.faults")

_KINDS = ("transient", "sticky", "error", "conn", "crash", "stall", "poison")

# Defaults for the hardening knobs (overridable via @app:faults(...)).
DEFAULT_TRANSFER_RETRY_ATTEMPTS = 3
DEFAULT_TRANSFER_RETRY_SCALE = 0.001  # seconds multiplier on the backoff ladder
DEFAULT_JOURNAL_DEPTH = 256


class FaultStats:
    """Counters for injected faults and the recovery machinery.

    Surfaced through ``StatisticsManager.fault_tracker`` and the REST
    statistics feed (model: EmitStats / EmitTransferTracker)."""

    __slots__ = (
        "faults_injected",
        "transfer_retries",
        "drains_recovered",
        "drains_failed",
        "callback_faults_isolated",
        "poison_quarantines",
        "timer_stalls",
        "replayed_batches",
        "suppressed_events",
        "journal_dropped",
        "connect_retries_exhausted",
        # journal spill tier (durability/spill.py): overflow segments
        # persisted instead of dropped, and how replay used them
        "journal_spills",
        "spilled_batches",
        "spill_retries",
        "replayed_spilled_batches",
    )

    def __init__(self) -> None:
        for name in self.__slots__:
            setattr(self, name, 0)

    def as_dict(self) -> Dict[str, int]:
        return {name: getattr(self, name) for name in self.__slots__}


class FaultSpec:
    """One armed fault at one site.

    ``p``          probability each check trips (seeded RNG)
    ``remaining``  how many times it may trip (``sticky`` never decrements)
    ``after``      number of checks to skip before arming
    """

    __slots__ = ("site", "kind", "p", "remaining", "after", "fired")

    def __init__(self, site: str, kind: str, p: float = 1.0,
                 count: int = 1, after: int = 0) -> None:
        if kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r}; expected one of {_KINDS}")
        self.site = site
        self.kind = kind
        self.p = float(p)
        self.remaining = int(count)
        self.after = int(after)
        self.fired = 0

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (f"FaultSpec({self.site!r}, {self.kind!r}, p={self.p}, "
                f"remaining={self.remaining}, after={self.after})")


class FaultInjector:
    """Seeded, site-addressed fault registry.

    Installed on ``SiddhiAppContext.fault_injector`` by the planner when
    ``@app:faults(...)`` is present (or programmatically in tests).  All
    hook sites are no-ops when no spec targets them, so the harness adds
    a dict lookup per choke point when idle.
    """

    def __init__(self, seed: int = 0) -> None:
        import random as _random

        self.seed = int(seed)
        self._rng = _random.Random(self.seed)
        self._specs: Dict[str, List[FaultSpec]] = {}
        self._lock = threading.Lock()
        self.stats = FaultStats()
        # Wired by the planner to app_context.exception_listeners so
        # injected faults are observable like any runtime error.
        self.listeners: List[Any] = []
        # Hardening knobs (read by EmitQueue / sharded ingest).
        self.transfer_retry_attempts = DEFAULT_TRANSFER_RETRY_ATTEMPTS
        self.transfer_retry_scale = DEFAULT_TRANSFER_RETRY_SCALE
        # Flight recorder (observability/trace.py Tracer), wired by the
        # planner: a simulated crash kill dumps the span ring on its way
        # out — the exact post-mortem the black box exists for.
        self.tracer = None

    # -- configuration ------------------------------------------------

    def configure(self, site: str, kind: str, p: float = 1.0,
                  count: int = 1, after: int = 0) -> "FaultInjector":
        """Arm a fault at ``site``.  Returns self for chaining."""
        spec = FaultSpec(site, kind, p=p, count=count, after=after)
        with self._lock:
            self._specs.setdefault(site, []).append(spec)
        return self

    def watches(self, site: str) -> bool:
        """True when any spec (armed or exhausted) targets ``site`` —
        gates expensive host-side guards (poison scans) to chaos runs."""
        with self._lock:
            return site in self._specs

    def clear(self, site: Optional[str] = None) -> None:
        with self._lock:
            if site is None:
                self._specs.clear()
            else:
                self._specs.pop(site, None)

    def configure_from_options(
            self, options: Dict[str, str]) -> Optional[int]:
        """Apply ``@app:faults(...)`` annotation options.

        Reserved keys: ``seed``, ``transfer.retry.attempts``,
        ``transfer.retry.scale``, ``journal`` / ``journal.depth``.
        Every other key is an injection site whose value is a fault spec
        ``kind[:k=v[:k=v...]]``, e.g.::

            @app:faults(seed='7', emit.drain='transient:count=2:p=0.5')

        Returns the requested journal depth (``None`` if journaling was
        not requested).
        """
        import random as _random

        journal_depth: Optional[int] = None
        for key, value in options.items():
            k = key.strip().lower()
            v = str(value).strip()
            if k == "seed":
                self.seed = int(v)
                self._rng = _random.Random(self.seed)
            elif k == "transfer.retry.attempts":
                self.transfer_retry_attempts = int(v)
            elif k == "transfer.retry.scale":
                self.transfer_retry_scale = float(v)
            elif k in ("journal", "journal.depth"):
                if v.lower() in ("true", "enable", "enabled"):
                    journal_depth = DEFAULT_JOURNAL_DEPTH
                elif v.lower() in ("false", "disable", "disabled"):
                    journal_depth = None
                else:
                    journal_depth = int(v)
            else:
                self._configure_spec(k, v)
        return journal_depth

    def _configure_spec(self, site: str, value: str) -> None:
        parts = [p.strip() for p in value.split(":") if p.strip()]
        if not parts:
            raise ValueError(f"empty fault spec for site {site!r}")
        kind = parts[0].lower()
        kwargs: Dict[str, float] = {}
        for part in parts[1:]:
            if "=" not in part:
                raise ValueError(
                    f"bad fault spec fragment {part!r} for site {site!r}")
            pk, pv = part.split("=", 1)
            pk = pk.strip().lower()
            if pk == "p":
                kwargs["p"] = float(pv)
            elif pk == "count":
                kwargs["count"] = int(pv)
            elif pk == "after":
                kwargs["after"] = int(pv)
            else:
                raise ValueError(
                    f"unknown fault spec key {pk!r} for site {site!r}")
        self.configure(site, kind, **kwargs)

    # -- runtime hooks ------------------------------------------------

    def _trip(self, site: str, kinds: Tuple[str, ...]) -> Optional[FaultSpec]:
        """Return the first armed spec at ``site`` among ``kinds`` that
        trips this check, decrementing its budget (sticky never does)."""
        with self._lock:
            specs = self._specs.get(site)
            if not specs:
                return None
            for spec in specs:
                if spec.kind not in kinds:
                    continue
                if spec.after > 0:
                    spec.after -= 1
                    continue
                if spec.kind != "sticky" and spec.remaining <= 0:
                    continue
                if spec.p < 1.0 and self._rng.random() >= spec.p:
                    continue
                if spec.kind != "sticky":
                    spec.remaining -= 1
                spec.fired += 1
                self.stats.faults_injected += 1
                return spec
        return None

    def check(self, site: str) -> None:
        """Raise the armed fault for ``site``, if any.

        Called at every raising choke point; no-op when nothing is armed.
        """
        spec = self._trip(site, ("transient", "sticky", "error", "conn",
                                 "crash"))
        if spec is None:
            return
        if spec.kind == "crash":
            log.warning("fault-injection: simulated crash at %s", site)
            if self.tracer is not None:
                try:
                    self.tracer.dump(f"fault-injector-crash:{site}")
                except Exception:  # noqa: BLE001 — the kill must win
                    log.exception("fault-injection: flight-recorder dump "
                                  "failed on simulated crash")
            raise SimulatedCrashError(f"injected crash at {site}")
        if spec.kind == "transient":
            e: Exception = TransferFaultError(
                f"injected transient transfer fault at {site}")
        elif spec.kind == "sticky":
            e = DeviceLostError(f"injected device loss at {site}")
        elif spec.kind == "conn":
            e = ConnectionUnavailableError(
                f"injected connection fault at {site}")
        else:
            e = InjectedFaultError(f"injected fault at {site}")
        log.debug("fault-injection: raising %s at %s", type(e).__name__, site)
        raise e

    def stalled(self, site: str) -> bool:
        """True when a ``stall`` fault trips at ``site`` (clock stall:
        the scheduler skips this advance instead of raising)."""
        spec = self._trip(site, ("stall",))
        if spec is not None:
            self.stats.timer_stalls += 1
            log.debug("fault-injection: clock stall at %s", site)
            return True
        return False

    def poisoned(self, site: str) -> bool:
        """True when a ``poison`` fault trips at ``site`` (the device
        runtime then corrupts its state with NaN to exercise the
        quarantine path)."""
        spec = self._trip(site, ("poison",))
        return spec is not None

    def notify(self, e: BaseException) -> None:
        """Feed an injected/handled fault to the runtime's exception
        listeners (best effort)."""
        for ln in list(self.listeners):
            try:
                ln(e)
            except Exception:  # pragma: no cover - listener bug
                log.exception("fault-injection: exception listener failed")


# -- poison helpers ---------------------------------------------------
# These materialize device arrays on the host.  They live here (not in
# the device runtime modules) so tests/test_emit_guard.py's AST scan of
# core/ device modules for synchronous transfers stays meaningful.

def host_copy(state: Any) -> Any:
    """Deep host copy of a (possibly nested) device state pytree.

    Supports the shapes the engines actually use: dicts, tuples/lists,
    and array leaves."""
    if isinstance(state, dict):
        return {k: host_copy(v) for k, v in state.items()}
    if isinstance(state, (tuple, list)):
        seq = [host_copy(v) for v in state]
        return tuple(seq) if isinstance(state, tuple) else seq
    if hasattr(state, "shape") and hasattr(state, "dtype"):
        return np.array(state)
    return state


def _leaves(state: Any) -> List[Any]:
    if isinstance(state, dict):
        out: List[Any] = []
        for v in state.values():
            out.extend(_leaves(v))
        return out
    if isinstance(state, (tuple, list)):
        out = []
        for v in state:
            out.extend(_leaves(v))
        return out
    return [state]


def state_has_poison(state: Any) -> bool:
    """True when any float leaf of ``state`` contains NaN/Inf.

    Materializes to host — callers gate this behind an armed injector or
    an explicit check so the hot path stays transfer-free."""
    for leaf in _leaves(state):
        if not (hasattr(leaf, "dtype") and hasattr(leaf, "shape")):
            continue
        try:
            arr = np.asarray(leaf)
        except Exception:
            continue
        if arr.dtype.kind == "f" and arr.size and not np.isfinite(arr).all():
            return True
    return False


def poison_state(state: Any) -> Any:
    """Return ``state`` with the first float leaf multiplied by NaN
    (structure and dtypes preserved).  Used by the ``poison`` fault."""

    done = {"v": False}

    def _walk(node: Any) -> Any:
        if isinstance(node, dict):
            return {k: _walk(v) for k, v in node.items()}
        if isinstance(node, (tuple, list)):
            seq = [_walk(v) for v in node]
            return tuple(seq) if isinstance(node, tuple) else seq
        if (not done["v"] and hasattr(node, "dtype") and hasattr(node, "shape")
                and getattr(node.dtype, "kind", "") == "f"
                and getattr(node, "size", 0)):
            done["v"] = True
            return node * np.float32("nan")
        return node

    return _walk(state)


# -- input journal + output ledger ------------------------------------

class InputJournal:
    """Bounded in-memory journal of input batches for restore-and-replay.

    ``record`` captures every batch entering an ``InputHandler`` (under
    the app's process lock, so ordering matches delivery order).
    ``mark_revision`` pins the journal to a ``SnapshotService`` revision
    at persist time and snapshots the per-endpoint output counts; after
    a crash, ``entries_after(revision)`` yields exactly the batches the
    checkpoint has not seen, and ``deliver`` suppresses the prefix of
    re-emitted output events each callback/sink already received, so the
    observable sequence is bit-identical to an uninterrupted run.

    The journal is bounded (``depth`` batches).  On overflow it first
    tries to SPILL the coldest ``spill_chunk`` entries to the
    persistence store through ``spill_sink``
    (durability/spill.py JournalSpillSink, attached by the planner);
    replay then stitches spilled + in-memory segments.  Without a
    spill-capable store the old behavior stands: the oldest entry is
    dropped and replay across the gap is refused (``entries_after``
    returns ``None``) because a gapped replay would silently diverge.

    Async persistence splits the old ``mark_revision`` into
    ``note_capture`` (at capture time, under the barrier: records the
    sequence watermark + output-ledger counts, prunes NOTHING) and
    ``commit_revision`` (after the store committed the revision: prunes
    entries and spilled segments at or below the watermark).  A crash
    between the two leaves both the previous and the new revision
    replayable; ``drop_mark`` abandons the mark of a failed/coalesced
    persist.  ``mark_revision`` (= note + commit) remains the
    synchronous-path entry point.
    """

    def __init__(self, depth: int = DEFAULT_JOURNAL_DEPTH,
                 spill_chunk: Optional[int] = None) -> None:
        self.depth = int(depth)
        # how many cold entries move per spill (amortizes store writes)
        self.spill_chunk = int(spill_chunk) if spill_chunk else max(
            1, self.depth // 2)
        self._lock = threading.RLock()
        self._entries: deque = deque()  # (seq, stream_id, batch)
        self._seq = 0
        self._revision: Optional[str] = None  # newest COMMITTED revision
        self._rev_seq = -1
        self._gap = False
        # seqs <= _gap_seq were dropped without spill (unrecoverable)
        self._gap_seq = 0
        # revision -> (seq watermark, output-ledger counts at capture)
        self._marks: Dict[str, Tuple[int, Dict[Any, int]]] = {}
        # spilled segment seq ranges [(seq0, seq1)], oldest first
        self._segments: List[Tuple[int, int]] = []
        # durability/spill.py JournalSpillSink (None = no spill tier)
        self.spill_sink: Optional[Any] = None
        # Output ledger: per-endpoint delivered-event counts.
        self._counts: Dict[Any, int] = {}
        self._marked_counts: Dict[Any, int] = {}
        self._remaining: Dict[Any, int] = {}
        self.replaying = False
        # replaced with the app's FaultInjector.stats by the planner so
        # journal counters ride the same statistics feed
        self.stats: FaultStats = FaultStats()

    # -- recording ----------------------------------------------------

    def record(self, stream_id: str, batch: Any) -> None:
        with self._lock:
            if self.replaying:
                return
            self._seq += 1
            self._entries.append((self._seq, stream_id, batch))
            if len(self._entries) > self.depth:
                self._overflow_locked()

    def _overflow_locked(self) -> None:
        while len(self._entries) > self.depth:
            sink = self.spill_sink
            if sink is not None:
                n = min(self.spill_chunk, len(self._entries))
                chunk = [self._entries[i] for i in range(n)]
                seq0, seq1 = chunk[0][0], chunk[-1][0]
                ok = False
                try:
                    # a `crash` fault (BaseException) propagates out of
                    # here by design — mid-spill kill of the matrix
                    ok = sink.spill(seq0, seq1, chunk, stats=self.stats)
                except Exception:
                    log.exception("journal: spill sink failed; falling "
                                  "back to dropping")
                if ok:
                    for _ in range(n):
                        self._entries.popleft()
                    self._segments.append((seq0, seq1))
                    if self.stats is not None:
                        self.stats.journal_spills += 1
                        self.stats.spilled_batches += n
                    continue
            seq, _sid, _b = self._entries.popleft()
            self._gap_seq = max(self._gap_seq, seq)
            self._gap = True
            if self.stats is not None:
                self.stats.journal_dropped += 1

    def note_capture(self, revision: str) -> None:
        """Record the checkpoint watermark of ``revision`` at CAPTURE
        time (under the barrier).  Prunes nothing — the revision is not
        durable yet."""
        with self._lock:
            self._marks[revision] = (self._seq, dict(self._counts))

    def drop_mark(self, revision: str) -> None:
        """Abandon the mark of a failed or coalesced persist."""
        with self._lock:
            self._marks.pop(revision, None)

    def commit_revision(self, revision: str) -> None:
        """The store committed ``revision``: prune entries and spilled
        segments its checkpoint covers.  No-op on an unknown/superseded
        mark (a commit arriving after a newer one already pruned)."""
        with self._lock:
            mark = self._marks.get(revision)
            if mark is None:
                return
            watermark, counts = mark
            while self._entries and self._entries[0][0] <= watermark:
                self._entries.popleft()
            prune_upto = 0
            keep = []
            for (s0, s1) in self._segments:
                if s1 <= watermark:
                    prune_upto = max(prune_upto, s1)
                else:
                    keep.append((s0, s1))
            self._segments = keep
            if prune_upto and self.spill_sink is not None:
                try:
                    self.spill_sink.prune(prune_upto)
                except Exception:
                    log.exception("journal: spilled-segment prune failed")
            # marks with older watermarks are superseded by this commit
            self._marks = {r: m for r, m in self._marks.items()
                           if m[0] >= watermark}
            self._revision = revision
            self._rev_seq = watermark
            self._marked_counts = counts
            if self._gap_seq <= watermark:
                self._gap = False

    def mark_revision(self, revision: str) -> None:
        """Synchronous-path pin: capture mark + immediate commit."""
        self.note_capture(revision)
        self.commit_revision(revision)

    def entries_after(self, revision: str) -> Optional[List[Tuple[str, Any]]]:
        """Batches recorded after ``revision``'s capture, oldest first —
        stitched from spilled segments + the in-memory tail, deduped by
        sequence number (mid-spill crashes leave an overlap).

        ``None`` when replay is impossible: unknown/unmarked revision,
        an unspilled overflow gap past the watermark, or unreadable
        spilled segments."""
        with self._lock:
            mark = self._marks.get(revision)
            if mark is None:
                return None
            watermark = mark[0]
            if self._gap_seq > watermark:
                return None
            if self._seq <= watermark:
                return []
            collected: Dict[int, Tuple[str, Any]] = {}
            spilled_needed = [s for s in self._segments if s[1] > watermark]
            if spilled_needed:
                sink = self.spill_sink
                loaded = sink.load_segments() if sink is not None else None
                if loaded is None:
                    return None
                for _s0, s1, seg_entries in loaded:
                    if s1 <= watermark:
                        continue
                    for seq, sid, b in seg_entries:
                        if seq > watermark:
                            collected[seq] = (sid, b)
            mem_seqs = set()
            for seq, sid, b in self._entries:
                if seq > watermark:
                    collected[seq] = (sid, b)
                    mem_seqs.add(seq)
            needed = range(watermark + 1, self._seq + 1)
            if any(s not in collected for s in needed):
                return None
            if self.stats is not None:
                self.stats.replayed_spilled_batches += sum(
                    1 for s in needed if s not in mem_seqs)
            return [collected[s] for s in needed]

    # -- live re-plan support -----------------------------------------
    #
    # A live re-plan (core/app_runtime.py replan) rebuilds the engines
    # from scratch — there is no checkpoint revision to restore, so the
    # new engines start EMPTY and the journal replays the WHOLE history
    # to rebuild their state.  The output ledger then suppresses every
    # event each endpoint already received, so the observable sequence
    # across the switch is bit-identical to an uninterrupted run on
    # either plan.

    def covers_from_start(self) -> bool:
        """True when the in-memory journal still holds every batch since
        the app started — the precondition for a full-history replay.
        Overflow (dropped OR spilled entries) breaks it: a re-plan needs
        the contiguous in-memory history, under the process lock, with
        no store round-trips mid-switch."""
        with self._lock:
            if self._gap or self._segments:
                return False
            if self._seq == 0:
                return True
            return bool(self._entries) and \
                self._entries[0][0] == 1 and \
                len(self._entries) == self._seq

    def all_entries(self) -> List[Tuple[str, Any]]:
        """Every recorded batch, oldest first (caller checked
        :meth:`covers_from_start`)."""
        with self._lock:
            return [(sid, b) for _seq, sid, b in self._entries]

    def begin_replay_from_start(self) -> None:
        """Arm the ledger for a full-history replay: every endpoint's
        entire delivered count becomes the suppression budget, and
        counts rebuild from zero as the replay re-delivers."""
        with self._lock:
            self.replaying = True
            self._remaining = dict(self._counts)
            self._counts = {}

    # -- replay + output dedup ---------------------------------------

    def begin_replay(self, revision: Optional[str] = None) -> None:
        with self._lock:
            base = self._marked_counts
            if revision is not None:
                mark = self._marks.get(revision)
                if mark is not None:
                    base = mark[1]
            self.replaying = True
            # Suppress exactly the delta each endpoint saw between the
            # checkpoint and the crash; counts restart from the mark.
            self._remaining = {
                k: self._counts.get(k, 0) - base.get(k, 0)
                for k in self._counts
            }
            self._counts = dict(base)

    def end_replay(self) -> None:
        with self._lock:
            self.replaying = False
            self._remaining = {}

    def deliver(self, key: Any, batch: Any):
        """Ledger gate for an output endpoint (callback / sink).

        Counts delivered events; during replay, suppresses the prefix
        the endpoint already received before the crash.  Returns the
        batch to actually deliver (possibly trimmed) or ``None`` when
        fully suppressed."""
        try:
            n = len(batch)
        except TypeError:
            n = 1
        if n == 0:
            return batch
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + n
            if not self.replaying:
                return batch
            rem = self._remaining.get(key, 0)
            if rem <= 0:
                return batch
            k = min(rem, n)
            self._remaining[key] = rem - k
            if self.stats is not None:
                self.stats.suppressed_events += k
            if k == n:
                return None
            take = getattr(batch, "take", None)
            if take is None:  # pragma: no cover - non-batch payloads
                return batch
            return take(np.arange(k, n))

    def reset(self) -> None:
        """Forget everything (restore from raw bytes / fresh start)."""
        with self._lock:
            self._entries.clear()
            self._seq = 0
            self._revision = None
            self._rev_seq = -1
            self._gap = False
            self._gap_seq = 0
            self._marks = {}
            self._segments = []
            if self.spill_sink is not None:
                try:
                    self.spill_sink.clear()
                except Exception:
                    log.exception("journal: spilled-segment clear failed")
            self._counts = {}
            self._marked_counts = {}
            self._remaining = {}
            self.replaying = False
