"""Scheduler: time-driven window eviction and triggers.

Replaces the reference's per-event scheduler threads
(util/Scheduler.java:48 notifyAt + ScheduledExecutorService) with a
watermark design: every event arrival advances the app watermark and
fires due window ticks under the app lock; a background timer thread
covers idle periods in processing-time mode (playback mode is purely
event-driven, reference: TimestampGeneratorImpl + @app:playback).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, List, Optional, Tuple

log = logging.getLogger("siddhi_tpu")

# per-task fire cap within one advance(); far above any legitimate
# timer fan (a task re-arming every fire drains one wakeup per fire)
_MAX_DRAIN_FIRES = 100_000


class Scheduler:
    def __init__(self, app_context):
        self.app_context = app_context
        # (query_runtime, window) pairs needing time ticks
        self._windows: List[Tuple[object, object]] = []
        # plain callbacks: fn(now) -> None, with next_wakeup() -> int|None
        self._tasks: List[object] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._last_advance = -1

    def register_window(self, query_runtime, window):
        self._windows.append((query_runtime, window))

    def register_task(self, task):
        """task must expose fire(now) and next_wakeup() -> Optional[int]."""
        self._tasks.append(task)

    def unregister_window(self, query_runtime, window):
        try:
            self._windows.remove((query_runtime, window))
        except ValueError:
            pass

    def unregister_task(self, task):
        try:
            self._tasks.remove(task)
        except ValueError:
            pass

    # -- event-driven path (called under app lock) --------------------------

    def advance(self, now: int):
        if now <= self._last_advance:
            return
        fi = getattr(self.app_context, "fault_injector", None)
        if fi is not None and fi.stalled("timer"):
            # injected clock stall: this watermark advance is dropped —
            # due fires are deferred until the next advance (which will
            # deliver every elapsed wakeup via the drain loop below)
            return
        self._last_advance = now
        # snapshot both lists: a fire may (un)register tasks mid-iteration
        # (e.g. a partition purge closing per-key instances)
        for qr, w in list(self._windows):
            wake = w.next_wakeup()
            if wake is not None and wake <= now:
                qr.on_time(now)
        for t in list(self._tasks):
            # drain ALL elapsed wakeups, not just one: a watermark jump
            # over several timer windows must deliver each fire (e.g.
            # `every not X for t` re-arms after each fire and fires once
            # per silent window — EveryAbsentPatternTestCase).  The
            # equal-wake guard stops tasks whose fire does not advance
            # their clock.
            prev = None
            # defensive cap: a task whose wakeups oscillate between two
            # distinct elapsed values would otherwise spin this drain
            # forever (the equal-wake guard only catches exact repeats)
            for _ in range(_MAX_DRAIN_FIRES):
                wake = t.next_wakeup()
                if wake is None or wake > now or wake == prev:
                    break
                prev = wake
                try:
                    if fi is not None:
                        fi.check("timer.fire")
                    t.fire(now)
                except Exception as e:
                    # timer-fire isolation: one failing task must not
                    # kill the watermark advance for every other task
                    # (SimulatedCrashError is a BaseException and still
                    # tears through, as a real crash would)
                    log.error("scheduler task %r failed on fire(%d): %s",
                              t, now, e)
                    for ln in list(
                            getattr(self.app_context,
                                    "exception_listeners", [])):
                        try:
                            ln(e)
                        except Exception:
                            log.exception("exception listener failed")
                    break
            else:
                logging.getLogger("siddhi_tpu").warning(
                    "scheduler task %r still has elapsed wakeups after "
                    "%d fires in one advance; deferring to the next tick",
                    t, _MAX_DRAIN_FIRES)

    # -- wall-clock fallback (processing-time mode only) --------------------

    def start(self, tick_ms: int = 50):
        now = self.app_context.timestamp_generator.current_time()
        for t in self._tasks:
            if hasattr(t, "on_start"):
                t.on_start(now)
        if self.app_context.playback:
            return  # event-time only
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, args=(tick_ms,), daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self, tick_ms: int):
        while not self._stop.wait(tick_ms / 1000.0):
            now = self.app_context.timestamp_generator.current_time()
            with self.app_context.process_lock:
                self.advance(now)
