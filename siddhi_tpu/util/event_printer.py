"""EventPrinter: test/debug output helper (reference: util/EventPrinter.java)."""

from __future__ import annotations

import logging
from typing import List, Optional

log = logging.getLogger("siddhi_tpu.EventPrinter")


def print_events(*args):
    """print_events(events) or print_events(timestamp, in_events, out_events)."""
    if len(args) == 1:
        log.info("%s", args[0])
        print(args[0])
    else:
        ts, in_events, out_events = args
        line = f"Events{{ @timestamp = {ts}, inEvents = {in_events}, RemoveEvents = {out_events} }}"
        log.info("%s", line)
        print(line)
