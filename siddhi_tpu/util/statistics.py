"""Statistics: throughput / latency / buffer metrics.

Re-design of the reference ``util/statistics/`` (SiddhiStatisticsManager
behind Dropwizard MetricRegistry, ThroughputTracker per junction,
LatencyTracker marked in/out around each query chain, Level
OFF/BASIC/DETAIL from @app:statistics, runtime-switchable): plain host
counters — the event path is micro-batched, so tracker overhead is one
increment per batch, not per event.

Metric naming follows the reference convention
``io.siddhi.SiddhiApps.<app>.Siddhi.<kind>.<name>.<metric>``
(SiddhiAppRuntimeImpl.registerForBufferedEvents:802-821).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from siddhi_tpu.observability.histograms import LatencyHistogram


class Level:
    OFF = "off"
    BASIC = "basic"
    DETAIL = "detail"

    _ORDER = {OFF: 0, BASIC: 1, DETAIL: 2}

    @classmethod
    def at_least(cls, level: str, needed: str) -> bool:
        return cls._ORDER.get(level, 0) >= cls._ORDER[needed]


class ThroughputTracker:
    """Events-seen counter with a windowed rate
    (reference: util/statistics/ThroughputTracker).

    ``events_per_second`` reports a recent-window rate: finished
    windows fold into an EMA, so the figure tracks what the stream is
    doing NOW.  The historical count-over-total-elapsed figure — which
    decays toward zero on any long-lived app whose traffic is not
    perfectly uniform — stays available as
    ``lifetime_events_per_second``.  ``clock`` is injectable for
    tests."""

    #: window width folded into the rate EMA
    WINDOW_S = 5.0
    #: EMA weight of the newest finished window
    ALPHA = 0.3

    def __init__(self, name: str, clock=time.monotonic):
        self.name = name
        self.count = 0
        self._clock = clock
        self._start = clock()
        self._win_start = self._start
        self._win_count = 0
        self._rate_ema: Optional[float] = None

    def _fold(self, now: float):
        """Close the current window into the EMA when it is old enough.
        A long idle stretch folds as several windows' worth at once —
        the EMA weight compounds with the elapsed window count, so the
        reported rate decays toward zero the way a live dashboard
        should instead of lingering on stale traffic."""
        dt = now - self._win_start
        if dt < self.WINDOW_S:
            return
        rate = self._win_count / dt
        alpha = 1.0 - (1.0 - self.ALPHA) ** (dt / self.WINDOW_S)
        self._rate_ema = (rate if self._rate_ema is None
                          else self._rate_ema + alpha
                          * (rate - self._rate_ema))
        self._win_start = now
        self._win_count = 0

    def add(self, n: int):
        self.count += n
        self._win_count += n
        self._fold(self._clock())

    def events_per_second(self) -> float:
        """Windowed rate; before the first window closes it equals the
        lifetime rate (identical to the historical read-out for young
        trackers)."""
        now = self._clock()
        self._fold(now)
        if self._rate_ema is None:
            dt = now - self._start
            return self.count / dt if dt > 0 else 0.0
        return self._rate_ema

    def lifetime_events_per_second(self) -> float:
        """Historical semantics: total count over total elapsed time."""
        dt = self._clock() - self._start
        return self.count / dt if dt > 0 else 0.0

    def reset(self):
        self.count = 0
        self._start = self._clock()
        self._win_start = self._start
        self._win_count = 0
        self._rate_ema = None


class LatencyTracker:
    """Per-query in-pipeline latency, marked around the chain
    (reference: util/statistics/LatencyTracker +
    ProcessStreamReceiver.java:79-87)."""

    def __init__(self, name: str):
        self.name = name
        self.batches = 0
        self.events = 0
        self.total_s = 0.0
        self.max_s = 0.0
        # fixed-bucket distribution behind the p50/p95/p99 read-outs
        # (observability/histograms.py; also scraped by /metrics)
        self.hist = LatencyHistogram()
        self._t0 = None

    def mark_in(self, n_events: int):
        self._t0 = time.perf_counter()
        self.events += n_events

    def mark_out(self, n_events: int):
        if self._t0 is None:
            return
        dt = time.perf_counter() - self._t0
        self._t0 = None
        self.batches += 1
        self.total_s += dt
        self.max_s = max(self.max_s, dt)
        self.hist.record_s(dt)

    def avg_ms(self) -> float:
        return (self.total_s / self.batches) * 1000.0 if self.batches else 0.0

    def max_ms(self) -> float:
        return self.max_s * 1000.0

    def p50_ms(self) -> float:
        return self.hist.p50_ms()

    def p95_ms(self) -> float:
        return self.hist.p95_ms()

    def p99_ms(self) -> float:
        return self.hist.p99_ms()

    def reset(self):
        self.batches = 0
        self.events = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self.hist.reset()


class BufferedEventsTracker:
    """Async-junction queue depth gauge (reference: buffer gauges in
    SiddhiAppRuntimeImpl.registerForBufferedEvents)."""

    def __init__(self, name: str, junction):
        self.name = name
        self.junction = junction

    def buffered(self) -> int:
        q = getattr(self.junction, "_queue", None)
        return q.qsize() if q is not None else 0


class EmitTransferTracker:
    """Device→host transfer counters of one device runtime's async emit
    pipeline (core/emit_queue.py EmitStats): a thin gauge view so the
    counters increment on the hot path without touching this module."""

    def __init__(self, name: str, emit_stats):
        self.name = name
        self.emit_stats = emit_stats

    def values(self) -> Dict[str, int]:
        return self.emit_stats.as_dict()


class IngestTracker:
    """Host→device staging counters of one device runtime's ingest
    pipeline (core/ingest_stage.py IngestStats): same thin-gauge pattern
    as EmitTransferTracker — the hot path increments its own counters,
    this view just reads them."""

    def __init__(self, name: str, ingest_stats):
        self.name = name
        self.ingest_stats = ingest_stats

    def values(self) -> Dict[str, int]:
        return self.ingest_stats.as_dict()


class FaultTracker:
    """Fault-injection / recovery counters (util/faults.py FaultStats):
    same thin-gauge pattern as EmitTransferTracker — the harness
    increments its own counters, this view just reads them."""

    def __init__(self, name: str, fault_stats):
        self.name = name
        self.fault_stats = fault_stats

    def values(self) -> Dict[str, int]:
        return self.fault_stats.as_dict()


class DurabilityTracker:
    """Checkpoint-pipeline counters (durability/writer.py
    DurabilityStats): same thin-gauge pattern as FaultTracker — the
    persist path increments its own counters, this view just reads
    them."""

    def __init__(self, name: str, durability_stats):
        self.name = name
        self.durability_stats = durability_stats

    def values(self) -> Dict[str, int]:
        return self.durability_stats.as_dict()


class RobustnessTracker:
    """Overload-protection counters (robustness/ RobustnessStats):
    same thin-gauge pattern as FaultTracker — the admission controller,
    breakers, watchdog and ladder increment their own counters, this
    view just reads them (and the health endpoint reads the SAME
    object, so feed and endpoint cannot disagree)."""

    def __init__(self, name: str, robustness_stats):
        self.name = name
        self.robustness_stats = robustness_stats

    def values(self) -> Dict[str, int]:
        return self.robustness_stats.as_dict()


class StatisticsManager:
    """Tracker registry + periodic console reporter
    (reference: util/statistics/metrics/SiddhiStatisticsManager.java:35)."""

    def __init__(self, app_name: str, interval_s: float = 60.0):
        self.app_name = app_name
        self.interval_s = interval_s
        self.throughput: Dict[str, ThroughputTracker] = {}
        self.latency: Dict[str, LatencyTracker] = {}
        self.buffers: Dict[str, BufferedEventsTracker] = {}
        # per-query device→host emit-transfer gauges (async emit
        # pipeline; one per device-lowered query)
        self.transfers: Dict[str, EmitTransferTracker] = {}
        # per-query host→device ingest-staging gauges (double-buffered
        # H2D pipeline; one per device-lowered query)
        self.ingests: Dict[str, IngestTracker] = {}
        # fault-injection / recovery gauges (@app:faults harness),
        # registered ungated so recovery events stay visible even at
        # statistics level 'off'
        self.faults: Dict[str, FaultTracker] = {}
        # checkpoint-pipeline gauges (async persist writer, durability/),
        # registered ungated like the fault counters — a degraded
        # durability pipeline must stay visible at statistics level 'off'
        self.durability: Dict[str, DurabilityTracker] = {}
        # overload-protection gauges (@app:limits, robustness/),
        # registered ungated — shedding and breaker trips must stay
        # visible at statistics level 'off'
        self.robustness: Dict[str, RobustnessTracker] = {}
        # persist-path degradations (unfreezable element → in-barrier
        # pickle, incremental store forcing sync): count + last reason,
        # keyed '<app>' or '<app>.<kind>:<element>', never silent
        self.persist_fallbacks: Dict[str, int] = {}
        self.persist_fallback_reasons: Dict[str, str] = {}
        # per-query engine placement ('host' | 'dense' | 'device'),
        # populated at app build — not a counter, but reported alongside
        # so execution('tpu') fallbacks are visible in the metrics feed
        self.lowering: Dict[str, str] = {}
        # queries that requested a mesh but fell back to a single
        # device (unsupported kind/feature): count + last reason per
        # query, populated by the planner so the downgrade is never
        # silent
        self.sharded_fallbacks: Dict[str, int] = {}
        self.sharded_fallback_reasons: Dict[str, str] = {}
        # queries (or partitions) under execution('tpu') that fell back
        # to a host engine — the dense/device/probe eligibility gates:
        # count + last reason, populated by the planner so the
        # downgrade is counted, not just logged
        self.device_fallbacks: Dict[str, int] = {}
        self.device_fallback_reasons: Dict[str, str] = {}
        # queries under @app:multiplex that could not be seated in a
        # shared engine (incompatible shape/feature): count + last
        # reason per query, populated by the multiplex planner; and the
        # placements that DID land, keyed by query with their group
        # fingerprint + seat occupancy at placement time
        self.multiplex_fallbacks: Dict[str, int] = {}
        self.multiplex_fallback_reasons: Dict[str, str] = {}
        self.multiplex_placements: Dict[str, str] = {}
        # queries under @app:fuse whose chain (or chain membership)
        # could not stay device-resident and went down the junction
        # path: count + last reason per query, populated by the fusion
        # planner so the downgrade is never silent
        self.fused_fallbacks: Dict[str, int] = {}
        self.fused_fallback_reasons: Dict[str, str] = {}
        # queries under @app:hotkeys that stayed on the plain dense
        # path (outside the scan class): count + last reason per query;
        # and the live routers that DID land, read each report for
        # their promotion/demotion/routed-event decision counters
        self.hotkey_fallbacks: Dict[str, int] = {}
        self.hotkey_fallback_reasons: Dict[str, str] = {}
        self.hotkey_routers: Dict[str, object] = {}
        # queries under @app:kernels whose Pallas kernel(s) could not
        # be enabled (probe failure, ineligible shape, lowering
        # rejection): count + last reason per query — the downgrade to
        # the plain XLA formulation is never silent
        self.kernel_fallbacks: Dict[str, int] = {}
        self.kernel_fallback_reasons: Dict[str, str] = {}
        # queries/tables under @app:devtables that kept (or returned to)
        # the host table path — build-time eligibility gates, plan-time
        # join/mutation gates, mid-run demotions and per-batch generic
        # delegations: count + last reason, keyed '<query>' or
        # 'table:<id>'; and the live DeviceTable instances, read each
        # report for their rows/capacity/revision/demotion gauges
        self.devtable_fallbacks: Dict[str, int] = {}
        self.devtable_fallback_reasons: Dict[str, str] = {}
        self.devtables: Dict[str, object] = {}
        # cost-based planner feed (planner/costmodel.py): candidates the
        # cost gates rejected (count + last reason — same discipline as
        # every other fallback family), pins that LOST to a
        # higher-precedence pin (fuse > shard > multiplex > hotkeys),
        # the per-query PlanRecords behind /siddhi-plan, and the
        # app-wide replan history the PlanMonitor / forced-REST path
        # appends to
        self.planner_fallbacks: Dict[str, int] = {}
        self.planner_fallback_reasons: Dict[str, str] = {}
        self.planner_conflicts: Dict[str, int] = {}
        self.planner_conflict_reasons: Dict[str, str] = {}
        self.plans: Dict[str, object] = {}
        self.replans: List[Dict[str, object]] = []
        # batch-cycle tracer (observability/trace.py); registered ungated
        # at app build — stage_stats() only reports stages that actually
        # recorded spans, so host-only apps keep an empty feed
        self.tracer = None
        self._reporter: Optional[threading.Thread] = None
        self._running = False
        # generation counter: a restarted reporter invalidates the old
        # thread even if it is still asleep inside its interval
        self._generation = 0

    def _metric(self, kind: str, name: str, metric: str) -> str:
        return f"io.siddhi.SiddhiApps.{self.app_name}.Siddhi.{kind}.{name}.{metric}"

    def throughput_tracker(self, name: str) -> ThroughputTracker:
        return self.throughput.setdefault(name, ThroughputTracker(name))

    def latency_tracker(self, name: str) -> LatencyTracker:
        return self.latency.setdefault(name, LatencyTracker(name))

    def buffer_tracker(self, name: str, junction) -> BufferedEventsTracker:
        return self.buffers.setdefault(name, BufferedEventsTracker(name, junction))

    def transfer_tracker(self, name: str, emit_stats) -> EmitTransferTracker:
        return self.transfers.setdefault(
            name, EmitTransferTracker(name, emit_stats))

    def ingest_tracker(self, name: str, ingest_stats) -> IngestTracker:
        return self.ingests.setdefault(
            name, IngestTracker(name, ingest_stats))

    def fault_tracker(self, name: str, fault_stats) -> FaultTracker:
        return self.faults.setdefault(name, FaultTracker(name, fault_stats))

    def durability_tracker(self, name: str,
                           durability_stats) -> DurabilityTracker:
        return self.durability.setdefault(
            name, DurabilityTracker(name, durability_stats))

    def robustness_tracker(self, name: str,
                           robustness_stats) -> RobustnessTracker:
        return self.robustness.setdefault(
            name, RobustnessTracker(name, robustness_stats))

    def record_persist_fallback(self, name: str, reason: str):
        """A persist degraded (element pickled in-barrier, async forced
        sync); counted with the last reason kept."""
        self.persist_fallbacks[name] = (
            self.persist_fallbacks.get(name, 0) + 1)
        self.persist_fallback_reasons[name] = reason

    def record_sharded_fallback(self, qname: str, reason: str):
        """A query that requested mesh sharding is running
        single-device; counted per query with the last reason kept."""
        self.sharded_fallbacks[qname] = (
            self.sharded_fallbacks.get(qname, 0) + 1)
        self.sharded_fallback_reasons[qname] = reason

    def record_device_fallback(self, qname: str, reason: str):
        """A query (or partition) that requested execution('tpu') is
        running on a host engine; counted with the last reason kept."""
        self.device_fallbacks[qname] = (
            self.device_fallbacks.get(qname, 0) + 1)
        self.device_fallback_reasons[qname] = reason

    def record_multiplex_fallback(self, qname: str, reason: str):
        """A query under @app:multiplex is running on a dedicated
        engine; counted per query with the last reason kept."""
        self.multiplex_fallbacks[qname] = (
            self.multiplex_fallbacks.get(qname, 0) + 1)
        self.multiplex_fallback_reasons[qname] = reason

    def record_fused_fallback(self, qname: str, reason: str):
        """A query under @app:fuse is hopping through its junction
        instead of a fused device chain; counted per query with the
        last reason kept."""
        self.fused_fallbacks[qname] = (
            self.fused_fallbacks.get(qname, 0) + 1)
        self.fused_fallback_reasons[qname] = reason

    def record_hotkey_fallback(self, qname: str, reason: str):
        """A query under @app:hotkeys is running plain dense routing;
        counted per query with the last reason kept."""
        self.hotkey_fallbacks[qname] = (
            self.hotkey_fallbacks.get(qname, 0) + 1)
        self.hotkey_fallback_reasons[qname] = reason

    def record_kernel_fallback(self, qname: str, reason: str):
        """A query (or aggregation) under @app:kernels is running the
        plain XLA formulation for at least one kernel kind; counted per
        query with the last reason kept."""
        self.kernel_fallbacks[qname] = (
            self.kernel_fallbacks.get(qname, 0) + 1)
        self.kernel_fallback_reasons[qname] = reason

    def record_devtable_fallback(self, name: str, reason: str):
        """A query or table under @app:devtables is using the host
        table path (ineligible, demoted, or a batch delegated to the
        generic callback); counted with the last reason kept."""
        self.devtable_fallbacks[name] = (
            self.devtable_fallbacks.get(name, 0) + 1)
        self.devtable_fallback_reasons[name] = reason

    def record_planner_fallback(self, qname: str, reason: str):
        """The cost model rejected a candidate lowering (or refused a
        replan) for a query; counted per query with the last reason
        kept — a cost-gate rejection is never silent."""
        self.planner_fallbacks[qname] = (
            self.planner_fallbacks.get(qname, 0) + 1)
        self.planner_fallback_reasons[qname] = reason

    def record_planner_conflict(self, qname: str, reason: str):
        """Two pinned annotations applied to one query and the
        lower-precedence pin lost (fuse > shard > multiplex > hotkeys);
        counted per query with the last reason kept."""
        self.planner_conflicts[qname] = (
            self.planner_conflicts.get(qname, 0) + 1)
        self.planner_conflict_reasons[qname] = reason

    def register_plan(self, qname: str, record):
        """The chosen PlanRecord for a query (planner/costmodel.py):
        candidates with costs, the pick, pins, and the per-query
        re-plan history — the payload behind /siddhi-plan/<app>."""
        self.plans[qname] = record

    def record_replan(self, qname: str, old: str, new: str,
                      forced: bool, reason: str):
        """A live re-lowering switched a query's plan; appended to the
        app-wide history and the query's PlanRecord."""
        entry = {"query": qname, "from": old, "to": new,
                 "forced": forced, "reason": reason, "ts": time.time()}
        self.replans.append(entry)
        rec = self.plans.get(qname)
        if rec is not None:
            rec.note_replan(old, new, forced, reason)

    def register_devtable(self, tname: str, table):
        """A live DeviceTable; its ``devtable_metrics()`` gauges (live
        rows, capacity, revision, scatter steps, compactions,
        demotions) join the feed under ``Tables.<name>.*``."""
        self.devtables[tname] = table

    def register_hotkey_router(self, qname: str, router):
        """A live HotKeyRouterRuntime; its ``hot_metrics()`` gauges
        (promotions/demotions/routed events/active keys) join the
        feed."""
        self.hotkey_routers[qname] = router

    def record_multiplex_placement(self, qname: str, fingerprint: str,
                                   occupied: int):
        """A query seated in a shared multiplex group."""
        self.multiplex_placements[qname] = (
            f"{fingerprint[:12]}:{occupied}")

    def register_tracer(self, tracer):
        """The app's batch-cycle tracer; its per-stage span histograms
        join the feed as ``Stages.<stage>.<metric>`` keys."""
        self.tracer = tracer

    def stats(self) -> Dict[str, object]:
        """Metric name -> value.  Values are floats except the
        ``Queries.<name>.loweredTo`` /
        ``Queries.<name>.shardedFallbackReason`` keys, whose values are
        strings."""
        out: Dict[str, object] = {}
        # snapshot the registries: _apply_statistics_level repopulates
        # them from another thread while the reporter iterates
        for t in list(self.throughput.values()):
            out[self._metric("Streams", t.name, "throughput")] = t.events_per_second()
            out[self._metric("Streams", t.name, "totalEvents")] = t.count
        for l in list(self.latency.values()):
            out[self._metric("Queries", l.name, "latencyAvgMs")] = l.avg_ms()
            out[self._metric("Queries", l.name, "latencyMaxMs")] = l.max_ms()
            out[self._metric("Queries", l.name, "latencyP50Ms")] = l.p50_ms()
            out[self._metric("Queries", l.name, "latencyP95Ms")] = l.p95_ms()
            out[self._metric("Queries", l.name, "latencyP99Ms")] = l.p99_ms()
            out[self._metric("Queries", l.name, "events")] = l.events
        for b in list(self.buffers.values()):
            out[self._metric("Streams", b.name, "bufferedEvents")] = b.buffered()
        for tt in list(self.transfers.values()):
            for metric, v in tt.values().items():
                out[self._metric("Queries", tt.name, metric)] = v
        for it in list(self.ingests.values()):
            for metric, v in it.values().items():
                out[self._metric("Queries", it.name, metric)] = v
        for ft in list(self.faults.values()):
            for metric, v in ft.values().items():
                out[self._metric("Faults", ft.name, metric)] = v
        for dt in list(self.durability.values()):
            for metric, v in dt.values().items():
                out[self._metric("Durability", dt.name, metric)] = v
        for rt in list(self.robustness.values()):
            for metric, v in rt.values().items():
                out[self._metric("Robustness", rt.name, metric)] = v
        for name, n in list(self.persist_fallbacks.items()):
            out[self._metric("Durability", name, "persistFallbacks")] = n
            out[self._metric("Durability", name, "persistFallbackReason")] = (
                self.persist_fallback_reasons.get(name, ""))
        for qname, engine in list(self.lowering.items()):
            out[self._metric("Queries", qname, "loweredTo")] = engine
        for qname, n in list(self.sharded_fallbacks.items()):
            out[self._metric("Queries", qname, "shardedFallbacks")] = n
            out[self._metric("Queries", qname, "shardedFallbackReason")] = (
                self.sharded_fallback_reasons.get(qname, ""))
        for qname, n in list(self.device_fallbacks.items()):
            out[self._metric("Queries", qname, "deviceFallbacks")] = n
            out[self._metric("Queries", qname, "deviceFallbackReason")] = (
                self.device_fallback_reasons.get(qname, ""))
        for qname, n in list(self.multiplex_fallbacks.items()):
            out[self._metric("Queries", qname, "multiplexFallbacks")] = n
            out[self._metric("Queries", qname, "multiplexFallbackReason")] = (
                self.multiplex_fallback_reasons.get(qname, ""))
        for qname, gp in list(self.multiplex_placements.items()):
            out[self._metric("Queries", qname, "multiplexGroup")] = gp
        for qname, n in list(self.fused_fallbacks.items()):
            out[self._metric("Queries", qname, "fusedFallbacks")] = n
            out[self._metric("Queries", qname, "fusedFallbackReason")] = (
                self.fused_fallback_reasons.get(qname, ""))
        for qname, n in list(self.hotkey_fallbacks.items()):
            out[self._metric("Queries", qname, "hotkeyFallbacks")] = n
            out[self._metric("Queries", qname, "hotkeyFallbackReason")] = (
                self.hotkey_fallback_reasons.get(qname, ""))
        for qname, router in list(self.hotkey_routers.items()):
            for metric, v in router.hot_metrics().items():
                out[self._metric("Queries", qname, metric)] = v
        for qname, n in list(self.kernel_fallbacks.items()):
            out[self._metric("Queries", qname, "kernelFallbacks")] = n
            out[self._metric("Queries", qname, "kernelFallbackReason")] = (
                self.kernel_fallback_reasons.get(qname, ""))
        for qname, n in list(self.devtable_fallbacks.items()):
            out[self._metric("Queries", qname, "devtableFallbacks")] = n
            out[self._metric("Queries", qname, "devtableFallbackReason")] = (
                self.devtable_fallback_reasons.get(qname, ""))
        for qname, n in list(self.planner_fallbacks.items()):
            out[self._metric("Queries", qname, "plannerFallbacks")] = n
            out[self._metric("Queries", qname, "plannerFallbackReason")] = (
                self.planner_fallback_reasons.get(qname, ""))
        for qname, n in list(self.planner_conflicts.items()):
            out[self._metric("Queries", qname, "plannerConflicts")] = n
            out[self._metric("Queries", qname, "plannerConflictReason")] = (
                self.planner_conflict_reasons.get(qname, ""))
        for qname, rec in list(self.plans.items()):
            # legacy-mode records are informational (the REST plan dump
            # reads them); they stay off the metrics feed so un-annotated
            # apps keep their pre-cost-model statistics surface
            if rec.mode == "legacy" and not rec.replans:
                continue
            out[self._metric("Queries", qname, "plannerPath")] = rec.chosen
            out[self._metric("Queries", qname, "plannerPredictedCost")] = (
                rec.predicted_cost)
            out[self._metric("Queries", qname, "plannerReplans")] = (
                len(rec.replans))
        for tname, table in list(self.devtables.items()):
            for metric, v in table.devtable_metrics().items():
                out[self._metric("Tables", tname, metric)] = v
        if self.tracer is not None:
            for stage, metrics in self.tracer.stage_stats().items():
                for metric, v in metrics.items():
                    out[self._metric("Stages", stage, metric)] = v
        return out

    def reset(self):
        for t in list(self.throughput.values()):
            t.reset()
        for l in list(self.latency.values()):
            l.reset()

    # -- console reporter ---------------------------------------------------

    def start_reporting(self):
        import logging

        if self._running:
            return
        self._running = True
        self._generation += 1
        gen = self._generation
        log = logging.getLogger(__name__)

        def loop():
            while self._running and gen == self._generation:
                time.sleep(self.interval_s)
                if not self._running or gen != self._generation:
                    break
                try:
                    for k, v in sorted(self.stats().items()):
                        log.info("%s = %s", k, v)
                except Exception:  # noqa: BLE001 — reporter must survive
                    log.exception("statistics reporter failed; continuing")

        self._reporter = threading.Thread(
            target=loop, name=f"stats-{self.app_name}", daemon=True
        )
        self._reporter.start()

    def stop_reporting(self):
        self._running = False
        self._generation += 1
