"""Config plane: extension system-parameters and transport/store refs.

Re-design of the reference ``util/config/`` (ConfigManager.java:26 SPI —
generateConfigReader / extractSystemConfigs / extractProperty,
InMemoryConfigManager.java, YAMLConfigManager.java with its
RootConfiguration model {extensions, refs, properties}).  A ConfigReader
feeds an extension its deployment-level defaults; ``refs`` let
``@source(ref='x')`` / ``@sink(ref='x')`` / ``@store(ref='x')`` pull
connection settings from config instead of inlining them in SiddhiQL.
"""

from __future__ import annotations

from typing import Dict, Optional

from siddhi_tpu.core.exceptions import SiddhiAppCreationError


class ConfigReader:
    """Per-extension key/value reader (reference: ConfigReader.java)."""

    def __init__(self, configs: Optional[Dict[str, str]] = None):
        self._configs = dict(configs or {})

    def read_config(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self._configs.get(key, default)

    def get_all_configs(self) -> Dict[str, str]:
        return dict(self._configs)

    # Java-style aliases
    readConfig = read_config
    getAllConfigs = get_all_configs


class ConfigManager:
    """SPI (reference: ConfigManager.java:26)."""

    def generate_config_reader(self, namespace: str, name: str) -> ConfigReader:
        raise NotImplementedError

    def extract_system_configs(self, name: str) -> Dict[str, str]:
        """Configs for a ``ref='name'`` reference (includes 'type')."""
        raise NotImplementedError

    def extract_property(self, name: str) -> Optional[str]:
        raise NotImplementedError

    # Java-style aliases
    def generateConfigReader(self, namespace, name):
        return self.generate_config_reader(namespace, name)

    def extractSystemConfigs(self, name):
        return self.extract_system_configs(name)

    def extractProperty(self, name):
        return self.extract_property(name)


class InMemoryConfigManager(ConfigManager):
    """Dict-backed manager (reference: InMemoryConfigManager.java).

    ``configs`` keys are '<namespace>.<name>.<key>' (extension configs)
    or plain property names; ``system_configs`` maps ref-name ->
    {'type': ..., **properties}.
    """

    def __init__(self, configs: Optional[Dict[str, str]] = None,
                 system_configs: Optional[Dict[str, Dict[str, str]]] = None):
        self._configs = dict(configs or {})
        self._system = {k: dict(v) for k, v in (system_configs or {}).items()}

    def generate_config_reader(self, namespace: str, name: str) -> ConfigReader:
        prefix = f"{namespace}.{name}."
        return ConfigReader({
            k[len(prefix):]: v for k, v in self._configs.items()
            if k.startswith(prefix)
        })

    def extract_system_configs(self, name: str) -> Dict[str, str]:
        return dict(self._system.get(name, {}))

    def extract_property(self, name: str) -> Optional[str]:
        return self._configs.get(name)


class YAMLConfigManager(ConfigManager):
    """YAML-backed manager (reference: YAMLConfigManager.java).  Accepts
    the reference's document shape::

        properties:
          some.property: value
        extensions:
          - extension:
              namespace: source
              name: http
              properties:
                default.port: '8280'
        refs:
          - ref:
              name: store1
              type: memory
              properties:
                topic: t1
    """

    def __init__(self, yaml_content: Optional[str] = None,
                 file_path: Optional[str] = None):
        try:
            import yaml
        except ImportError as e:  # pragma: no cover — baked into the image
            raise SiddhiAppCreationError("pyyaml is required for YAMLConfigManager") from e
        if file_path is not None:
            with open(file_path) as f:
                yaml_content = f.read()
        try:
            root = yaml.safe_load(yaml_content or "") or {}
        except yaml.YAMLError as e:
            raise SiddhiAppCreationError(f"unable to parse YAML config: {e}") from e
        self._properties: Dict[str, str] = {
            str(k): str(v) for k, v in (root.get("properties") or {}).items()
        }
        self._extensions: Dict[tuple, Dict[str, str]] = {}
        for item in root.get("extensions") or []:
            ext = (item or {}).get("extension") or {}
            key = (str(ext.get("namespace", "")), str(ext.get("name", "")))
            self._extensions[key] = {
                str(k): str(v) for k, v in (ext.get("properties") or {}).items()
            }
        self._refs: Dict[str, Dict[str, str]] = {}
        for item in root.get("refs") or []:
            ref = (item or {}).get("ref") or {}
            nm = str(ref.get("name", ""))
            configs = {"type": str(ref.get("type", ""))}
            configs.update(
                {str(k): str(v) for k, v in (ref.get("properties") or {}).items()}
            )
            self._refs[nm] = configs

    def generate_config_reader(self, namespace: str, name: str) -> ConfigReader:
        return ConfigReader(self._extensions.get((namespace, name), {}))

    def extract_system_configs(self, name: str) -> Dict[str, str]:
        return dict(self._refs.get(name, {}))

    def extract_property(self, name: str) -> Optional[str]:
        return self._properties.get(name)
