"""Cross-cutting services: scheduler, statistics, persistence, transport
(the reference ``core/util/`` analog)."""
