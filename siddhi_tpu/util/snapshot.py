"""Snapshot service: full and incremental app state capture/restore.

Re-design of the reference ``util/snapshot/SnapshotService.java:90``: the
reference quiesces event threads with a ThreadBarrier, walks every
registered StateHolder keyed partitionId -> query -> element ->
(partitionKey x groupByKey), and Java-serializes the map.  Here the
quiesce point is the app's process lock (micro-batches are atomic under
it), the walk covers queries / tables / named windows / partitions /
aggregations, and serialization is pickle (numpy arrays and host dicts
round-trip losslessly).
"""

from __future__ import annotations

import hashlib
import pickle
import time
from typing import Dict, List, Optional, Tuple

from siddhi_tpu.core.exceptions import CannotRestoreSiddhiAppStateError


SNAPSHOT_FORMAT_VERSION = 1


class SnapshotService:
    """Captures and restores the full state tree of one SiddhiAppRuntime."""

    def __init__(self, app_runtime):
        self.app = app_runtime
        # incremental mode: per-element digests since the last base
        self._digests: Dict[Tuple[str, str], str] = {}
        self._incs_since_base = 0

    # -- capture ------------------------------------------------------------

    def _state_tree(self) -> Dict:
        tree: Dict = {
                "version": SNAPSHOT_FORMAT_VERSION,
                "app": self.app.name,
                "queries": {},
                "tables": {},
                "named_windows": {},
                "partitions": {},
                "aggregations": {},
            }
        for qname, qr in self.app.query_runtimes.items():
            if hasattr(qr, "snapshot_state"):
                tree["queries"][qname] = qr.snapshot_state()
        for tname, t in self.app.tables.items():
            tree["tables"][tname] = t.snapshot()
        for wname, w in self.app.named_windows.items():
            tree["named_windows"][wname] = w.snapshot()
        for pname, p in self.app.partitions.items():
            tree["partitions"][pname] = p.snapshot()
        for aname, a in self.app.aggregations.items():
            tree["aggregations"][aname] = a.snapshot()
        return tree

    def full_snapshot(self) -> bytes:
        with self.app.app_context.process_lock:
            return pickle.dumps(self._state_tree(), protocol=pickle.HIGHEST_PROTOCOL)

    def capture(self, on_fallback=None):
        """Non-blocking capture for the async persist path
        (durability/capture.py): under the lock, freeze each element —
        immutable device-array references + cheap host copies — instead
        of pickling the whole tree.  Elements freeze cannot copy are
        pickled here (in-barrier) and reported via ``on_fallback``.
        Returns a ``StateCapture``; serialization and the D2H fetch run
        on the checkpoint writer thread."""
        from siddhi_tpu.durability.capture import capture_elements

        with self.app.app_context.process_lock:
            tree = self._state_tree()
            return capture_elements(self.app.name, SNAPSHOT_FORMAT_VERSION,
                                    tree, self._ELEMENT_KINDS,
                                    on_fallback=on_fallback)

    # -- incremental capture -------------------------------------------------

    _ELEMENT_KINDS = ("queries", "tables", "named_windows", "partitions", "aggregations")

    def incremental_snapshot(self, base_interval: int = 10) -> Tuple[str, bytes]:
        """Capture state at changed-element granularity (re-design of the
        reference BASE/INCREMENT split, SnapshotService.java:186 +
        IncrementalSnapshot.java: the reference logs per-queue operations;
        here each element whose serialized state digest changed since the
        last base/increment is shipped whole — elements are the unit of
        incrementality).

        Returns ``(kind, bytes)`` with kind 'base' (full tree) or 'inc'
        (changed elements only).  A base is emitted on the first call and
        every ``base_interval`` increments."""
        with self.app.app_context.process_lock:
            tree = self._state_tree()
            blobs: Dict[Tuple[str, str], bytes] = {}
            digests: Dict[Tuple[str, str], str] = {}
            for kind in self._ELEMENT_KINDS:
                for name, state in tree[kind].items():
                    b = pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)
                    blobs[(kind, name)] = b
                    digests[(kind, name)] = hashlib.sha1(b).hexdigest()
            make_base = (
                not self._digests
                or self._incs_since_base + 1 >= base_interval
            )
            if make_base:
                self._digests = digests
                self._incs_since_base = 0
                return "base", pickle.dumps(tree, protocol=pickle.HIGHEST_PROTOCOL)
            changed = {
                key: blobs[key]
                for key, dg in digests.items()
                if self._digests.get(key) != dg
            }
            self._digests = digests
            self._incs_since_base += 1
            inc = {
                "version": SNAPSHOT_FORMAT_VERSION,
                "app": self.app.name,
                "elements": changed,
            }
            return "inc", pickle.dumps(inc, protocol=pickle.HIGHEST_PROTOCOL)

    def restore_incremental(self, base: bytes, increments: List[bytes]):
        """Restore a base snapshot overlaid with increments (in order)."""
        try:
            tree = pickle.loads(base)
        except Exception as e:
            raise CannotRestoreSiddhiAppStateError(
                f"app '{self.app.name}': base snapshot is unreadable: {e}"
            ) from e
        for raw in increments:
            try:
                inc = pickle.loads(raw)
            except Exception as e:
                raise CannotRestoreSiddhiAppStateError(
                    f"app '{self.app.name}': increment is unreadable: {e}"
                ) from e
            for (kind, name), blob in inc.get("elements", {}).items():
                tree[kind][name] = pickle.loads(blob)
        self.restore(pickle.dumps(tree, protocol=pickle.HIGHEST_PROTOCOL))

    # -- restore ------------------------------------------------------------

    def restore(self, snapshot: bytes):
        try:
            tree = pickle.loads(snapshot)
        except Exception as e:
            raise CannotRestoreSiddhiAppStateError(
                f"app '{self.app.name}': snapshot is unreadable: {e}"
            ) from e
        if tree.get("version") != SNAPSHOT_FORMAT_VERSION:
            raise CannotRestoreSiddhiAppStateError(
                f"app '{self.app.name}': snapshot format "
                f"{tree.get('version')!r} != {SNAPSHOT_FORMAT_VERSION}"
            )
        with self.app.app_context.process_lock:
            try:
                for qname, qs in tree["queries"].items():
                    qr = self.app.query_runtimes.get(qname)
                    if qr is not None and hasattr(qr, "restore_state"):
                        qr.restore_state(qs)
                for tname, ts in tree["tables"].items():
                    t = self.app.tables.get(tname)
                    if t is not None:
                        t.restore(ts)
                for wname, ws in tree["named_windows"].items():
                    w = self.app.named_windows.get(wname)
                    if w is not None:
                        w.restore(ws)
                for pname, ps in tree["partitions"].items():
                    p = self.app.partitions.get(pname)
                    if p is not None:
                        p.restore(ps)
                for aname, as_ in tree["aggregations"].items():
                    a = self.app.aggregations.get(aname)
                    if a is not None:
                        a.restore(as_)
            except CannotRestoreSiddhiAppStateError:
                raise
            except Exception as e:
                raise CannotRestoreSiddhiAppStateError(
                    f"app '{self.app.name}': state restore failed: {e}"
                ) from e
            finally:
                # a restore invalidates the incremental digest cache: an
                # 'inc' diffed against PRE-restore digests would corrupt
                # the chain on replay — force the next snapshot to a base
                self._digests = {}
                self._incs_since_base = 0

    # -- revisions ----------------------------------------------------------

    _rev_lock = __import__("threading").Lock()
    _last_rev_ts = 0

    @classmethod
    def new_revision(cls, app_name: str) -> str:
        """Monotonic per-process revision ids: two persists in the same
        millisecond must not collide (file names and the base/increment
        ordering are keyed by this timestamp)."""
        with cls._rev_lock:
            ts = max(int(time.time() * 1000), cls._last_rev_ts + 1)
            cls._last_rev_ts = ts
        return f"{ts}_{app_name}"
