"""Snapshot service: full app state capture and restore.

Re-design of the reference ``util/snapshot/SnapshotService.java:90``: the
reference quiesces event threads with a ThreadBarrier, walks every
registered StateHolder keyed partitionId -> query -> element ->
(partitionKey x groupByKey), and Java-serializes the map.  Here the
quiesce point is the app's process lock (micro-batches are atomic under
it), the walk covers queries / tables / named windows / partitions /
aggregations, and serialization is pickle (numpy arrays and host dicts
round-trip losslessly).
"""

from __future__ import annotations

import pickle
import time
from typing import Dict, Optional

from siddhi_tpu.core.exceptions import CannotRestoreSiddhiAppStateError


SNAPSHOT_FORMAT_VERSION = 1


class SnapshotService:
    """Captures and restores the full state tree of one SiddhiAppRuntime."""

    def __init__(self, app_runtime):
        self.app = app_runtime

    # -- capture ------------------------------------------------------------

    def full_snapshot(self) -> bytes:
        with self.app.app_context.process_lock:
            tree: Dict = {
                "version": SNAPSHOT_FORMAT_VERSION,
                "app": self.app.name,
                "queries": {},
                "tables": {},
                "named_windows": {},
                "partitions": {},
                "aggregations": {},
            }
            for qname, qr in self.app.query_runtimes.items():
                if hasattr(qr, "snapshot_state"):
                    tree["queries"][qname] = qr.snapshot_state()
            for tname, t in self.app.tables.items():
                tree["tables"][tname] = t.snapshot()
            for wname, w in self.app.named_windows.items():
                tree["named_windows"][wname] = w.snapshot()
            for pname, p in self.app.partitions.items():
                tree["partitions"][pname] = p.snapshot()
            for aname, a in self.app.aggregations.items():
                tree["aggregations"][aname] = a.snapshot()
            return pickle.dumps(tree, protocol=pickle.HIGHEST_PROTOCOL)

    # -- restore ------------------------------------------------------------

    def restore(self, snapshot: bytes):
        try:
            tree = pickle.loads(snapshot)
        except Exception as e:
            raise CannotRestoreSiddhiAppStateError(
                f"app '{self.app.name}': snapshot is unreadable: {e}"
            ) from e
        if tree.get("version") != SNAPSHOT_FORMAT_VERSION:
            raise CannotRestoreSiddhiAppStateError(
                f"app '{self.app.name}': snapshot format "
                f"{tree.get('version')!r} != {SNAPSHOT_FORMAT_VERSION}"
            )
        with self.app.app_context.process_lock:
            try:
                for qname, qs in tree["queries"].items():
                    qr = self.app.query_runtimes.get(qname)
                    if qr is not None and hasattr(qr, "restore_state"):
                        qr.restore_state(qs)
                for tname, ts in tree["tables"].items():
                    t = self.app.tables.get(tname)
                    if t is not None:
                        t.restore(ts)
                for wname, ws in tree["named_windows"].items():
                    w = self.app.named_windows.get(wname)
                    if w is not None:
                        w.restore(ws)
                for pname, ps in tree["partitions"].items():
                    p = self.app.partitions.get(pname)
                    if p is not None:
                        p.restore(ps)
                for aname, as_ in tree["aggregations"].items():
                    a = self.app.aggregations.get(aname)
                    if a is not None:
                        a.restore(as_)
            except CannotRestoreSiddhiAppStateError:
                raise
            except Exception as e:
                raise CannotRestoreSiddhiAppStateError(
                    f"app '{self.app.name}': state restore failed: {e}"
                ) from e

    # -- revisions ----------------------------------------------------------

    @staticmethod
    def new_revision(app_name: str) -> str:
        return f"{int(time.time() * 1000)}_{app_name}"
