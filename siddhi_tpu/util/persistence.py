"""Persistence stores: where serialized app snapshots live.

Re-design of the reference ``util/persistence/``
(InMemoryPersistenceStore.java, FileSystemPersistenceStore.java,
PersistenceHelper.java): a store maps (app name, revision) -> bytes,
where revision = ``<epoch_ms>_<app name>`` so lexicographic-by-timestamp
ordering gives the latest revision.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, List, Optional, Tuple

log = logging.getLogger("siddhi_tpu.persistence")


def fsync_dir(path: str):
    """fsync a directory so a just-renamed/created entry survives power
    loss (best effort: some filesystems refuse directory fds)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError as e:  # pragma: no cover - fs-dependent
        log.debug("persistence: directory fsync of %s failed: %s", path, e)
    finally:
        os.close(fd)


class FileJournalSegmentMixin:
    """Journal spill segments for filesystem-backed stores: one file per
    segment under ``<base>/<app>/journal/<seq0>_<seq1>.seg`` (the dir
    name carries no ``_``/revision prefix, so revision listings skip
    it).  Requires ``self._app_dir`` and ``self._lock``."""

    _JOURNAL_DIR = "journal"

    def _journal_dir(self, app_name: str) -> str:
        return os.path.join(self._app_dir(app_name), self._JOURNAL_DIR)

    @staticmethod
    def _seg_name(seq0: int, seq1: int) -> str:
        return f"{seq0:012d}-{seq1:012d}.seg"

    def save_journal_segment(self, app_name: str, seq0: int, seq1: int,
                             payload: bytes):
        with self._lock:
            d = self._journal_dir(app_name)
            os.makedirs(d, exist_ok=True)
            name = self._seg_name(seq0, seq1)
            tmp = os.path.join(d, name + ".tmp")
            with open(tmp, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(d, name))
            fsync_dir(d)

    def _segments(self, app_name: str) -> List[Tuple[int, int, str]]:
        d = self._journal_dir(app_name)
        try:
            names = os.listdir(d)
        except OSError:
            return []
        out = []
        for f in names:
            if not f.endswith(".seg"):
                continue
            try:
                seq0, seq1 = f[:-4].split("-", 1)
                out.append((int(seq0), int(seq1), f))
            except ValueError:
                log.warning("persistence: skipping foreign journal "
                            "segment %r in %s", f, d)
        return sorted(out)

    def load_journal_segments(
            self, app_name: str) -> List[Tuple[int, int, bytes]]:
        with self._lock:
            out = []
            d = self._journal_dir(app_name)
            for seq0, seq1, fname in self._segments(app_name):
                with open(os.path.join(d, fname), "rb") as f:
                    out.append((seq0, seq1, f.read()))
            return out

    def prune_journal_segments(self, app_name: str, upto_seq: int):
        """Remove segments fully covered by a committed checkpoint."""
        with self._lock:
            d = self._journal_dir(app_name)
            for _seq0, seq1, fname in self._segments(app_name):
                if seq1 <= upto_seq:
                    try:
                        os.remove(os.path.join(d, fname))
                    except OSError:
                        pass

    def clear_journal(self, app_name: str):
        with self._lock:
            d = self._journal_dir(app_name)
            for _seq0, _seq1, fname in self._segments(app_name):
                try:
                    os.remove(os.path.join(d, fname))
                except OSError:
                    pass


class PersistenceStore:
    """SPI: save / load / last revision / clear for one app's snapshots."""

    def save(self, app_name: str, revision: str, snapshot: bytes):
        raise NotImplementedError

    def load(self, app_name: str, revision: str) -> Optional[bytes]:
        raise NotImplementedError

    def get_last_revision(self, app_name: str) -> Optional[str]:
        raise NotImplementedError

    def revisions(self, app_name: str) -> List[str]:
        """All revisions, oldest first.  Default falls back to the last
        revision only; concrete stores override with the full list so
        restore can walk backwards past a corrupted newest revision."""
        last = self.get_last_revision(app_name)
        return [last] if last else []

    def clear_all_revisions(self, app_name: str):
        raise NotImplementedError


class InMemoryPersistenceStore(PersistenceStore):
    """Keeps revisions in a process-local dict (reference:
    InMemoryPersistenceStore.java).  Bounded: only the newest
    ``revisions_to_keep`` survive, so periodic persistence cannot grow
    the process without limit (parity with the filesystem store)."""

    def __init__(self, revisions_to_keep: int = 10):
        self.revisions_to_keep = revisions_to_keep
        self._store: Dict[str, Dict[str, bytes]] = {}
        # journal spill segments: app -> {(seq0, seq1): payload}
        self._journal: Dict[str, Dict[Tuple[int, int], bytes]] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _rev_key(revision: str):
        """Order by the leading timestamp of ``<ts>_<app>`` revision ids
        (SnapshotService.new_revision); foreign ids sort lexically."""
        head = revision.split("_", 1)[0]
        return (0, int(head), "") if head.isdigit() else (1, 0, revision)

    def save(self, app_name: str, revision: str, snapshot: bytes):
        with self._lock:
            revs = self._store.setdefault(app_name, {})
            revs[revision] = snapshot
            for old in sorted(revs, key=self._rev_key
                              )[: max(0, len(revs) - self.revisions_to_keep)]:
                del revs[old]

    def load(self, app_name: str, revision: str) -> Optional[bytes]:
        with self._lock:
            return self._store.get(app_name, {}).get(revision)

    def get_last_revision(self, app_name: str) -> Optional[str]:
        with self._lock:
            revs = self._store.get(app_name)
            if not revs:
                return None
            return max(revs, key=self._rev_key)

    def revisions(self, app_name: str) -> List[str]:
        with self._lock:
            revs = self._store.get(app_name, {})
            return sorted(revs, key=self._rev_key)

    def clear_all_revisions(self, app_name: str):
        with self._lock:
            self._store.pop(app_name, None)

    # -- journal spill segments (durability/spill.py) -----------------

    def save_journal_segment(self, app_name: str, seq0: int, seq1: int,
                             payload: bytes):
        with self._lock:
            self._journal.setdefault(app_name, {})[(seq0, seq1)] = payload

    def load_journal_segments(
            self, app_name: str) -> List[Tuple[int, int, bytes]]:
        with self._lock:
            segs = self._journal.get(app_name, {})
            return [(s0, s1, segs[(s0, s1)]) for s0, s1 in sorted(segs)]

    def prune_journal_segments(self, app_name: str, upto_seq: int):
        with self._lock:
            segs = self._journal.get(app_name, {})
            for key in [k for k in segs if k[1] <= upto_seq]:
                del segs[key]

    def clear_journal(self, app_name: str):
        with self._lock:
            self._journal.pop(app_name, None)


class FileSystemPersistenceStore(FileJournalSegmentMixin, PersistenceStore):
    """One file per revision under ``<base>/<app>/<revision>``
    (reference: FileSystemPersistenceStore.java).  Keeps the newest
    ``revisions_to_keep`` files (reference default 3)."""

    def __init__(self, base_dir: str, revisions_to_keep: int = 3):
        self.base_dir = base_dir
        self.revisions_to_keep = revisions_to_keep
        self._lock = threading.Lock()

    def _app_dir(self, app_name: str) -> str:
        return os.path.join(self.base_dir, app_name)

    def _revisions(self, app_name: str) -> List[str]:
        d = self._app_dir(app_name)
        try:
            names = os.listdir(d)
        except OSError:
            # missing or concurrently-deleted app dir: no revisions
            return []
        # .tmp files are crash leftovers from an interrupted save;
        # names without a valid <epoch_ms>_ prefix are foreign junk
        revs = []
        for f in names:
            if "_" not in f or f.endswith(".tmp"):
                continue
            try:
                int(f.split("_", 1)[0])
            except ValueError:
                log.warning("persistence: skipping foreign file %r in %s",
                            f, d)
                continue
            revs.append(f)
        return sorted(revs, key=lambda r: int(r.split("_", 1)[0]))

    def save(self, app_name: str, revision: str, snapshot: bytes):
        with self._lock:
            d = self._app_dir(app_name)
            os.makedirs(d, exist_ok=True)
            tmp = os.path.join(d, revision + ".tmp")
            # fsync before the rename and fsync the dir after: without
            # both, a power loss can leave a "committed" revision empty
            # (rename durable, data not) or missing (rename not durable)
            with open(tmp, "wb") as f:
                f.write(snapshot)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(d, revision))
            fsync_dir(d)
            # evict oldest beyond the keep count
            revs = self._revisions(app_name)
            for old in revs[: max(0, len(revs) - self.revisions_to_keep)]:
                try:
                    os.remove(os.path.join(d, old))
                except OSError:
                    pass

    def load(self, app_name: str, revision: str) -> Optional[bytes]:
        path = os.path.join(self._app_dir(app_name), revision)
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError as e:
            # missing file OR app dir deleted between listing and read
            log.warning("persistence: cannot read revision %r of app "
                        "%r (%s); skipping", revision, app_name, e)
            return None
        if not data:
            # zero-length file: a save truncated by a crash before any
            # bytes landed — treat as absent, restore falls back
            log.warning("persistence: revision %r of app %r is empty "
                        "(truncated save?); skipping", revision, app_name)
            return None
        return data

    def get_last_revision(self, app_name: str) -> Optional[str]:
        with self._lock:
            revs = self._revisions(app_name)
            return revs[-1] if revs else None

    def revisions(self, app_name: str) -> List[str]:
        with self._lock:
            return self._revisions(app_name)

    def clear_all_revisions(self, app_name: str):
        with self._lock:
            d = self._app_dir(app_name)
            try:
                names = os.listdir(d)
            except OSError:
                return  # already gone (or never created)
            for f in names:
                try:
                    os.remove(os.path.join(d, f))
                except OSError:
                    # concurrently deleted: the goal state is reached
                    pass


class IncrementalPersistenceStore:
    """SPI for base+increment persistence (reference:
    util/persistence/IncrementalPersistenceStore.java): revisions carry a
    kind ('base' | 'inc'); restore needs the newest base plus every
    increment after it, in order."""

    def save(self, app_name: str, revision: str, kind: str, data: bytes):
        raise NotImplementedError

    def load_chain(self, app_name: str, until_revision: Optional[str] = None):
        """-> (base_revision, base_bytes, [(inc_revision, inc_bytes), ...])
        or None when no base exists.  With ``until_revision``, the chain
        stops at that revision (newest base at or before it plus the
        increments between)."""
        raise NotImplementedError

    def get_last_revision(self, app_name: str) -> Optional[str]:
        raise NotImplementedError

    def clear_all_revisions(self, app_name: str):
        raise NotImplementedError


class IncrementalFileSystemPersistenceStore(IncrementalPersistenceStore):
    """``<base>/<app>/<revision>.base|.inc`` files (reference:
    IncrementalFileSystemPersistenceStore.java).  Old bases and their
    increment chains are pruned, keeping ``bases_to_keep`` chains."""

    def __init__(self, base_dir: str, bases_to_keep: int = 2):
        self.base_dir = base_dir
        self.bases_to_keep = bases_to_keep
        self._lock = threading.Lock()

    def _app_dir(self, app_name: str) -> str:
        return os.path.join(self.base_dir, app_name)

    def _entries(self, app_name: str) -> List[tuple]:
        """[(ts, revision, kind)] sorted by timestamp."""
        d = self._app_dir(app_name)
        try:
            names = os.listdir(d)
        except OSError:
            return []
        out = []
        for f in names:
            if f.endswith(".tmp"):
                continue
            if f.endswith(".base") or f.endswith(".inc"):
                rev, kind = f.rsplit(".", 1)
                try:
                    ts = int(rev.split("_", 1)[0])
                except ValueError:
                    continue
                out.append((ts, rev, kind))
        return sorted(out)

    def save(self, app_name: str, revision: str, kind: str, data: bytes):
        assert kind in ("base", "inc")
        with self._lock:
            d = self._app_dir(app_name)
            os.makedirs(d, exist_ok=True)
            tmp = os.path.join(d, f"{revision}.{kind}.tmp")
            # same crash-consistency contract as the full store: data
            # durable before the rename, rename durable via dir fsync
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(d, f"{revision}.{kind}"))
            fsync_dir(d)
            if kind == "base":
                self._prune(app_name)

    def _prune(self, app_name: str):
        entries = self._entries(app_name)
        base_ts = [ts for ts, _, kind in entries if kind == "base"]
        if len(base_ts) <= self.bases_to_keep:
            return
        cutoff = sorted(base_ts)[-self.bases_to_keep]
        d = self._app_dir(app_name)
        for ts, rev, kind in entries:
            if ts < cutoff:
                try:
                    os.remove(os.path.join(d, f"{rev}.{kind}"))
                except OSError:
                    pass

    def load_chain(self, app_name: str, until_revision: Optional[str] = None):
        with self._lock:
            entries = self._entries(app_name)
            if until_revision is not None:
                try:
                    limit = int(until_revision.split("_", 1)[0])
                except ValueError:
                    return None
                entries = [e for e in entries if e[0] <= limit]
            bases = [(ts, rev) for ts, rev, kind in entries if kind == "base"]
            if not bases:
                return None
            base_ts, base_rev = bases[-1]
            d = self._app_dir(app_name)
            with open(os.path.join(d, f"{base_rev}.base"), "rb") as f:
                base_bytes = f.read()
            incs = []
            for ts, rev, kind in entries:
                if kind == "inc" and ts > base_ts:
                    with open(os.path.join(d, f"{rev}.inc"), "rb") as f:
                        incs.append((rev, f.read()))
            return base_rev, base_bytes, incs

    def get_last_revision(self, app_name: str) -> Optional[str]:
        with self._lock:
            entries = self._entries(app_name)
            return entries[-1][1] if entries else None

    def clear_all_revisions(self, app_name: str):
        with self._lock:
            d = self._app_dir(app_name)
            try:
                names = os.listdir(d)
            except OSError:
                return  # already gone (or never created)
            for f in names:
                try:
                    os.remove(os.path.join(d, f))
                except OSError:
                    pass
