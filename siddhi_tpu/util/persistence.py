"""Persistence stores: where serialized app snapshots live.

Re-design of the reference ``util/persistence/``
(InMemoryPersistenceStore.java, FileSystemPersistenceStore.java,
PersistenceHelper.java): a store maps (app name, revision) -> bytes,
where revision = ``<epoch_ms>_<app name>`` so lexicographic-by-timestamp
ordering gives the latest revision.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional


class PersistenceStore:
    """SPI: save / load / last revision / clear for one app's snapshots."""

    def save(self, app_name: str, revision: str, snapshot: bytes):
        raise NotImplementedError

    def load(self, app_name: str, revision: str) -> Optional[bytes]:
        raise NotImplementedError

    def get_last_revision(self, app_name: str) -> Optional[str]:
        raise NotImplementedError

    def clear_all_revisions(self, app_name: str):
        raise NotImplementedError


class InMemoryPersistenceStore(PersistenceStore):
    """Keeps every revision in a process-local dict
    (reference: InMemoryPersistenceStore.java)."""

    def __init__(self):
        self._store: Dict[str, Dict[str, bytes]] = {}
        self._lock = threading.Lock()

    def save(self, app_name: str, revision: str, snapshot: bytes):
        with self._lock:
            self._store.setdefault(app_name, {})[revision] = snapshot

    def load(self, app_name: str, revision: str) -> Optional[bytes]:
        with self._lock:
            return self._store.get(app_name, {}).get(revision)

    def get_last_revision(self, app_name: str) -> Optional[str]:
        with self._lock:
            revs = self._store.get(app_name)
            if not revs:
                return None
            return max(revs, key=lambda r: int(r.split("_", 1)[0]))

    def clear_all_revisions(self, app_name: str):
        with self._lock:
            self._store.pop(app_name, None)


class FileSystemPersistenceStore(PersistenceStore):
    """One file per revision under ``<base>/<app>/<revision>``
    (reference: FileSystemPersistenceStore.java).  Keeps the newest
    ``revisions_to_keep`` files (reference default 3)."""

    def __init__(self, base_dir: str, revisions_to_keep: int = 3):
        self.base_dir = base_dir
        self.revisions_to_keep = revisions_to_keep
        self._lock = threading.Lock()

    def _app_dir(self, app_name: str) -> str:
        return os.path.join(self.base_dir, app_name)

    def _revisions(self, app_name: str) -> List[str]:
        d = self._app_dir(app_name)
        if not os.path.isdir(d):
            return []
        # .tmp files are crash leftovers from an interrupted save
        revs = [f for f in os.listdir(d) if "_" in f and not f.endswith(".tmp")]
        return sorted(revs, key=lambda r: int(r.split("_", 1)[0]))

    def save(self, app_name: str, revision: str, snapshot: bytes):
        with self._lock:
            d = self._app_dir(app_name)
            os.makedirs(d, exist_ok=True)
            tmp = os.path.join(d, revision + ".tmp")
            with open(tmp, "wb") as f:
                f.write(snapshot)
            os.replace(tmp, os.path.join(d, revision))
            # evict oldest beyond the keep count
            revs = self._revisions(app_name)
            for old in revs[: max(0, len(revs) - self.revisions_to_keep)]:
                try:
                    os.remove(os.path.join(d, old))
                except OSError:
                    pass

    def load(self, app_name: str, revision: str) -> Optional[bytes]:
        path = os.path.join(self._app_dir(app_name), revision)
        if not os.path.isfile(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def get_last_revision(self, app_name: str) -> Optional[str]:
        with self._lock:
            revs = self._revisions(app_name)
            return revs[-1] if revs else None

    def clear_all_revisions(self, app_name: str):
        with self._lock:
            d = self._app_dir(app_name)
            if not os.path.isdir(d):
                return
            for f in os.listdir(d):
                try:
                    os.remove(os.path.join(d, f))
                except OSError:
                    pass
