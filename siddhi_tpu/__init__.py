"""siddhi_tpu — a TPU-native streaming Complex Event Processing framework.

A ground-up re-design of the capabilities of the reference Siddhi engine
(YangGuang001/siddhi, Java) for TPU: SiddhiQL apps compile to dense tensor
programs over micro-batches of events; per-key state (window buffers,
aggregator accumulators, NFA active-state bitmasks) lives in sharded device
arrays advanced by jit-compiled step functions; scale-out rides
``jax.sharding`` meshes with XLA collectives.

Public API mirrors the reference surface (SiddhiManager /
SiddhiAppRuntime / InputHandler / callbacks) so a Siddhi user can switch.
"""

__version__ = "0.1.0"

from siddhi_tpu.compiler import SiddhiCompiler, SiddhiParserError


def __getattr__(name):
    # Lazy imports keep `import siddhi_tpu` light (no jax import cost) for
    # pure-compiler uses.
    if name in ("SiddhiManager",):
        from siddhi_tpu.core.manager import SiddhiManager

        return SiddhiManager
    if name in ("SiddhiAppRuntime",):
        from siddhi_tpu.core.app_runtime import SiddhiAppRuntime

        return SiddhiAppRuntime
    raise AttributeError(f"module 'siddhi_tpu' has no attribute {name!r}")
