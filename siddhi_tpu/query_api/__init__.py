"""Query object model (AST/IR) for SiddhiQL on TPU.

TPU-native re-design of the reference L0 layer
(``modules/siddhi-query-api/src/main/java/io/siddhi/query/api/``,
see /root/repo/SURVEY.md section 1, row L0).  Pure data: immutable-ish
dataclasses that the compiler produces and the planner consumes.
"""

from siddhi_tpu.query_api.attribute import Attribute, AttrType
from siddhi_tpu.query_api.annotation import Annotation
from siddhi_tpu.query_api.expression import (
    Expression,
    Constant,
    TimeConstant,
    Variable,
    FunctionCall,
    ArithmeticOp,
    CompareOp,
    AndOp,
    OrOp,
    NotOp,
    InOp,
    IsNull,
    IsNullStream,
)
from siddhi_tpu.query_api.definition import (
    AbstractDefinition,
    StreamDefinition,
    TableDefinition,
    WindowDefinition,
    TriggerDefinition,
    FunctionDefinition,
    AggregationDefinition,
)
from siddhi_tpu.query_api.execution import (
    InputStream,
    Query,
    Selector,
    OutputAttribute,
    OrderByAttribute,
    SingleInputStream,
    JoinInputStream,
    StateInputStream,
    StreamHandler,
    Filter,
    StreamFunction,
    WindowHandler,
    StateElement,
    StreamStateElement,
    AbsentStreamStateElement,
    CountStateElement,
    LogicalStateElement,
    NextStateElement,
    EveryStateElement,
    OutputStream,
    InsertIntoStream,
    ReturnStream,
    DeleteStream,
    UpdateStream,
    UpdateOrInsertStream,
    SetAttribute,
    OutputRate,
    EventOutputRate,
    TimeOutputRate,
    SnapshotOutputRate,
    Partition,
    PartitionType,
    ValuePartitionType,
    RangePartitionType,
    OnDemandQuery,
)
from siddhi_tpu.query_api.app import SiddhiApp
