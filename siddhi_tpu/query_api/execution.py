"""Execution elements: queries, input streams (single/join/state),
pattern/sequence state-element trees, output streams, rate limits,
partitions, and on-demand (store) queries.

Mirrors ``io.siddhi.query.api.execution.*`` (SURVEY.md §1 L0): the state
element tree here is what the planner lowers to the dense TPU NFA (the
reference instead walks it into a chain-of-processors NFA in
util/parser/StateInputStreamParser.java:73).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Union

from siddhi_tpu.query_api.annotation import Annotation
from siddhi_tpu.query_api.expression import Expression, FunctionCall, Variable


# ---------------------------------------------------------------------------
# Stream handlers (filters / stream functions / windows on a source)
# ---------------------------------------------------------------------------


class StreamHandler:
    __slots__ = ()


@dataclass
class Filter(StreamHandler):
    expression: Expression


@dataclass
class StreamFunction(StreamHandler):
    """``#ns:fn(args)`` stream processor call."""

    namespace: Optional[str]
    name: str
    args: tuple = ()


@dataclass
class WindowHandler(StreamHandler):
    """``#window.ns:fn(args)``."""

    namespace: Optional[str]
    name: str
    args: tuple = ()


# ---------------------------------------------------------------------------
# Input streams
# ---------------------------------------------------------------------------


class InputStream:
    __slots__ = ()


@dataclass
class SingleInputStream(InputStream):
    stream_id: str
    is_inner: bool = False
    is_fault: bool = False
    handlers: List[StreamHandler] = field(default_factory=list)
    alias: Optional[str] = None

    @property
    def window(self) -> Optional[WindowHandler]:
        for h in self.handlers:
            if isinstance(h, WindowHandler):
                return h
        return None

    @property
    def unique_id(self) -> str:
        return self.alias or self.stream_id


@dataclass
class AnonymousInputStream(InputStream):
    """``from (from X select ... return)`` inner query as a source."""

    query: "Query" = None


@dataclass
class JoinInputStream(InputStream):
    JOIN = "join"
    INNER_JOIN = "inner_join"
    LEFT_OUTER = "left_outer"
    RIGHT_OUTER = "right_outer"
    FULL_OUTER = "full_outer"

    left: SingleInputStream = None
    join_type: str = "join"
    right: SingleInputStream = None
    on_condition: Optional[Expression] = None
    # UNIDIRECTIONAL marker: 'left' | 'right' | None
    trigger: Optional[str] = None
    within: Optional[Expression] = None
    per: Optional[Expression] = None


# --- pattern / sequence state elements -------------------------------------


class StateElement:
    __slots__ = ()


@dataclass
class StreamStateElement(StateElement):
    """A single event-capturing state: ``e1=Stream[filter]``."""

    stream: SingleInputStream = None
    event_ref: Optional[str] = None  # e1
    within: Optional[int] = None  # ms (pattern-level withins pushed down)


@dataclass
class AbsentStreamStateElement(StreamStateElement):
    """``not Stream[filter] for 5 sec`` — absence detection."""

    waiting_time_ms: Optional[int] = None


@dataclass
class CountStateElement(StateElement):
    """``e=S[f]<2:5>`` (pattern count) or sequence ``*``/``+``/``?``."""

    ANY = -1

    stream_state: StreamStateElement = None
    min_count: int = 1
    max_count: int = 1  # ANY for unbounded


@dataclass
class LogicalStateElement(StateElement):
    """``A and B`` / ``A or B`` over two stream states."""

    element1: StateElement = None
    operator: str = "and"  # 'and' | 'or'
    element2: StateElement = None


@dataclass
class NextStateElement(StateElement):
    """Pattern ``A -> B`` or sequence ``A , B``."""

    element: StateElement = None
    next: StateElement = None


@dataclass
class EveryStateElement(StateElement):
    """``every (A -> B)`` — re-arming start state."""

    element: StateElement = None


@dataclass
class StateInputStream(InputStream):
    PATTERN = "pattern"
    SEQUENCE = "sequence"

    type: str = PATTERN
    state: StateElement = None
    within_ms: Optional[int] = None

    def stream_ids(self) -> List[str]:
        out: List[str] = []

        def walk(e: StateElement):
            if isinstance(e, StreamStateElement):
                out.append(e.stream.stream_id)
            elif isinstance(e, CountStateElement):
                walk(e.stream_state)
            elif isinstance(e, LogicalStateElement):
                walk(e.element1)
                walk(e.element2)
            elif isinstance(e, NextStateElement):
                walk(e.element)
                walk(e.next)
            elif isinstance(e, EveryStateElement):
                walk(e.element)

        walk(self.state)
        return out


# ---------------------------------------------------------------------------
# Selection
# ---------------------------------------------------------------------------


@dataclass
class OutputAttribute:
    expression: Expression
    rename: Optional[str] = None

    @property
    def name(self) -> str:
        if self.rename:
            return self.rename
        e = self.expression
        if isinstance(e, Variable):
            return e.attribute
        raise ValueError(f"output attribute needs 'as' rename: {e}")


@dataclass
class OrderByAttribute:
    variable: Variable
    ascending: bool = True


@dataclass
class Selector:
    # None means `select *`
    selection: Optional[List[OutputAttribute]] = None
    group_by: List[Variable] = field(default_factory=list)
    having: Optional[Expression] = None
    order_by: List[OrderByAttribute] = field(default_factory=list)
    limit: Optional[Expression] = None
    offset: Optional[Expression] = None

    @property
    def is_select_all(self) -> bool:
        return self.selection is None


# ---------------------------------------------------------------------------
# Output streams & rate limiting
# ---------------------------------------------------------------------------


class OutputStream:
    __slots__ = ()


@dataclass
class InsertIntoStream(OutputStream):
    target: str = ""
    # which events flow out: 'current' | 'expired' | 'all'
    event_type: str = "current"
    is_inner: bool = False
    is_fault: bool = False


@dataclass
class ReturnStream(OutputStream):
    event_type: str = "current"


@dataclass
class SetAttribute:
    variable: Variable
    expression: Expression


@dataclass
class DeleteStream(OutputStream):
    target: str = ""
    event_type: str = "current"
    on_condition: Optional[Expression] = None


@dataclass
class UpdateStream(OutputStream):
    target: str = ""
    event_type: str = "current"
    set_clause: Optional[List[SetAttribute]] = None
    on_condition: Optional[Expression] = None


@dataclass
class UpdateOrInsertStream(OutputStream):
    target: str = ""
    event_type: str = "current"
    set_clause: Optional[List[SetAttribute]] = None
    on_condition: Optional[Expression] = None


class OutputRate:
    __slots__ = ()


@dataclass
class EventOutputRate(OutputRate):
    events: int = 1
    type: str = "all"  # all | first | last


@dataclass
class TimeOutputRate(OutputRate):
    value_ms: int = 0
    type: str = "all"


@dataclass
class SnapshotOutputRate(OutputRate):
    value_ms: int = 0


# ---------------------------------------------------------------------------
# Query / partition / on-demand query
# ---------------------------------------------------------------------------


@dataclass
class Query:
    input_stream: InputStream = None
    selector: Selector = field(default_factory=Selector)
    output_stream: OutputStream = None
    output_rate: Optional[OutputRate] = None
    annotations: List[Annotation] = field(default_factory=list)


class PartitionType:
    __slots__ = ()


@dataclass
class ValuePartitionType(PartitionType):
    stream_id: str = ""
    expression: Expression = None


@dataclass
class RangePartitionType(PartitionType):
    stream_id: str = ""
    # ordered (condition, label) pairs
    ranges: List[Tuple[Expression, str]] = field(default_factory=list)


@dataclass
class Partition:
    partition_types: List[PartitionType] = field(default_factory=list)
    queries: List[Query] = field(default_factory=list)
    annotations: List[Annotation] = field(default_factory=list)


@dataclass
class OnDemandQuery:
    """Pull query against a table / window / aggregation
    (reference: query/OnDemandQueryRuntime.java, SiddhiCompiler.parseOnDemandQuery).
    """

    # FIND | INSERT | DELETE | UPDATE | UPDATE_OR_INSERT
    type: str = "find"
    input_store: Optional[str] = None
    input_alias: Optional[str] = None
    on_condition: Optional[Expression] = None
    within: Optional[Tuple[Expression, Optional[Expression]]] = None
    per: Optional[Expression] = None
    selector: Selector = field(default_factory=Selector)
    output_stream: Optional[OutputStream] = None
