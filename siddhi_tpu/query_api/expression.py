"""Expression AST.

Mirrors ``io.siddhi.query.api.expression`` (Expression/Variable/constant/
condition/math trees).  Unlike the reference — which lowers these to ~155
per-type executor classes (reference: core/executor/, SURVEY.md §2.2) — the
TPU build compiles one expression tree into a single vectorized columnar
evaluator (numpy on host, jax.numpy under jit), so no per-type class
explosion is needed: dtype dispatch is handled by the array library.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Union

from siddhi_tpu.query_api.attribute import AttrType


class Expression:
    """Base class for all expression nodes."""

    __slots__ = ()


@dataclass(frozen=True)
class Constant(Expression):
    value: object
    type: AttrType


@dataclass(frozen=True)
class TimeConstant(Expression):
    """A time literal like ``5 sec``; value is milliseconds (long)."""

    value: int

    @property
    def type(self) -> AttrType:
        return AttrType.LONG


@dataclass(frozen=True)
class Variable(Expression):
    """Attribute reference: ``attr``, ``Stream.attr``, ``e1[2].attr``,
    ``#innerStream.attr``, ``!faultStream.attr``."""

    attribute: str
    stream_id: Optional[str] = None
    # index into a pattern event collection, e.g. e1[0].price; LAST = -1,
    # LAST - k = -(k+1)
    stream_index: Optional[int] = None
    is_inner: bool = False
    is_fault: bool = False
    # second-level reference for on-demand queries over named windows
    function_id: Optional[str] = None


@dataclass(frozen=True)
class FunctionCall(Expression):
    """``ns:fn(arg, ...)`` — builtins, UDFs, window/stream processors."""

    namespace: Optional[str]
    name: str
    args: tuple = ()
    # True when the call was written as fn(*)
    star: bool = False


@dataclass(frozen=True)
class ArithmeticOp(Expression):
    op: str  # '+', '-', '*', '/', '%'
    left: Expression
    right: Expression


@dataclass(frozen=True)
class CompareOp(Expression):
    op: str  # '<', '<=', '>', '>=', '==', '!='
    left: Expression
    right: Expression


@dataclass(frozen=True)
class AndOp(Expression):
    left: Expression
    right: Expression


@dataclass(frozen=True)
class OrOp(Expression):
    left: Expression
    right: Expression


@dataclass(frozen=True)
class NotOp(Expression):
    expr: Expression


@dataclass(frozen=True)
class InOp(Expression):
    """``expr IN TableName`` membership test."""

    expr: Expression
    source_id: str


@dataclass(frozen=True)
class IsNull(Expression):
    expr: Expression


@dataclass(frozen=True)
class IsNullStream(Expression):
    """``e1 IS NULL`` / ``e1[1] IS NULL`` over a pattern event slot."""

    stream_id: str
    stream_index: Optional[int] = None
    is_inner: bool = False
    is_fault: bool = False
