"""Attribute types for stream/table schemas.

Mirrors the reference type system
(``io.siddhi.query.api.definition.Attribute.Type``): STRING, INT, LONG,
FLOAT, DOUBLE, BOOL, OBJECT.  On TPU, numeric types map to device dtypes
(int32/int64/float32/float64) while STRING/OBJECT stay host-side (string
keys are interned to int64 ids when used for partitioning/group-by).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import numpy as np


class AttrType(enum.Enum):
    STRING = "string"
    INT = "int"
    LONG = "long"
    FLOAT = "float"
    DOUBLE = "double"
    BOOL = "bool"
    OBJECT = "object"

    @property
    def is_numeric(self) -> bool:
        return self in (AttrType.INT, AttrType.LONG, AttrType.FLOAT, AttrType.DOUBLE)

    @property
    def np_dtype(self):
        return _NP_DTYPES[self]


_NP_DTYPES = {
    AttrType.STRING: np.dtype(object),
    AttrType.INT: np.dtype(np.int32),
    AttrType.LONG: np.dtype(np.int64),
    AttrType.FLOAT: np.dtype(np.float32),
    AttrType.DOUBLE: np.dtype(np.float64),
    AttrType.BOOL: np.dtype(np.bool_),
    AttrType.OBJECT: np.dtype(object),
}

# Numeric promotion lattice used by arithmetic type inference, mirroring the
# per-type executor selection of the reference ExpressionParser
# (reference: util/parser/ExpressionParser.java:207).
_PROMOTION_ORDER = [AttrType.INT, AttrType.LONG, AttrType.FLOAT, AttrType.DOUBLE]


def promote(a: AttrType, b: AttrType) -> AttrType:
    """Binary arithmetic result type (int < long < float < double)."""
    if not (a.is_numeric and b.is_numeric):
        raise TypeError(f"cannot promote non-numeric types {a} and {b}")
    return _PROMOTION_ORDER[max(_PROMOTION_ORDER.index(a), _PROMOTION_ORDER.index(b))]


@dataclass(frozen=True)
class Attribute:
    name: str
    type: AttrType

    def __repr__(self) -> str:
        return f"{self.name} {self.type.value}"
