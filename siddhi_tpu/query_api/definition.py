"""Definitions: stream / table / window / trigger / function / aggregation.

Mirrors ``io.siddhi.query.api.definition.*`` (SURVEY.md §1 L0).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from siddhi_tpu.query_api.annotation import Annotation
from siddhi_tpu.query_api.attribute import Attribute, AttrType
from siddhi_tpu.query_api.expression import Expression, FunctionCall


@dataclass
class AbstractDefinition:
    id: str
    attributes: List[Attribute] = field(default_factory=list)
    annotations: List[Annotation] = field(default_factory=list)

    @property
    def attribute_names(self) -> List[str]:
        return [a.name for a in self.attributes]

    def attribute_type(self, name: str) -> AttrType:
        for a in self.attributes:
            if a.name == name:
                return a.type
        raise KeyError(f"attribute '{name}' not in definition '{self.id}'")

    def attribute_position(self, name: str) -> int:
        for i, a in enumerate(self.attributes):
            if a.name == name:
                return i
        raise KeyError(f"attribute '{name}' not in definition '{self.id}'")


@dataclass
class StreamDefinition(AbstractDefinition):
    pass


@dataclass
class TableDefinition(AbstractDefinition):
    pass


@dataclass
class WindowDefinition(AbstractDefinition):
    """``define window W (a int) length(5) output all events``."""

    window_function: Optional[FunctionCall] = None
    # reference default: ALL events (WindowDefinition.java:40)
    output_event_type: str = "all"  # current | expired | all


@dataclass
class TriggerDefinition(AbstractDefinition):
    """``define trigger T at every 5 sec | 'cron-expr' | 'start'``.

    Trigger streams carry one attribute: triggered_time (long).
    """

    at_every_ms: Optional[int] = None
    at_cron: Optional[str] = None
    at_start: bool = False

    def __post_init__(self):
        if not self.attributes:
            self.attributes = [Attribute("triggered_time", AttrType.LONG)]


@dataclass
class FunctionDefinition(AbstractDefinition):
    """``define function f[lang] return type { body }`` (script UDF)."""

    language: str = "python"
    return_type: AttrType = AttrType.OBJECT
    body: str = ""


@dataclass
class AggregationDefinition(AbstractDefinition):
    """``define aggregation A from S select ... group by ... aggregate by ts
    every sec ... year`` (reference: aggregation/AggregationRuntime.java:81).

    ``durations`` is an ordered list of duration names among
    seconds/minutes/hours/days/weeks/months/years.
    """

    input_stream: object = None  # SingleInputStream
    selector: object = None  # Selector
    aggregate_by: Optional[str] = None  # attribute name (timestamp source)
    durations: List[str] = field(default_factory=list)
