"""Annotations: ``@name(key='value', ...)`` attached to definitions/queries.

Mirrors ``io.siddhi.query.api.annotation.Annotation``.  Elements with no
key (positional values) are stored under ascending integer-string keys in
``elements`` order, matching the reference behavior of `@store('a','b')`.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple


@dataclass
class Annotation:
    name: str
    # ordered (key-or-None, value) pairs
    elements: List[Tuple[Optional[str], str]] = field(default_factory=list)
    annotations: List["Annotation"] = field(default_factory=list)

    def element(self, key: Optional[str] = None, default: Optional[str] = None) -> Optional[str]:
        """Value for `key`; with key=None, the first keyless element."""
        for k, v in self.elements:
            if k is None and key is None:
                return v
            if k is not None and key is not None and k.lower() == key.lower():
                return v
        return default

    def values(self) -> List[str]:
        return [v for _, v in self.elements]

    def nested(self, name: str) -> Optional["Annotation"]:
        for a in self.annotations:
            if a.name.lower() == name.lower():
                return a
        return None


def find_annotation(annotations: List[Annotation], name: str) -> Optional[Annotation]:
    for a in annotations:
        if a.name.lower() == name.lower():
            return a
    return None
