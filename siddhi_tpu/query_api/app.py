"""SiddhiApp: top-level container of definitions + execution elements.

Mirrors ``io.siddhi.query.api.SiddhiApp`` (SiddhiApp.java:1-375) including
the duplicate-definition checks, but as a plain dataclass the planner
consumes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Union

from siddhi_tpu.query_api.annotation import Annotation
from siddhi_tpu.query_api.definition import (
    AggregationDefinition,
    FunctionDefinition,
    StreamDefinition,
    TableDefinition,
    TriggerDefinition,
    WindowDefinition,
)
from siddhi_tpu.query_api.execution import Partition, Query


from siddhi_tpu.core.exceptions import SiddhiAppValidationError


class DuplicateDefinitionError(SiddhiAppValidationError):
    """reference: DuplicateDefinitionException extends
    SiddhiAppValidationException (extends SiddhiAppCreationException) —
    so callers catching creation errors see duplicates too."""


@dataclass
class SiddhiApp:
    annotations: List[Annotation] = field(default_factory=list)
    stream_definitions: Dict[str, StreamDefinition] = field(default_factory=dict)
    table_definitions: Dict[str, TableDefinition] = field(default_factory=dict)
    window_definitions: Dict[str, WindowDefinition] = field(default_factory=dict)
    trigger_definitions: Dict[str, TriggerDefinition] = field(default_factory=dict)
    function_definitions: Dict[str, FunctionDefinition] = field(default_factory=dict)
    aggregation_definitions: Dict[str, AggregationDefinition] = field(default_factory=dict)
    execution_elements: List[Union[Query, Partition]] = field(default_factory=list)

    def _check_unique(self, id: str):
        for group in (
            self.stream_definitions,
            self.table_definitions,
            self.window_definitions,
            self.trigger_definitions,
            self.aggregation_definitions,
        ):
            if id in group:
                raise DuplicateDefinitionError(f"'{id}' is already defined")

    def define_stream(self, d: StreamDefinition) -> "SiddhiApp":
        # Re-defining an identical stream is legal in the reference; schema
        # mismatch is an error.
        if d.id in self.stream_definitions:
            old = self.stream_definitions[d.id]
            if old.attributes != d.attributes:
                raise DuplicateDefinitionError(
                    f"stream '{d.id}' re-defined with a different schema"
                )
            return self
        self._check_unique(d.id)
        self.stream_definitions[d.id] = d
        return self

    def define_table(self, d: TableDefinition) -> "SiddhiApp":
        self._check_unique(d.id)
        self.table_definitions[d.id] = d
        return self

    def define_window(self, d: WindowDefinition) -> "SiddhiApp":
        self._check_unique(d.id)
        self.window_definitions[d.id] = d
        return self

    def define_trigger(self, d: TriggerDefinition) -> "SiddhiApp":
        self._check_unique(d.id)
        self.trigger_definitions[d.id] = d
        # a trigger implicitly defines a stream of the same name carrying
        # `triggered_time long` (reference SiddhiApp.defineTrigger behavior)
        self.stream_definitions[d.id] = StreamDefinition(
            id=d.id, attributes=list(d.attributes), annotations=list(d.annotations)
        )
        return self

    def define_function(self, d: FunctionDefinition) -> "SiddhiApp":
        if d.id in self.function_definitions:
            raise DuplicateDefinitionError(f"function '{d.id}' is already defined")
        self.function_definitions[d.id] = d
        return self

    def define_aggregation(self, d: AggregationDefinition) -> "SiddhiApp":
        self._check_unique(d.id)
        self.aggregation_definitions[d.id] = d
        return self

    def add_query(self, q: Query) -> "SiddhiApp":
        self.execution_elements.append(q)
        return self

    def add_partition(self, p: Partition) -> "SiddhiApp":
        self.execution_elements.append(p)
        return self

    @property
    def queries(self) -> List[Query]:
        return [e for e in self.execution_elements if isinstance(e, Query)]
