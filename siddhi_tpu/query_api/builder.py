"""Fluent builder API for constructing apps programmatically.

Mirrors the reference's L0 fluent surface (SURVEY.md §1:
``SiddhiApp.siddhiApp(...)``, ``StreamDefinition.id(...).attribute(...)``,
``Query.query().from_(...).select(...).insertInto(...)``,
``Expression.value/variable/compare`` — SiddhiApp.java:1-375,
query/api/expression/Expression.java) so apps can be built without
SiddhiQL strings::

    from siddhi_tpu.query_api import builder as b

    app = (b.siddhi_app("demo")
           .define_stream(b.stream("S").attribute("sym", AttrType.STRING)
                                        .attribute("v", AttrType.LONG))
           .add_query(b.query("q1")
                      .from_stream("S", where=b.compare(b.var("v"), ">", b.value(10)))
                      .select(("sym", b.var("sym")), ("v", b.var("v")))
                      .insert_into("Out")))

The produced objects are the ordinary query-api dataclasses; pass the
app to ``SiddhiManager.create_siddhi_app_runtime``.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

from siddhi_tpu.query_api.annotation import Annotation
from siddhi_tpu.query_api.app import SiddhiApp
from siddhi_tpu.query_api.attribute import Attribute, AttrType
from siddhi_tpu.query_api.definition import StreamDefinition, TableDefinition
from siddhi_tpu.query_api.execution import (
    Filter,
    InsertIntoStream,
    OutputAttribute,
    Query,
    Selector,
    SingleInputStream,
    WindowHandler,
)
from siddhi_tpu.query_api.expression import (
    AndOp,
    ArithmeticOp,
    CompareOp,
    Constant,
    Expression,
    FunctionCall,
    NotOp,
    OrOp,
    Variable,
)

_PY_TYPES = {
    bool: AttrType.BOOL,
    int: AttrType.LONG,
    float: AttrType.DOUBLE,
    str: AttrType.STRING,
}


# -- expressions (reference: Expression.value/variable/compare/and/or/not) ---


def value(v) -> Constant:
    t = _PY_TYPES.get(type(v), AttrType.OBJECT)
    return Constant(v, t)


def var(attribute: str, of: Optional[str] = None) -> Variable:
    return Variable(attribute=attribute, stream_id=of)


def compare(left: Expression, op: str, right: Expression) -> CompareOp:
    return CompareOp(op, left, right)


def and_(left: Expression, right: Expression) -> AndOp:
    return AndOp(left, right)


def or_(left: Expression, right: Expression) -> OrOp:
    return OrOp(left, right)


def not_(e: Expression) -> NotOp:
    return NotOp(e)


def function(name: str, *args: Expression, namespace: Optional[str] = None) -> FunctionCall:
    return FunctionCall(namespace, name, tuple(args))


def add(left: Expression, right: Expression) -> ArithmeticOp:
    return ArithmeticOp("+", left, right)


def subtract(left: Expression, right: Expression) -> ArithmeticOp:
    return ArithmeticOp("-", left, right)


def multiply(left: Expression, right: Expression) -> ArithmeticOp:
    return ArithmeticOp("*", left, right)


def divide(left: Expression, right: Expression) -> ArithmeticOp:
    return ArithmeticOp("/", left, right)


# -- definitions -------------------------------------------------------------


class _DefinitionBuilder:
    _cls = StreamDefinition

    def __init__(self, id: str):
        self._d = self._cls(id)

    def attribute(self, name: str, type: AttrType):
        self._d.attributes.append(Attribute(name, type))
        return self

    def annotation(self, ann: Annotation):
        self._d.annotations.append(ann)
        return self

    def build(self):
        return self._d


class stream(_DefinitionBuilder):
    """``StreamDefinition.id(x).attribute(...)`` analog."""

    _cls = StreamDefinition


class table(_DefinitionBuilder):
    """``TableDefinition.id(x).attribute(...)`` analog."""

    _cls = TableDefinition


# -- queries -----------------------------------------------------------------


class query:
    """``Query.query().from(...).select(...).insertInto(...)`` analog."""

    def __init__(self, name: Optional[str] = None):
        self._q = Query()
        if name:
            self._q.annotations.append(Annotation("info", [("name", name)]))

    def from_stream(self, stream_id: str, where: Optional[Expression] = None,
                    window: Optional[Tuple[str, Sequence[Expression]]] = None,
                    alias: Optional[str] = None) -> "query":
        handlers = []
        if where is not None:
            handlers.append(Filter(where))
        if window is not None:
            w_name, w_args = window
            handlers.append(WindowHandler(None, w_name, tuple(w_args)))
        self._q.input_stream = SingleInputStream(
            stream_id, handlers=handlers, alias=alias)
        return self

    def select(self, *items: Union[str, Tuple[str, Expression]]) -> "query":
        sel = []
        for item in items:
            if isinstance(item, str):
                sel.append(OutputAttribute(Variable(attribute=item)))
            else:
                name, expr = item
                sel.append(OutputAttribute(expr, rename=name))
        self._q.selector.selection = sel
        return self

    def group_by(self, *attrs: str) -> "query":
        self._q.selector.group_by = [Variable(attribute=a) for a in attrs]
        return self

    def having(self, condition: Expression) -> "query":
        self._q.selector.having = condition
        return self

    def insert_into(self, target: str, event_type: str = "current") -> "query":
        self._q.output_stream = InsertIntoStream(target, event_type)
        return self

    # Java-style aliases
    insertInto = insert_into
    groupBy = group_by

    def build(self) -> Query:
        return self._q


# -- app ---------------------------------------------------------------------


class siddhi_app:
    """``SiddhiApp.siddhiApp(name)`` analog."""

    def __init__(self, name: Optional[str] = None):
        self._app = SiddhiApp()
        if name:
            self._app.annotations.append(Annotation("app:name", [(None, name)]))

    def define_stream(self, d: Union[stream, StreamDefinition]) -> "siddhi_app":
        self._app.define_stream(d.build() if isinstance(d, stream) else d)
        return self

    def define_table(self, d: Union[table, TableDefinition]) -> "siddhi_app":
        self._app.define_table(d.build() if isinstance(d, table) else d)
        return self

    def add_query(self, q: Union[query, Query]) -> "siddhi_app":
        self._app.add_query(q.build() if isinstance(q, query) else q)
        return self

    # Java-style aliases
    defineStream = define_stream
    defineTable = define_table
    addQuery = add_query

    def build(self) -> SiddhiApp:
        return self._app
