"""Sink SPI: publishing stream output to external transports.

Re-design of the reference ``stream/output/sink/`` (Sink.java:59 —
publish :174/:243, connectWithRetry :276 with BackoffRetryCounter,
onError :354; SinkMapper event -> payload; InMemorySink, LogSink;
distributed/ multi-endpoint strategies): a sink subscribes to its
stream's junction, maps each event batch to payloads, and publishes.
Publish failures route through ``on_error`` (drop + log, or raise into
the junction's @OnError handling).
"""

from __future__ import annotations

import logging
import re
from typing import Dict, List, Optional

import numpy as np

from siddhi_tpu.core.event import (
    Event,
    EventBatch,
    batch_from_events,
    events_from_batch,
)
from siddhi_tpu.core.exceptions import (
    ConnectionUnavailableError,
    InjectedFaultError,
    SiddhiAppRuntimeError,
)
from siddhi_tpu.extension.registry import extension
from siddhi_tpu.transport.broker import InMemoryBroker
from siddhi_tpu.transport.retry import ConnectRetryMixin

# '{{attr}}' dynamic-option placeholders (reference: util/transport/
# Option + TemplateBuilder)
_TEMPLATE_RE = re.compile(r"\{\{(\w+)\}\}")

log = logging.getLogger(__name__)


class SinkMapper:
    """events -> transport payloads (reference: SinkMapper.java)."""

    def init(self, definition, options: Dict[str, str]):
        self.definition = definition
        self.options = options

    def map(self, events: List[Event]) -> List:
        raise NotImplementedError


@extension("sink_mapper", "passThrough")
class PassThroughSinkMapper(SinkMapper):
    def map(self, events: List[Event]) -> List:
        return list(events)


@extension("sink_mapper", "json")
class JsonSinkMapper(SinkMapper):
    """One JSON object string per event (attribute name -> value); the
    stdlib stand-in for siddhi-map-json."""

    def map(self, events: List[Event]) -> List:
        import json

        names = self.definition.attribute_names

        def clean(v):
            if isinstance(v, np.generic):
                return v.item()
            return v

        return [
            json.dumps({nm: clean(v) for nm, v in zip(names, e.data)})
            for e in events
        ]


class Sink(ConnectRetryMixin):
    """Transport publisher SPI (reference: Sink.java:59)."""

    def init(self, definition, options: Dict[str, str], mapper: SinkMapper, app_context):
        import threading

        self.definition = definition
        self.options = options
        self.mapper = mapper
        self.app_context = app_context
        self.connected = False
        # @app:faults harness: arms the sink.connect / sink.publish
        # injection sites (None when chaos testing is off)
        self._fault_injector = getattr(app_context, "fault_injector", None)
        self._fault_site_connect = "sink.connect"
        # wired by the planner: the stream's junction, consulted for
        # the @OnError publish-failure contract
        self.stream_junction = None
        # per-THREAD dynamic-option context: sync junctions deliver on
        # the caller's thread, so two senders may traverse one sink
        # concurrently — instance state would cross their topics
        self._tls = threading.local()
        self._init_retry(options)
        # open-breaker spool (robustness/breaker.py): batches held while
        # the circuit is open, flushed once it closes.  The output
        # ledger counted them at junction dispatch, so a crash-replay
        # suppresses them and the flush never double-emits.
        self._spool = None
        self._spool_cap = 0
        # REENTRANT: a flush publishing through a half-open breaker
        # closes it via record_success(), and publish_with_reconnect
        # then re-enters _flush_spool on the same thread — a plain
        # Lock self-deadlocks on that path (the nested flush drains
        # whatever remains and the outer loop exits on empty)
        self._spool_lock = threading.RLock()

    def attach_breaker(self, breaker, spool_cap: int = 1024):
        """Planner hook: install the circuit breaker and its bounded
        open-state spool (@app:limits(breaker='N'))."""
        from collections import deque

        self._breaker = breaker
        self._spool_cap = int(spool_cap)
        self._spool = deque(maxlen=self._spool_cap)

    # -- SPI ---------------------------------------------------------------

    def connect(self):
        pass

    def disconnect(self):
        pass

    def publish(self, payload):
        raise NotImplementedError

    # -- lifecycle ---------------------------------------------------------
    # start/_connect_with_retry/_retry_connect come from ConnectRetryMixin

    def shutdown(self):
        self._shutdown_retry()
        if self._spool and self.connected and (
                self._breaker is None or self._breaker.allow()):
            # final barrier flush: the transport is still up and the
            # breaker admits a delivery (closed, or open past cooldown
            # — allow() flips it to a half-open probe and the first
            # publish closes it), so the batches spooled during the
            # last open window can still go out in order — shutting
            # down without this drain strands them behind the barrier
            # (the loss warning below then fires for events that were
            # perfectly deliverable); a deny leaves the spool for the
            # warning, respecting the open circuit
            self._flush_spool()
        if self._spool:
            # ledger-counted as delivered at junction dispatch, so a
            # replay will NOT re-emit them: the exactly-once discipline
            # errs on at-most-once for the spool — make the loss loud
            log.warning(
                "sink %s on stream '%s' shutting down with %d batch(es) "
                "still spooled behind an open breaker",
                type(self).__name__, self.definition.id, len(self._spool))
        if self.connected:
            self.disconnect()
            # the retry thread writes `connected` under _retry_lock;
            # the main-path clear takes the same lock
            with self._retry_lock:
                self.connected = False

    # -- junction-facing ---------------------------------------------------

    def _intercepted_events(self, batch: EventBatch):
        """Batch -> events, passed through the optional SinkHandler."""
        events = events_from_batch(batch)
        hook = getattr(self, "handler", None)
        if hook is not None:
            events = hook.on_events(events)
        return events

    def send_batch(self, batch: EventBatch):
        b = self._breaker
        if b is not None:
            if not b.allow():
                # circuit open: hold the batch instead of burning a
                # publish attempt per event (short-circuit is counted
                # by the breaker)
                self._spool_batch(batch)
                return
            if self._spool:
                # breaker closed with spooled history: drain it FIRST
                # so external observers see the original order
                self._flush_spool()
        events = self._intercepted_events(batch)
        if not events:
            return
        payloads = self.mapper.map(events)
        if len(payloads) == len(events):
            # 1:1 mappers carry per-event context for dynamic options
            # ('{{attr}}' templates, reference: util/transport/Option +
            # TemplateBuilder — e.g. @sink(topic='{{symbol}}'))
            for e, payload in zip(events, payloads):
                self._tls.event = e
                try:
                    self.publish_with_reconnect(payload)
                finally:
                    self._tls.event = None
        else:
            for payload in payloads:
                self.publish_with_reconnect(payload)

    def resolve_option(self, name: str, default: Optional[str] = None):
        """Option value with '{{attr}}' placeholders substituted from
        the event being published (static values pass through)."""
        v = self.options.get(name, default)
        if v is None or "{{" not in v:
            return v
        e = getattr(self._tls, "event", None)
        names = self.definition.attribute_names

        def sub(m):
            attr = m.group(1)
            if e is None or attr not in names:
                raise SiddhiAppRuntimeError(
                    f"sink option '{name}': cannot resolve "
                    f"'{{{{{attr}}}}}' (no per-event context or unknown "
                    "attribute)")
            return str(e.data[names.index(attr)])

        return _TEMPLATE_RE.sub(sub, v)

    def publish_with_reconnect(self, payload):
        """Publish one payload; on connection failure route to
        ``on_error`` and kick off the single reconnect chain."""
        if not self.connected:
            if self._breaker is not None:
                # disconnected publishes count as breaker failures: once
                # the threshold trips, later batches spool in send_batch
                # instead of dropping through on_error one by one
                self._breaker.record_failure()
            self.on_error(payload, ConnectionUnavailableError("not connected"))
            return
        try:
            fi = self._fault_injector
            if fi is not None:
                fi.check("sink.publish")
            self.publish(payload)
            if self._breaker is not None and self._breaker.record_success():
                # the half-open probe just succeeded through the PUBLISH
                # path — flush whatever spooled while the circuit was open
                self._flush_spool()
        except ConnectionUnavailableError as e:
            # the retry thread writes `connected` under _retry_lock;
            # the main-path clear takes the same lock
            with self._retry_lock:
                self.connected = False
            if self._breaker is not None:
                self._breaker.record_failure()
            self.on_error(payload, e)
            self._connect_with_retry()
        except InjectedFaultError as e:
            # injected sink failure: the event routes through the same
            # @OnError contract a real publish error would use
            self.on_error(payload, e)

    # -- circuit breaker ----------------------------------------------------

    def _spool_batch(self, batch: EventBatch):
        """Hold a batch while the circuit is open.  The deque is
        bounded (attach_breaker); on overflow the OLDEST batch is
        evicted and its events counted as spool drops — under overload
        the freshest output survives, matching the junction's drop
        discipline."""
        sp = self._spool
        stats = getattr(self.app_context, "robustness", None)
        with self._spool_lock:
            if len(sp) == sp.maxlen:
                evicted = sp[0]  # appending below auto-evicts it
                if stats is not None:
                    stats.breaker_spool_dropped += len(evicted)
                log.warning(
                    "sink %s on stream '%s': open-breaker spool full "
                    "(%d batches) — dropping oldest %d event(s)",
                    type(self).__name__, self.definition.id, sp.maxlen,
                    len(evicted))
            sp.append(batch)
        if stats is not None:
            stats.breaker_spooled_batches += 1

    def _on_breaker_closed(self):
        """Mixin hook: a successful CONNECT closed the breaker."""
        self._flush_spool()

    def _flush_spool(self):
        """Publish everything spooled while the circuit was open, in
        order.  Events were already counted by the output ledger at
        junction dispatch, so this goes straight through the publish
        path — never back through ``SinkStreamCallback.receive`` —
        and a replay can never double-emit them.  If the breaker
        re-opens mid-flush the remainder stays spooled for the next
        close; the batch in flight routes its failures through
        ``on_error`` like any other publish."""
        sp = self._spool
        if not sp:
            return
        stats = getattr(self.app_context, "robustness", None)
        with self._spool_lock:
            while sp:
                if self._breaker is not None and self._breaker.is_open():
                    break
                batch = sp.popleft()
                if stats is not None:
                    stats.breaker_flushed_batches += 1
                events = self._intercepted_events(batch)
                if not events:
                    continue
                payloads = self.mapper.map(events)
                if len(payloads) == len(events):
                    for e, payload in zip(events, payloads):
                        self._tls.event = e
                        try:
                            self.publish_with_reconnect(payload)
                        finally:
                            self._tls.event = None
                else:
                    for payload in payloads:
                        self.publish_with_reconnect(payload)

    def _on_retry_exhausted(self, e: Exception):
        """retry.max.attempts ran out: the sink is marked failed
        (``self.failed``, set by the mixin) and the exhaustion surfaces
        through the OnError/exception-listener machinery instead of
        silently ending the timer chain."""
        log.error(
            "sink %s on stream '%s' marked FAILED after %d reconnect "
            "attempts: %s", type(self).__name__, self.definition.id,
            self._retry_attempts, e)
        j = self.stream_junction
        ev_ = getattr(self._tls, "event", None)
        if (j is not None and ev_ is not None
                and j.fault_junction is not None
                and j.route_fault(batch_from_events(self.definition, [ev_]),
                                  e)):
            return
        ac = getattr(self, "app_context", None)
        for ln in list(getattr(ac, "exception_listeners", None) or []):
            try:
                ln(e)
            except Exception:
                log.exception("exception listener failed")

    def on_error(self, payload, e: Exception):
        """Publish-failure hook (reference Sink.onError:354): when the
        sink's stream declares @OnError(action='STREAM'), the failing
        EVENT routes into its '!stream' fault junction with the error
        attached; otherwise log and drop."""
        j = self.stream_junction
        ev_ = getattr(self._tls, "event", None)
        if j is not None and ev_ is not None and j.fault_junction is not None:
            if j.route_fault(batch_from_events(self.definition, [ev_]), e):
                return
        log.error(
            "sink %s on stream '%s' failed to publish: %s",
            type(self).__name__, self.definition.id, e,
        )


class SinkStreamCallback:
    """Junction subscriber adapting batches into a Sink.

    ``ledger_key`` (set by the planner) identifies this sink endpoint in
    the crash-recovery output ledger: during restore-and-replay the
    journal suppresses the prefix of events the sink already published
    before the crash, so external observers see each event exactly
    once."""

    def __init__(self, sink: Sink):
        self.sink = sink
        self.ledger_key = None

    def receive(self, batch: EventBatch):
        jr = getattr(self.sink.app_context, "input_journal", None)
        if jr is not None and self.ledger_key is not None:
            batch = jr.deliver(self.ledger_key, batch)
            if batch is None:
                return
        self.sink.send_batch(batch)


@extension("sink", "inMemory")
class InMemorySink(Sink):
    """Publishes payloads to an InMemoryBroker topic
    (reference: InMemorySink.java)."""

    def publish(self, payload):
        topic = self.resolve_option("topic")
        InMemoryBroker.publish(topic, payload)


@extension("sink", "log")
class LogSink(Sink):
    """Logs each event (reference: LogSink.java).  Options: prefix,
    priority (debug|info|warn|error)."""

    def publish(self, payload):
        prefix = self.options.get("prefix", f"{self.definition.id} : ")
        level = {
            "debug": logging.DEBUG, "info": logging.INFO,
            "warn": logging.WARNING, "error": logging.ERROR,
        }.get(self.options.get("priority", "info").lower(), logging.INFO)
        log.log(level, "%s%s", prefix, payload)


# ---------------------------------------------------------------------------
# Distributed (multi-endpoint) transport
# ---------------------------------------------------------------------------


class DistributionStrategy:
    """Chooses destination indices per event among the ACTIVE
    destinations — failed endpoints leave the rotation until their
    reconnect succeeds (reference: stream/output/sink/distributed/
    DistributionStrategy.java:71 destinationFailed /
    destinationAvailable)."""

    def init(self, n_destinations: int, options: Dict[str, str], definition):
        self.n = n_destinations
        self.options = options
        self.definition = definition
        self.active: List[int] = list(range(n_destinations))

    def destination_failed(self, d: int):
        if d in self.active:
            self.active = [x for x in self.active if x != d]

    def destination_available(self, d: int):
        if d not in self.active:
            self.active = sorted(self.active + [d])

    def destinations_for(self, event: Event) -> List[int]:
        raise NotImplementedError


class RoundRobinDistributionStrategy(DistributionStrategy):
    def init(self, n_destinations, options, definition):
        super().init(n_destinations, options, definition)
        self._i = 0

    def destinations_for(self, event: Event) -> List[int]:
        if not self.active:
            return []
        d = self.active[self._i % len(self.active)]
        self._i += 1
        return [d]


class PartitionedDistributionStrategy(DistributionStrategy):
    """Hashes the ``partitionKey`` attribute onto a destination
    (reference: PartitionedDistributionStrategy.java).  Uses crc32, not
    Python's per-process-randomized hash(), so a key maps to the same
    destination across restarts."""

    def init(self, n_destinations, options, definition):
        super().init(n_destinations, options, definition)
        key = options.get("partitionKey")
        if key is None or key not in definition.attribute_names:
            raise ValueError(
                "partitioned distribution needs a 'partitionKey' option "
                "naming a stream attribute"
            )
        self._idx = definition.attribute_names.index(key)

    def destinations_for(self, event: Event) -> List[int]:
        import zlib

        if event is None:
            raise SiddhiAppRuntimeError(
                "partitioned distribution needs per-event context "
                "(a 1:1 sink mapper)")
        if not self.active:
            return []
        # sticky primary over the TOTAL destination count: keys on
        # healthy endpoints keep their affinity through another
        # endpoint's outage; only the failed endpoint's keys redirect
        h = zlib.crc32(str(event.data[self._idx]).encode())
        primary = h % self.n
        if primary in self.active:
            return [primary]
        return [self.active[h % len(self.active)]]


class BroadcastDistributionStrategy(DistributionStrategy):
    def destinations_for(self, event: Event) -> List[int]:
        return list(self.active)


_STRATEGIES = {
    "roundrobin": RoundRobinDistributionStrategy,
    "partitioned": PartitionedDistributionStrategy,
    "broadcast": BroadcastDistributionStrategy,
}


class DistributedSink(Sink):
    """One logical sink fanned out over N destination connections
    (reference: distributed/DistributedTransport.java + strategies).

    Built from ``@sink(..., @distribution(strategy='...',
    @destination(...), ...))``: each @destination's options overlay the
    parent sink options for its child connection.
    """

    def __init__(self, child_factory, destination_options: List[Dict[str, str]],
                 strategy_name: str, strategy_options: Dict[str, str]):
        cls = _STRATEGIES.get(strategy_name.lower().replace("_", ""))
        if cls is None:
            raise ValueError(f"unknown distribution strategy '{strategy_name}'")
        self._child_factory = child_factory
        self._destination_options = destination_options
        self.strategy: DistributionStrategy = cls()
        self._strategy_options = strategy_options
        self.children: List[Sink] = []

    def init(self, definition, options, mapper, app_context):
        super().init(definition, options, mapper, app_context)
        self.strategy.init(
            len(self._destination_options),
            {**options, **self._strategy_options},
            definition,
        )
        for dest in self._destination_options:
            child = self._child_factory()
            child.init(definition, {**options, **dest}, mapper, app_context)
            self.children.append(child)

    def start(self):
        for c in self.children:
            # children follow the same stream-level @OnError contract
            c.stream_junction = self.stream_junction
            c.start()

    def shutdown(self):
        for c in self.children:
            c.shutdown()

    def send_batch(self, batch: EventBatch):
        events = self._intercepted_events(batch)
        if not events:
            return
        # sync the rotation with observable endpoint health: re-admit
        # reconnected children, evict already-down ones (e.g. a failed
        # initial connect) BEFORE routing so their events go to healthy
        # endpoints instead of the drop path
        for d, c in enumerate(self.children):
            if c.connected and d not in self.strategy.active:
                self.strategy.destination_available(d)
            elif not c.connected and d in self.strategy.active:
                self.strategy.destination_failed(d)
        payloads = self.mapper.map(events)
        pairs = (zip(events, payloads) if len(payloads) == len(events)
                 else ((None, p) for p in payloads))
        for event, payload in pairs:
            dests = self.strategy.destinations_for(event)
            if not dests:
                # every destination down: the drop must stay diagnosable
                # (and fault-routable — keep the event context)
                self._tls.event = event
                try:
                    self.on_error(payload, ConnectionUnavailableError(
                        "no active destinations"))
                finally:
                    self._tls.event = None
                continue
            for d in dests:
                child = self.children[d]
                child._tls.event = event  # dynamic-option context
                try:
                    child.publish_with_reconnect(payload)
                finally:
                    child._tls.event = None
                if not child.connected:
                    # endpoint down: drop it from rotation until its
                    # reconnect chain succeeds
                    self.strategy.destination_failed(d)
