"""In-memory topic broker: the test-double transport.

Mirrors the reference ``util/transport/InMemoryBroker.java`` (a static
topic -> subscribers map used by InMemorySource/InMemorySink and the
whole transport test corpus).
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Callable, Dict, List


class Subscriber:
    """SPI: implement ``on_message`` and ``get_topic`` (reference:
    InMemoryBroker.Subscriber)."""

    def on_message(self, message):
        raise NotImplementedError

    def get_topic(self) -> str:
        raise NotImplementedError


class FunctionSubscriber(Subscriber):
    def __init__(self, topic: str, fn: Callable):
        self._topic = topic
        self._fn = fn

    def on_message(self, message):
        self._fn(message)

    def get_topic(self) -> str:
        return self._topic


class InMemoryBroker:
    """Process-global topic bus (all methods static, like the reference)."""

    _lock = threading.RLock()
    _subscribers: Dict[str, List[Subscriber]] = defaultdict(list)

    @classmethod
    def subscribe(cls, subscriber: Subscriber):
        with cls._lock:
            cls._subscribers[subscriber.get_topic()].append(subscriber)

    @classmethod
    def unsubscribe(cls, subscriber: Subscriber):
        with cls._lock:
            subs = cls._subscribers.get(subscriber.get_topic(), [])
            if subscriber in subs:
                subs.remove(subscriber)

    @classmethod
    def publish(cls, topic: str, message):
        with cls._lock:
            subs = list(cls._subscribers.get(topic, []))
        for s in subs:
            s.on_message(message)

    @classmethod
    def clear(cls):
        """Test helper: drop every subscription."""
        with cls._lock:
            cls._subscribers.clear()
