from siddhi_tpu.transport.broker import InMemoryBroker
from siddhi_tpu.transport.source import (
    InMemorySource,
    PassThroughSourceMapper,
    JsonSourceMapper,
    Source,
    SourceMapper,
)
from siddhi_tpu.transport.sink import (
    InMemorySink,
    JsonSinkMapper,
    LogSink,
    PassThroughSinkMapper,
    Sink,
    SinkMapper,
)

__all__ = [
    "InMemoryBroker",
    "InMemorySource",
    "InMemorySink",
    "JsonSinkMapper",
    "JsonSourceMapper",
    "LogSink",
    "PassThroughSinkMapper",
    "PassThroughSourceMapper",
    "Sink",
    "SinkMapper",
    "Source",
    "SourceMapper",
]
