"""Source/Sink handler SPI: interception hooks on the transport path.

Re-design of the reference HA interception points
(``stream/input/source/SourceHandler.java:35`` — events pass through the
handler between transport and junction; ``stream/output/sink/
SinkHandler.java:34`` — events pass through before mapping/publishing;
``SourceHandlerManager``/``SinkHandlerManager`` generate one handler per
source/sink and track them by element id).  Handlers see event lists at
micro-batch granularity and may filter, annotate, or buffer them.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from siddhi_tpu.core.event import Event


class SourceHandler:
    """Intercepts inbound events between transport and stream junction.
    Override ``on_events``; return the (possibly modified) list."""

    def init(self, app_name: str, stream_id: str):
        self.app_name = app_name
        self.stream_id = stream_id

    def on_events(self, events: List[Event]) -> List[Event]:
        return events


class SinkHandler:
    """Intercepts outbound events before the sink mapper.  Override
    ``on_events``; return the (possibly modified) list."""

    def init(self, app_name: str, stream_id: str):
        self.app_name = app_name
        self.stream_id = stream_id

    def on_events(self, events: List[Event]) -> List[Event]:
        return events


class _HandlerManager:
    def __init__(self):
        self.handlers: Dict[str, object] = {}
        self._seq = 0

    def _register(self, base_id: str, handler) -> str:
        # unique element ids: a stream may carry several @source/@sink
        # annotations, each with its own live handler (the reference
        # tracks by generated element id)
        self._seq += 1
        element_id = f"{base_id}#{self._seq}"
        self.handlers[element_id] = handler
        handler.element_id = element_id  # lets the runtime unregister it
        return element_id

    def unregister(self, element_id: str):
        self.handlers.pop(element_id, None)


class SourceHandlerManager(_HandlerManager):
    """reference: stream/input/source/SourceHandlerManager.java"""

    def generate_source_handler(self) -> SourceHandler:
        return SourceHandler()

    def generate(self, app_name: str, stream_id: str) -> SourceHandler:
        h = self.generate_source_handler()
        h.init(app_name, stream_id)
        self._register(f"{app_name}:{stream_id}", h)
        return h


class SinkHandlerManager(_HandlerManager):
    """reference: stream/output/sink/SinkHandlerManager.java"""

    def generate_sink_handler(self) -> SinkHandler:
        return SinkHandler()

    def generate(self, app_name: str, stream_id: str) -> SinkHandler:
        h = self.generate_sink_handler()
        h.init(app_name, stream_id)
        self._register(f"{app_name}:{stream_id}", h)
        return h


class RecordTableHandlerManager(_HandlerManager):
    """reference: table/record/RecordTableHandlerManager.java"""

    def generate_record_table_handler(self):
        from siddhi_tpu.table.record import RecordTableHandler

        return RecordTableHandler()

    def generate(self, app_name: str, table_id: str):
        h = self.generate_record_table_handler()
        # identity so one manager's handler can route by table
        # (the reference passes elementId into RecordTableHandler)
        h.app_name = app_name
        h.table_id = table_id
        self._register(f"{app_name}:{table_id}", h)
        return h
