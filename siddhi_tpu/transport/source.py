"""Source SPI: external-transport receivers feeding a stream.

Re-design of the reference ``stream/input/source/`` (Source.java:50 —
lifecycle init/connect-with-retry/pause/resume/disconnect,
SourceMapper.java payload -> Event mapping, InMemorySource.java): a
source owns a transport connection and pushes mapped events into its
stream's junction.  Pausing (used while a snapshot is taken) buffers
incoming payloads and replays them on resume.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from siddhi_tpu.core.event import Event
from siddhi_tpu.core.exceptions import ConnectionUnavailableError
from siddhi_tpu.extension.registry import extension
from siddhi_tpu.transport.broker import InMemoryBroker, Subscriber
from siddhi_tpu.transport.retry import ConnectRetryMixin

log = logging.getLogger(__name__)


class SourceMapper:
    """payload -> List[Event] (reference: SourceMapper.java)."""

    def init(self, definition, options: Dict[str, str]):
        self.definition = definition
        self.options = options

    def map(self, payload) -> List[Event]:
        raise NotImplementedError


@extension("source_mapper", "passThrough")
class PassThroughSourceMapper(SourceMapper):
    """Payload already is an Event / row / list thereof
    (reference: PassThroughSourceMapper.java)."""

    def map(self, payload) -> List[Event]:
        if isinstance(payload, Event):
            return [payload]
        if isinstance(payload, (list, tuple)):
            if payload and isinstance(payload[0], Event):
                return list(payload)
            return [Event(data=list(payload))]
        raise ValueError(f"passThrough mapper: cannot map {type(payload).__name__}")


@extension("source_mapper", "json")
class JsonSourceMapper(SourceMapper):
    """JSON object / array of objects -> events by attribute name.

    A stdlib stand-in for the reference's siddhi-map-json extension; the
    optional ``enclosing.element`` option selects a nested list/object.
    """

    def map(self, payload) -> List[Event]:
        import json

        obj = json.loads(payload) if isinstance(payload, (str, bytes)) else payload
        enclosing = self.options.get("enclosing.element")
        if enclosing:
            obj = obj[enclosing]
        rows = obj if isinstance(obj, list) else [obj]
        names = self.definition.attribute_names
        return [Event(data=[r.get(nm) for nm in names]) for r in rows]


class Source(ConnectRetryMixin):
    """Transport receiver SPI (reference: Source.java:50).

    Subclasses implement connect / disconnect and call ``self.deliver``
    with raw payloads.
    """

    def init(self, definition, options: Dict[str, str], mapper: SourceMapper,
             junction, app_context):
        self.definition = definition
        self.options = options
        self.mapper = mapper
        self.junction = junction
        self.app_context = app_context
        self.connected = False
        # @app:faults harness: arms the source.connect injection site
        self._fault_injector = getattr(app_context, "fault_injector", None)
        self._fault_site_connect = "source.connect"
        self._paused = False
        self._pause_buffer: List = []
        self._lock = threading.Lock()
        self._init_retry(options)

    # -- SPI ---------------------------------------------------------------

    def connect(self):
        raise NotImplementedError

    def disconnect(self):
        pass

    # -- lifecycle ---------------------------------------------------------
    # start/_connect_with_retry/_retry_connect come from ConnectRetryMixin

    def pause(self):
        self._paused = True

    def resume(self):
        # drain the pause buffer BEFORE lifting the pause: payloads arriving
        # mid-replay keep buffering behind the older ones, preserving order
        while True:
            with self._lock:
                if not self._pause_buffer:
                    self._paused = False
                    return
                buffered, self._pause_buffer = self._pause_buffer, []
            for p in buffered:
                events = self.mapper.map(p)
                if events:
                    self._send_events(events)

    def shutdown(self):
        self._shutdown_retry()
        if self.connected:
            self.disconnect()
            # the retry thread writes `connected` under _retry_lock;
            # the main-path clear takes the same lock
            with self._retry_lock:
                self.connected = False

    # -- delivery ----------------------------------------------------------

    def deliver(self, payload):
        """Transport thread entry: map and push into the junction."""
        with self._lock:
            if self._paused:
                self._pause_buffer.append(payload)
                return
        events = self.mapper.map(payload)
        if events:
            self._send_events(events)

    def _send_events(self, events: List[Event]):
        from siddhi_tpu.core.stream import InputHandler

        hook = getattr(self, "handler", None)
        if hook is not None:
            events = hook.on_events(events)
            if not events:
                return
        handler = getattr(self, "_handler", None)
        if handler is None:
            handler = self._handler = InputHandler(self.junction, self.app_context)
        handler.send(events)


@extension("source", "inMemory")
class InMemorySource(Source):
    """Subscribes its stream to an InMemoryBroker topic
    (reference: InMemorySource.java — topic validated at init, so a
    missing option fails app creation, not the retry loop)."""

    def init(self, definition, options, mapper, junction, app_context):
        super().init(definition, options, mapper, junction, app_context)
        if options.get("topic") is None:
            from siddhi_tpu.core.exceptions import SiddhiAppCreationError

            raise SiddhiAppCreationError(
                f"inMemory source on '{definition.id}': 'topic' option required"
            )

    def connect(self):
        topic = self.options.get("topic")
        src = self

        class _Sub(Subscriber):
            def on_message(self, message):
                src.deliver(message)

            def get_topic(self) -> str:
                return topic

        self._subscriber = _Sub()
        InMemoryBroker.subscribe(self._subscriber)

    def disconnect(self):
        sub = getattr(self, "_subscriber", None)
        if sub is not None:
            InMemoryBroker.unsubscribe(sub)
