"""Exponential backoff counter for transport reconnects.

Mirrors the reference ``util/transport/BackoffRetryCounter.java`` (interval
ladder 5s, 10s, 15s, 30s, 1min, 2min, 5min, capped), scaled by a factor so
tests can run the ladder in milliseconds.
"""

from __future__ import annotations

from siddhi_tpu.core.exceptions import ConnectionUnavailableError

_INTERVALS_MS = [5_000, 10_000, 15_000, 30_000, 60_000, 120_000, 300_000]


class BackoffRetryCounter:
    def __init__(self, scale: float = 1.0):
        self.scale = scale
        self._idx = 0

    def reset(self):
        self._idx = 0

    def get_time_interval_ms(self) -> int:
        return int(_INTERVALS_MS[min(self._idx, len(_INTERVALS_MS) - 1)] * self.scale)

    def increment(self):
        if self._idx < len(_INTERVALS_MS) - 1:
            self._idx += 1


class ConnectRetryMixin:
    """Single-chain exponential-backoff reconnect shared by Source and
    Sink (reference: Sink.connectWithRetry:276, Source.connectWithRetry).

    Host class provides ``connect()``, ``definition``, and calls
    ``_init_retry(options)`` from its init; the mixin maintains
    ``connected`` and guarantees at most one pending retry chain.
    """

    def _init_retry(self, options):
        import threading

        self._retry = BackoffRetryCounter(scale=float(options.get("retry.scale", "1.0")))
        # retry.max.attempts: bound on consecutive failed connect
        # attempts before the transport gives up (0 = retry forever, the
        # reference's behavior and the default)
        self._retry_max_attempts = int(options.get("retry.max.attempts", "0"))
        self._retry_attempts = 0
        self.failed = False
        self._retrying = False
        self._retry_lock = threading.Lock()
        self._retry_timer = None
        self._shutdown = False

    def start(self):
        # under the retry lock: a pending Timer chain from a previous
        # start may still be mutating these from its own thread
        with self._retry_lock:
            self._shutdown = False
            self.failed = False
            self._retry_attempts = 0
        self._connect_with_retry()

    def _on_retry_exhausted(self, e: Exception):
        """Hook: the retry ladder ran out of attempts.  Subclasses route
        this through their OnError machinery; the base just logs."""
        import logging

        logging.getLogger(type(self).__module__).error(
            "%s on stream '%s' giving up after %d failed connect "
            "attempts: %s", type(self).__name__, self.definition.id,
            self._retry_attempts, e)

    def _connect_with_retry(self):
        import logging
        import threading

        log = logging.getLogger(type(self).__module__)
        # one reconnect chain at a time — a batch of publish failures must
        # not fan out into parallel perpetual timer chains
        with self._retry_lock:
            if self._retrying:
                return
            self._retrying = True
        try:
            fi = getattr(self, "_fault_injector", None)
            if fi is not None:
                fi.check(getattr(self, "_fault_site_connect", "connect"))
            self.connect()
        except ConnectionUnavailableError as e:
            with self._retry_lock:
                self._retry_attempts += 1
                exhausted = (
                    self._retry_max_attempts
                    and self._retry_attempts >= self._retry_max_attempts)
                if exhausted:
                    self.failed = True
                    self._retrying = False
            if exhausted:
                fi = getattr(self, "_fault_injector", None)
                if fi is not None:
                    fi.stats.connect_retries_exhausted += 1
                self._on_retry_exhausted(e)
                return
            interval = self._retry.get_time_interval_ms()
            self._retry.increment()
            log.warning(
                "%s on stream '%s' connection failed (%s); retrying in %d ms",
                type(self).__name__, self.definition.id, e, interval,
            )
            t = threading.Timer(interval / 1000.0, self._retry_connect)
            t.daemon = True
            with self._retry_lock:
                self._retry_timer = t
            t.start()
            return  # flag stays held until the timer fires
        except BaseException:
            with self._retry_lock:
                self._retrying = False
            raise
        self._retry.reset()
        with self._retry_lock:
            self.connected = True
            self._retry_attempts = 0
            self.failed = False
            self._retrying = False

    def _retry_connect(self):
        with self._retry_lock:
            self._retrying = False
        if not self._shutdown:
            self._connect_with_retry()

    def _shutdown_retry(self):
        """Cancel any pending chain; leaves the mixin restartable."""
        with self._retry_lock:
            self._shutdown = True
            t, self._retry_timer = self._retry_timer, None
            self._retrying = False
        if t is not None:
            t.cancel()
