"""Exponential backoff counter for transport reconnects.

Mirrors the reference ``util/transport/BackoffRetryCounter.java`` (interval
ladder 5s, 10s, 15s, 30s, 1min, 2min, 5min, capped), scaled by a factor so
tests can run the ladder in milliseconds.
"""

from __future__ import annotations

from siddhi_tpu.core.exceptions import ConnectionUnavailableError

_INTERVALS_MS = [5_000, 10_000, 15_000, 30_000, 60_000, 120_000, 300_000]


class BackoffRetryCounter:
    def __init__(self, scale: float = 1.0):
        self.scale = scale
        self._idx = 0

    def reset(self):
        self._idx = 0

    def get_time_interval_ms(self) -> int:
        return int(_INTERVALS_MS[min(self._idx, len(_INTERVALS_MS) - 1)] * self.scale)

    def increment(self):
        if self._idx < len(_INTERVALS_MS) - 1:
            self._idx += 1


class ConnectRetryMixin:
    """Single-chain exponential-backoff reconnect shared by Source and
    Sink (reference: Sink.connectWithRetry:276, Source.connectWithRetry).

    Host class provides ``connect()``, ``definition``, and calls
    ``_init_retry(options)`` from its init; the mixin maintains
    ``connected`` and guarantees at most one pending retry chain.
    """

    def _init_retry(self, options):
        import threading

        self._retry = BackoffRetryCounter(scale=float(options.get("retry.scale", "1.0")))
        # retry.max.attempts: bound on consecutive failed connect
        # attempts before the transport gives up (0 = retry forever, the
        # reference's behavior and the default)
        self._retry_max_attempts = int(options.get("retry.max.attempts", "0"))
        self._retry_attempts = 0
        self.failed = False
        self._retrying = False
        self._retry_lock = threading.Lock()
        self._retry_timer = None
        self._shutdown = False
        # circuit breaker (robustness/breaker.py), attached by the
        # planner when @app:limits(breaker='N') is present
        self._breaker = None

    def start(self):
        # under the retry lock: a pending Timer chain from a previous
        # start may still be mutating these from its own thread
        with self._retry_lock:
            self._shutdown = False
            self.failed = False
            self._retry_attempts = 0
        self._connect_with_retry()

    def _on_retry_exhausted(self, e: Exception):
        """Hook: the retry ladder ran out of attempts.  Subclasses route
        this through their OnError machinery; the base just logs."""
        import logging

        logging.getLogger(type(self).__module__).error(
            "%s on stream '%s' giving up after %d failed connect "
            "attempts: %s", type(self).__name__, self.definition.id,
            self._retry_attempts, e)

    def _connect_with_retry(self):
        import logging
        import threading

        log = logging.getLogger(type(self).__module__)
        # one reconnect chain at a time — a batch of publish failures must
        # not fan out into parallel perpetual timer chains
        with self._retry_lock:
            if self._retrying:
                return
            self._retrying = True
        breaker = getattr(self, "_breaker", None)
        if breaker is not None and not breaker.allow():
            # circuit open: skip the connect attempt entirely and
            # re-check after the ladder interval (by then the cooldown
            # may have elapsed and this chain becomes the half-open probe)
            interval = self._retry.get_time_interval_ms()
            self._retry.increment()
            self._arm_retry_timer(interval)
            return
        try:
            fi = getattr(self, "_fault_injector", None)
            if fi is not None:
                fi.check(getattr(self, "_fault_site_connect", "connect"))
            self.connect()
        except ConnectionUnavailableError as e:
            if breaker is not None:
                try:
                    breaker.record_failure()
                except Exception as fault:  # noqa: BLE001
                    # injected breaker.open fault: already counted by the
                    # injector; the backoff chain must survive it
                    log.warning(
                        "%s on stream '%s': breaker.open site fault: %s",
                        type(self).__name__, self.definition.id, fault)
            with self._retry_lock:
                self._retry_attempts += 1
                exhausted = (
                    self._retry_max_attempts
                    and self._retry_attempts >= self._retry_max_attempts)
                if exhausted:
                    self.failed = True
                    self._retrying = False
            if exhausted:
                fi = getattr(self, "_fault_injector", None)
                if fi is not None:
                    fi.stats.connect_retries_exhausted += 1
                self._on_retry_exhausted(e)
                return
            interval = self._retry.get_time_interval_ms()
            self._retry.increment()
            log.warning(
                "%s on stream '%s' connection failed (%s); retrying in %d ms",
                type(self).__name__, self.definition.id, e, interval,
            )
            self._arm_retry_timer(interval)
            return  # flag stays held until the timer fires
        except BaseException:
            with self._retry_lock:
                self._retrying = False
            raise
        self._retry.reset()
        with self._retry_lock:
            self.connected = True
            self._retry_attempts = 0
            self.failed = False
            self._retrying = False
        if breaker is not None and breaker.record_success():
            # this connect CLOSED the breaker — drain anything the owner
            # spooled while it was open (sinks override; default no-op)
            self._on_breaker_closed()

    def _on_breaker_closed(self):
        """Hook: the circuit breaker closed after a successful connect.
        Sinks flush their open-state spool here; sources have nothing
        buffered (their pause path already replays in order)."""

    def _arm_retry_timer(self, interval_ms: int):
        """Arm the next backoff Timer — under ``_retry_lock`` and gated
        on ``_shutdown``.  A concurrent ``shutdown()`` that already ran
        ``_shutdown_retry()`` found no timer to cancel; arming one here
        anyway would leave a zombie firing after shutdown (and, because
        ``start()`` re-clears ``_shutdown``, able to interleave with a
        NEW chain's state).  Checking under the same lock closes the
        race: either the cancel sees our timer, or we see the flag."""
        import threading

        t = threading.Timer(interval_ms / 1000.0, self._retry_connect)
        t.daemon = True
        with self._retry_lock:
            if self._shutdown:
                self._retrying = False
                return
            self._retry_timer = t
        t.start()

    def _retry_connect(self):
        with self._retry_lock:
            self._retrying = False
        if not self._shutdown:
            self._connect_with_retry()

    def _shutdown_retry(self):
        """Cancel any pending chain; leaves the mixin restartable."""
        with self._retry_lock:
            self._shutdown = True
            t, self._retry_timer = self._retry_timer, None
            self._retrying = False
        if t is not None:
            t.cancel()
