"""Exponential backoff counter for transport reconnects.

Mirrors the reference ``util/transport/BackoffRetryCounter.java`` (interval
ladder 5s, 10s, 15s, 30s, 1min, 2min, 5min, capped), scaled by a factor so
tests can run the ladder in milliseconds.
"""

from __future__ import annotations

_INTERVALS_MS = [5_000, 10_000, 15_000, 30_000, 60_000, 120_000, 300_000]


class BackoffRetryCounter:
    def __init__(self, scale: float = 1.0):
        self.scale = scale
        self._idx = 0

    def reset(self):
        self._idx = 0

    def get_time_interval_ms(self) -> int:
        return int(_INTERVALS_MS[min(self._idx, len(_INTERVALS_MS) - 1)] * self.scale)

    def increment(self):
        if self._idx < len(_INTERVALS_MS) - 1:
            self._idx += 1
