"""Extension SPI: pluggable windows, functions, aggregators, sources,
sinks, mappers, stores (reference: siddhi-annotations @Extension +
util/SiddhiExtensionLoader, SURVEY.md §2.2 Extension loading)."""

from siddhi_tpu.extension.registry import ExtensionRegistry, extension
