"""Plan-time extension parameter validation.

Re-design of the reference's annotation-driven validator
(util/extension/validator/InputParameterValidator.java, driven by the
``@Parameter`` / ``@ParameterOverload`` metadata in siddhi-annotations):
extension classes declare ``PARAMETERS`` (name -> allowed types) and
``OVERLOADS`` (accepted signatures, optionally ending with the
repetitive marker ``"..."``), and the planner validates compiled
argument types against them *before* instantiation, so a bad call fails
app creation with a typed error instead of a runtime shape/type error.

Classes without an ``OVERLOADS`` declaration are accepted unchecked
(the reference behaves the same for extensions without
``parameterOverloads``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Sequence, Tuple

from siddhi_tpu.core.exceptions import SiddhiAppValidationError
from siddhi_tpu.query_api.attribute import AttrType

#: Repetitive-parameter marker: an overload ending with REPEAT accepts
#: zero or more further arguments matching the parameter named just
#: before it (reference: SiddhiConstants.REPETITIVE_PARAMETER_NOTATION).
REPEAT = "..."


@dataclass(frozen=True)
class Param:
    """One declared parameter (the ``@Parameter`` analog).  An empty
    ``types`` tuple accepts any type."""

    name: str
    types: Tuple[AttrType, ...] = ()


def _accepts(param: Param, t: AttrType) -> bool:
    return not param.types or t in param.types or t is AttrType.OBJECT


def _signature(overload: Sequence[str], params: Dict[str, Param]) -> str:
    parts = []
    for name in overload:
        if name == REPEAT:
            parts.append(REPEAT)
            continue
        p = params.get(name)
        ts = "|".join(t.value for t in p.types) if p and p.types else "any"
        parts.append(f"{name} <{ts}>")
    return "(" + ", ".join(parts) + ")"


def validate_extension_args(cls, name: str, arg_types: Sequence[AttrType],
                            where: str = "") -> None:
    """Check compiled argument types against ``cls.OVERLOADS``.

    Raises SiddhiAppValidationError when overloads are declared and no
    signature matches; silently accepts undeclared extensions."""
    # own-class declaration only — like Java's getAnnotation(), a subclass
    # does not inherit the base extension's signature (it may legitimately
    # accept different arguments)
    overloads = (cls.__dict__.get("OVERLOADS") if isinstance(cls, type)
                 else getattr(cls, "OVERLOADS", None))
    if overloads is None:
        return
    declared = getattr(cls, "PARAMETERS", ())
    params = {p.name: p for p in declared}

    def matches(overload: Sequence[str]) -> bool:
        names = list(overload)
        repeat = bool(names) and names[-1] == REPEAT
        if repeat:
            names = names[:-1]
            if len(arg_types) < len(names):
                return False
        elif len(arg_types) != len(names):
            return False
        for i, pname in enumerate(names):
            p = params.get(pname, Param(pname))
            if not _accepts(p, arg_types[i]):
                return False
        if repeat and names:
            tail_param = params.get(names[-1], Param(names[-1]))
            for t in arg_types[len(names):]:
                if not _accepts(tail_param, t):
                    return False
        return True

    for overload in overloads:
        if matches(overload):
            return
    got = "(" + ", ".join(t.value for t in arg_types) + ")"
    expected = " or ".join(_signature(o, params) for o in overloads) or "()"
    raise SiddhiAppValidationError(
        f"{where or name}: arguments {got} match no declared signature "
        f"of '{name}'; expected {expected}"
    )
