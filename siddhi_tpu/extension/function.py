"""Custom scalar functions and script UDFs.

Re-design of the reference ``core/executor/function/FunctionExecutor``
extension base plus the script surface (``define function f[lang]
return type { body }``, executor/function/ScriptFunctionExecutor): a
custom function is a class with ``execute(*values)`` called per row
(vectorized by the wrapper), a script engine is an extension of kind
'script' keyed by language that compiles a body into such a callable.
"""

from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

from siddhi_tpu.core.exceptions import SiddhiAppCreationError
from siddhi_tpu.extension.registry import extension
from siddhi_tpu.planner.expr import CompiledExpression, _to_type
from siddhi_tpu.query_api import AttrType


class FunctionExecutor:
    """Custom scalar function SPI (reference: FunctionExecutor.java).

    Subclass, set ``return_type``, implement ``execute(*values)`` (one
    row's argument values -> one value).  Register via
    ``SiddhiManager.set_extension('ns:name', cls, kind='function')``.
    """

    return_type: AttrType = AttrType.OBJECT

    def init(self, arg_types: List[AttrType]):
        pass

    def execute(self, *values):
        raise NotImplementedError


def make_scalar_function_builder(scalar: Callable, return_type: Optional[AttrType]):
    """Wrap a per-row callable into an expression-compiler function
    builder: argument arrays are broadcast and the callable applied
    row-wise via a numpy ufunc."""

    def builder(args: List[CompiledExpression]) -> CompiledExpression:
        nin = len(args)
        ufunc = np.frompyfunc(scalar, nin, 1) if nin else None

        def fn(env):
            if nin == 0:
                return scalar()
            vals = [np.atleast_1d(np.asarray(a.fn(env))) for a in args]
            vals = np.broadcast_arrays(*vals)
            out = ufunc(*vals)
            if return_type is not None and return_type != AttrType.OBJECT:
                return _to_type(out, return_type)
            return out

        return CompiledExpression(fn, return_type or AttrType.OBJECT)

    return builder


def builder_for_extension(factory) -> Callable:
    """An extension registered as kind='function' may be a
    FunctionExecutor subclass, an instance, or a plain callable.
    Executor classes are instantiated per call site and ``init`` receives
    the argument types (reference: FunctionExecutor.initExecutor)."""
    if isinstance(factory, type) and issubclass(factory, FunctionExecutor):
        def builder(args: List[CompiledExpression]) -> CompiledExpression:
            inst = factory()
            inst.init([a.type for a in args])
            return make_scalar_function_builder(inst.execute, inst.return_type)(args)

        return builder
    if isinstance(factory, FunctionExecutor):
        def builder(args: List[CompiledExpression]) -> CompiledExpression:
            factory.init([a.type for a in args])
            return make_scalar_function_builder(factory.execute, factory.return_type)(args)

        return builder
    if callable(factory):
        return make_scalar_function_builder(factory, None)
    raise SiddhiAppCreationError(
        f"function extension {factory!r} is neither FunctionExecutor nor callable")


class ScriptEngine:
    """Script-language SPI (extension kind 'script', name = language)."""

    def compile(self, name: str, body: str, return_type: AttrType) -> Callable:
        raise NotImplementedError


@extension("script", "python")
class PythonScript(ScriptEngine):
    """``define function f[python] return type { body }``.

    The body sees the argument values as ``data`` (a list).  A body that
    is a single expression is evaluated directly; otherwise it is
    executed and must assign ``result``.
    """

    def compile(self, name: str, body: str, return_type: AttrType) -> Callable:
        src = body.strip()
        try:
            code = compile(src, f"<function {name}>", "eval")
            mode = "eval"
        except SyntaxError:
            try:
                code = compile(src, f"<function {name}>", "exec")
                mode = "exec"
            except SyntaxError as e:
                raise SiddhiAppCreationError(
                    f"function '{name}[python]': body does not compile: {e}"
                ) from e

        def scalar(*values):
            g = {"data": list(values)}
            if mode == "eval":
                return eval(code, g)  # noqa: S307 — user-defined script UDF
            exec(code, g)  # noqa: S102
            if "result" not in g:
                raise SiddhiAppCreationError(
                    f"function '{name}[python]': multi-statement body must set 'result'")
            return g["result"]

        return scalar


@extension("script", "javascript")
@extension("script", "js")
class JavaScriptScript(ScriptEngine):
    """Placeholder matching the reference's JS script support: no JS
    engine ships in this environment, so planning a [javascript]
    function fails with a clear error unless the user registers their
    own engine under kind='script'."""

    def compile(self, name: str, body: str, return_type: AttrType) -> Callable:
        raise SiddhiAppCreationError(
            f"function '{name}[javascript]': no JavaScript engine available; "
            "register one with set_extension('javascript', Engine, kind='script') "
            "or use [python]"
        )
