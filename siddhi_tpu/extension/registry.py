"""Extension registry: namespace:name -> factory, per extension kind.

Replaces the reference's classpath annotation scan
(util/SiddhiExtensionLoader.java:58 + typed holders under
util/extension/holder/) with an explicit registry; the ``@extension``
decorator is the ``@Extension`` annotation analog.  Kinds mirror the
reference holder types: window, function (scalar), aggregator,
stream_processor, stream_function, source, sink, source_mapper,
sink_mapper, table, store, script.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Callable, Dict, Optional

KINDS = (
    "window",
    "function",
    "aggregator",
    "stream_processor",
    "stream_function",
    "source",
    "sink",
    "source_mapper",
    "sink_mapper",
    "table",
    "store",
    "script",
)


class ExtensionRegistry:
    def __init__(self):
        self._kinds: Dict[str, Dict[str, Callable]] = defaultdict(dict)

    @staticmethod
    def full_name(namespace: Optional[str], name: str) -> str:
        return f"{namespace}:{name}" if namespace else name

    def register(self, kind: str, name: str, factory: Callable, namespace: Optional[str] = None):
        assert kind in KINDS, f"unknown extension kind {kind!r}"
        self._kinds[kind][self.full_name(namespace, name)] = factory

    def items(self, kind: str):
        return list(self._kinds[kind].items())

    def unregister(self, kind: str, name: str, namespace: Optional[str] = None):
        self._kinds[kind].pop(self.full_name(namespace, name), None)

    def lookup(self, kind: str, name: str, namespace: Optional[str] = None) -> Optional[Callable]:
        return self._kinds[kind].get(self.full_name(namespace, name))

    def names(self, kind: str):
        return sorted(self._kinds[kind])

    def copy(self) -> "ExtensionRegistry":
        r = ExtensionRegistry()
        for kind, entries in self._kinds.items():
            r._kinds[kind] = dict(entries)
        return r


# global default registry populated by builtin modules at import time
_DEFAULT = ExtensionRegistry()


def extension(kind: str, name: str, namespace: Optional[str] = None):
    """Decorator registering a builtin/user extension in the default
    registry (the @Extension annotation analog)."""

    def wrap(cls):
        _DEFAULT.register(kind, name, cls, namespace)
        return cls

    return wrap


def default_registry() -> ExtensionRegistry:
    # import builtin extension modules for their registration side effects
    import siddhi_tpu.extension.function  # noqa: F401
    import siddhi_tpu.ops.stream_functions  # noqa: F401
    import siddhi_tpu.ops.windows  # noqa: F401
    import siddhi_tpu.table.record  # noqa: F401
    import siddhi_tpu.transport.sink  # noqa: F401
    import siddhi_tpu.transport.source  # noqa: F401

    return _DEFAULT.copy()
