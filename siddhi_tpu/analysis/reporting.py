"""Text / JSON / SARIF reporters and baseline handling for the CLI.

A baseline is a JSON file (``analysis_baseline.json``) listing finding
identities (``"<rule>:<key>"``) that are acknowledged-but-unfixed; the
CLI subtracts them so a legacy violation doesn't block the run while a
NEW one still fails it.  Like allowlist entries, baselined identities
that no longer match anything are reported (``--prune-baseline`` style
hygiene is left to the operator — they are listed as ``stale`` in the
report, not failures, since a baseline is a ratchet, not a sanction).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import List, Sequence

from .framework import Finding, Rule


def finding_identity(f: Finding) -> str:
    return f"{f.rule}:{f.key}"


def load_baseline(path) -> List[str]:
    data = json.loads(Path(path).read_text())
    if isinstance(data, dict):
        data = data.get("findings", [])
    if not isinstance(data, list) or \
            not all(isinstance(x, str) for x in data):
        raise ValueError(
            f"baseline {path}: expected a JSON list of "
            '"<rule>:<key>" strings (or {"findings": [...]})')
    return data


def write_baseline(path, findings: Sequence[Finding]):
    ids = sorted({finding_identity(f) for f in findings})
    Path(path).write_text(json.dumps({"findings": ids}, indent=2) + "\n")


def apply_baseline(findings: Sequence[Finding], baseline: Sequence[str]):
    """(unbaselined, baselined, stale_baseline_ids)."""
    known = set(baseline)
    kept = [f for f in findings if finding_identity(f) not in known]
    suppressed = [f for f in findings if finding_identity(f) in known]
    stale = sorted(known - {finding_identity(f) for f in findings})
    return kept, suppressed, stale


def render_text(findings: Sequence[Finding], rules: Sequence[Rule],
                suppressed_count: int = 0, baselined_count: int = 0,
                stale_baseline: Sequence[str] = (),
                modules: int = 0) -> str:
    lines: List[str] = []
    for f in findings:
        lines.append(f.render())
    if findings:
        lines.append("")
    lines.append(
        f"{len(findings)} finding(s) from {len(rules)} rule(s) over "
        f"{modules} module(s); {suppressed_count} allowlisted, "
        f"{baselined_count} baselined")
    for ident in stale_baseline:
        lines.append(f"note: baseline entry no longer matches: {ident}")
    return "\n".join(lines)


def render_json(findings: Sequence[Finding], rules: Sequence[Rule],
                suppressed: Sequence[Finding] = (),
                baselined_count: int = 0,
                stale_baseline: Sequence[str] = (),
                modules: int = 0) -> str:
    return json.dumps({
        # header stamps for trend tracking: CI diffs these two numbers
        # across runs without parsing the body
        "rule_count": len(rules),
        "finding_count": len(findings),
        "rules": [{"name": r.name, "description": r.description}
                  for r in rules],
        "modules": modules,
        "findings": [f.as_dict() for f in findings],
        "allowlisted": len(suppressed),
        "baselined": baselined_count,
        "stale_baseline": list(stale_baseline),
    }, indent=2)


#: SARIF 2.1.0 — the minimal profile editors/CI ingest: one run, the
#: rule catalog on tool.driver, one result per finding with a physical
#: location and a stable partialFingerprint (the allowlist key, which
#: is deliberately line-number-free).
SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def render_sarif(findings: Sequence[Finding], rules: Sequence[Rule]) -> str:
    rule_index = {r.name: i for i, r in enumerate(rules)}
    results = []
    for f in findings:
        results.append({
            "ruleId": f.rule,
            # stale-allowlist findings are synthesized by the framework
            # and have no registered rule entry to index
            **({"ruleIndex": rule_index[f.rule]}
               if f.rule in rule_index else {}),
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.rel},
                    "region": {"startLine": max(f.line, 1)},
                },
            }],
            "partialFingerprints": {"analysisKey/v1": f"{f.rule}:{f.key}"},
        })
    return json.dumps({
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "siddhi-tpu-analysis",
                    "rules": [{
                        "id": r.name,
                        "shortDescription": {"text": r.description},
                    } for r in rules],
                },
            },
            "results": results,
        }],
    }, indent=2)
