"""Flow-sensitive lockset machinery shared by the concurrency rules.

Built on ``cfg`` + ``dataflow``, this module computes Eraser-style
**must-hold locksets**: for every statement of every function in the
project, the set of lock tokens that are held on EVERY path from the
function's entry to that statement.  The three flow-sensitive rules
(``lockset-race``, ``lock-order-deadlock``, ``barrier-flush``) consume
one shared :class:`LockModel` per run (cached on the ``ProjectIndex``),
so the package is lowered and iterated once, not once per rule.

**Lock tokens.**  A ``with``-context expression or ``.acquire()`` /
``.release()`` receiver whose final attribute ends in ``lock`` (the same
heuristic the lexical ``under_lock`` check used) becomes a token:

- ``self._lock`` → ``('attr', '_lock')`` — an instance lock, compared
  per-class (and qualified by its *defining* class for the global
  lock-order graph, so a mixin's lock is one node however many
  subclasses inherit it);
- ``self.app_context.process_lock`` / ``ctx.process_lock`` →
  ``('chain', 'app_context.process_lock')`` — an engine-level lock
  reached through a chain; the last two components identify it across
  modules, and single-assignment local aliases (``ctx =
  self.runtime.app_context``) are expanded first so every spelling
  normalizes to the same token.

**Transfer function.** ``WithEnter``/``WithExit`` pseudo-statements add
and remove tokens; explicit ``.acquire()`` adds and ``.release()``
removes, which is exactly what the lexical pass could not see — a write
after a mid-``with`` release, or between ``acquire()`` pairs, gets the
correct (empty) lockset.  ``Condition.wait()`` is a no-op: the lock is
re-held when the call returns.

**Interprocedural seeding.** Private helpers (leading ``_``, not
dunder, not a thread target) that are only ever called with a lock held
inherit that lock as their entry lockset: the model intersects the
caller-side locksets over every call site the PR 12 call graph resolves,
then re-runs the dataflow with the grown seeds (two rounds — seeds grow
monotonically, so the iteration is convergent and bounded).  Public
methods always start empty: anything may call them.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from .cfg import CFG, WithEnter, WithExit, build_cfg
from .dataflow import TOP, Analysis, Result, solve, stmt_facts
from .index import ModuleIndex
from .project import ProjectIndex, plain_dotted

#: a lock token: ('attr', '<name>') for self/cls-owned instance locks,
#: ('chain', '<a.b>') for locks reached through an attribute chain
Token = Tuple[str, str]

_THREAD_CTORS = {"threading.Thread", "Thread"}
_TIMER_CTORS = {"threading.Timer", "Timer"}

_SCOPE_NODES = (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef,
                ast.ClassDef)


def thread_target_of(call: ast.Call, index: ModuleIndex):
    """(kind, name) for a thread-launching call: ``('method', m)`` for a
    ``self.m`` target, ``('local', f)`` for a local function — shared by
    the lock rules and the lexical lock-discipline wrapper."""
    name = index.dotted(call.func)
    target = None
    if name in _THREAD_CTORS:
        for kw in call.keywords:
            if kw.arg == "target":
                target = kw.value
    elif name in _TIMER_CTORS:
        if len(call.args) >= 2:
            target = call.args[1]
        else:
            for kw in call.keywords:
                if kw.arg == "function":
                    target = kw.value
    if target is None:
        return None
    if isinstance(target, ast.Attribute) and \
            isinstance(target.value, ast.Name) and \
            target.value.id in ("self", "cls"):
        return ("method", target.attr)
    if isinstance(target, ast.Name):
        return ("local", target.id)
    return None


def render_token(tok: Token) -> str:
    return tok[1]


def lock_token(expr: ast.AST, aliases: Dict[str, str]) -> Optional[Token]:
    """Token for a lock expression, or None when it isn't lock-shaped."""
    p = plain_dotted(expr)
    if p is None:
        return None
    parts = p.split(".")
    if parts[0] in aliases:
        parts = aliases[parts[0]].split(".") + parts[1:]
    self_rooted = parts[0] in ("self", "cls")
    if self_rooted:
        parts = parts[1:]
    if not parts:
        return None
    leaf = parts[-1]
    if not leaf.lower().endswith("lock"):
        return None
    if self_rooted and len(parts) == 1:
        return ("attr", leaf)
    return ("chain", ".".join(parts[-2:]))


def _walk_no_scopes(root: ast.AST) -> Iterator[ast.AST]:
    """``ast.walk`` that does not descend into nested scopes."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _SCOPE_NODES):
                continue
            stack.append(child)


def shallow_calls(stmt) -> Iterator[ast.Call]:
    """Calls evaluated BY this statement itself: compound headers yield
    only their test/iterator expression (their bodies are separate
    statements of other blocks), plain statements their full expression
    tree minus nested scopes."""
    if isinstance(stmt, (WithEnter, WithExit)):
        roots = [stmt.item.context_expr]
    elif isinstance(stmt, (ast.If, ast.While)):
        roots = [stmt.test]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        roots = [stmt.iter]
    elif hasattr(ast, "Match") and isinstance(stmt, ast.Match):
        roots = [stmt.subject]
    elif isinstance(stmt, (ast.Try, ast.ExceptHandler)) or \
            isinstance(stmt, _SCOPE_NODES):
        return
    else:
        roots = [stmt]
    for root in roots:
        for node in _walk_no_scopes(root):
            if isinstance(node, ast.Call):
                yield node


def stmt_writes(stmt) -> Iterator[Tuple[str, int]]:
    """Direct ``self.x = / += / :`` writes of ONE statement —
    ``(attr, lineno)``; compound headers yield nothing."""
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    else:
        return
    for t in targets:
        elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
        for e in elts:
            if isinstance(e, ast.Attribute) and \
                    isinstance(e.value, ast.Name) and \
                    e.value.id in ("self", "cls"):
                yield (e.attr, e.lineno)


class LocksetAnalysis(Analysis):
    """Forward must-hold analysis: join is set intersection."""

    direction = "forward"

    def __init__(self, seed: FrozenSet[Token], aliases: Dict[str, str]):
        self.seed = frozenset(seed)
        self.aliases = aliases

    def initial(self, cfg: CFG) -> FrozenSet[Token]:
        return self.seed

    def join(self, a, b):
        return a & b

    def transfer(self, stmt, fact):
        if isinstance(stmt, WithEnter):
            tok = lock_token(stmt.item.context_expr, self.aliases)
            return fact | {tok} if tok else fact
        if isinstance(stmt, WithExit):
            tok = lock_token(stmt.item.context_expr, self.aliases)
            return fact - {tok} if tok else fact
        for call in shallow_calls(stmt):
            if not isinstance(call.func, ast.Attribute):
                continue
            if call.func.attr == "acquire":
                tok = lock_token(call.func.value, self.aliases)
                if tok:
                    fact = fact | {tok}
            elif call.func.attr == "release":
                tok = lock_token(call.func.value, self.aliases)
                if tok:
                    fact = fact - {tok}
        return fact


class FnFacts:
    """One function's fixpoint: per-statement must-hold locksets."""

    __slots__ = ("index", "fn", "qual", "cfg", "analysis", "result")

    def __init__(self, index: ModuleIndex, fn: ast.AST, qual: str,
                 cfg: CFG, analysis: LocksetAnalysis, result: Result):
        self.index = index
        self.fn = fn
        self.qual = qual
        self.cfg = cfg
        self.analysis = analysis
        self.result = result

    def statements(self):
        """Yield ``(stmt, lockset_before)``; the lockset is ``TOP`` in
        unreachable blocks (callers skip those)."""
        for _block, stmt, fact in stmt_facts(
                self.cfg, self.analysis, self.result):
            yield stmt, fact

    def acquisitions(self):
        """Yield ``(token, held_before, lineno)`` for every lock
        acquisition this function performs on a reachable path."""
        for stmt, fact in self.statements():
            if fact is TOP:
                continue
            if isinstance(stmt, WithEnter):
                tok = lock_token(stmt.item.context_expr,
                                 self.analysis.aliases)
                if tok:
                    yield tok, fact, stmt.lineno
                continue
            held = fact
            for call in shallow_calls(stmt):
                if not isinstance(call.func, ast.Attribute):
                    continue
                if call.func.attr == "acquire":
                    tok = lock_token(call.func.value,
                                     self.analysis.aliases)
                    if tok:
                        yield tok, held, call.lineno
                        held = held | {tok}
                elif call.func.attr == "release":
                    tok = lock_token(call.func.value,
                                     self.analysis.aliases)
                    if tok:
                        held = held - {tok}


def _lock_ctor_kind(value: ast.AST) -> Optional[str]:
    """'lock' | 'rlock' for a ``threading.Lock()``-style RHS; Conditions
    carry the reentrancy of their underlying lock (bare ``Condition()``
    allocates an RLock)."""
    if not isinstance(value, ast.Call):
        return None
    func = value.func
    leaf = None
    if isinstance(func, ast.Attribute):
        leaf = func.attr
    elif isinstance(func, ast.Name):
        leaf = func.id
    if leaf == "Lock":
        return "lock"
    if leaf == "RLock":
        return "rlock"
    if leaf == "Condition":
        if value.args:
            return _lock_ctor_kind(value.args[0]) or "lock"
        return "rlock"
    return None


class LockModel:
    """Whole-project lockset facts, shared by every flow rule."""

    #: seeding rounds: seeds grow monotonically, two rounds reach the
    #: helpers-two-hops-down cases the engine actually has
    ROUNDS = 2

    def __init__(self, project: ProjectIndex):
        self.project = project
        self._fq_of_fn: Dict[int, str] = {
            id(fn): fq for fq, (_idx, fn) in project.functions.items()}
        self._cfgs: Dict[int, CFG] = {}
        self._aliases: Dict[int, Dict[str, str]] = {}
        self._facts: Dict[Tuple[int, FrozenSet[Token]], FnFacts] = {}
        #: (class fq, attr) -> 'lock' | 'rlock'
        self.lock_defs: Dict[Tuple[str, str], str] = {}
        #: method/local-def NAMES that are Thread/Timer targets anywhere
        self.thread_target_names: Set[str] = set()
        self._collect_lock_defs()
        self._collect_thread_targets()
        #: fq -> entry lockset (interprocedural seeding)
        self.seeds: Dict[str, FrozenSet[Token]] = {}
        self._compute_seeds()

    # -- structure scans ----------------------------------------------------

    def _collect_lock_defs(self):
        for class_fq, (idx, cls) in self.project.classes.items():
            for stmt in cls.body:
                if isinstance(stmt, ast.Assign) and \
                        _lock_ctor_kind(stmt.value):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            self.lock_defs[(class_fq, t.id)] = \
                                _lock_ctor_kind(stmt.value)
            for node in ast.walk(cls):
                if not isinstance(node, ast.Assign):
                    continue
                kind = _lock_ctor_kind(node.value)
                if kind is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id in ("self", "cls"):
                        self.lock_defs[(class_fq, t.attr)] = kind

    def _collect_thread_targets(self):
        for idx in self.project.indexes:
            for call in idx.calls():
                tgt = thread_target_of(call, idx)
                if tgt is not None:
                    self.thread_target_names.add(tgt[1])

    # -- per-function facts --------------------------------------------------

    def cfg_of(self, fn: ast.AST) -> CFG:
        cfg = self._cfgs.get(id(fn))
        if cfg is None:
            cfg = build_cfg(fn)
            self._cfgs[id(fn)] = cfg
        return cfg

    def aliases_of(self, index: ModuleIndex, fn: ast.AST
                   ) -> Dict[str, str]:
        cached = self._aliases.get(id(fn))
        if cached is not None:
            return cached
        qual = index.def_qualname(fn)
        assigned: Dict[str, int] = {}
        values: Dict[str, str] = {}
        for node in ast.walk(fn):
            if node is fn or index.qualname(node) != qual:
                continue
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                assigned[name] = assigned.get(name, 0) + 1
                v = plain_dotted(node.value)
                if v is not None:
                    values[name] = v
        out = {n: v for n, v in values.items() if assigned.get(n) == 1}
        self._aliases[id(fn)] = out
        return out

    def facts(self, index: ModuleIndex, fn: ast.AST,
              seed: FrozenSet[Token] = frozenset()) -> FnFacts:
        key = (id(fn), frozenset(seed))
        hit = self._facts.get(key)
        if hit is not None:
            return hit
        analysis = LocksetAnalysis(seed, self.aliases_of(index, fn))
        cfg = self.cfg_of(fn)
        result = solve(cfg, analysis)
        ff = FnFacts(index, fn, index.def_qualname(fn), cfg, analysis,
                     result)
        self._facts[key] = ff
        return ff

    def seed_of(self, fn: ast.AST) -> FrozenSet[Token]:
        fq = self._fq_of_fn.get(id(fn))
        if fq is None:
            return frozenset()
        return self.seeds.get(fq, frozenset())

    # -- interprocedural seeding --------------------------------------------

    def _seedable(self, fq: str) -> bool:
        leaf = fq.rsplit(".", 1)[-1]
        return (leaf.startswith("_") and not leaf.startswith("__")
                and leaf not in self.thread_target_names)

    def _compute_seeds(self):
        seeds: Dict[str, FrozenSet[Token]] = {}
        for _round in range(self.ROUNDS):
            acc: Dict[str, Optional[FrozenSet[Token]]] = {}
            for fq, (idx, fn) in self.project.functions.items():
                ff = self.facts(idx, fn, seeds.get(fq, frozenset()))
                if not ff.result.converged:
                    continue
                for stmt, fact in ff.statements():
                    if fact is TOP:
                        continue
                    for call in shallow_calls(stmt):
                        hit = self.project.resolve_call(idx, call)
                        if hit is None:
                            continue
                        # hit[2] is the defining CLASS for self.m()
                        # calls — recover the function's own fq from
                        # the resolved def node
                        t_fq = self._fq_of_fn.get(id(hit[1]))
                        if t_fq is None or not self._seedable(t_fq):
                            continue
                        cur = acc.get(t_fq)
                        acc[t_fq] = (frozenset(fact) if cur is None
                                     else cur & fact)
            new_seeds = {fq: s for fq, s in acc.items() if s}
            if new_seeds == seeds:
                break
            seeds = new_seeds
        self.seeds = seeds

    # -- token identity across the project ----------------------------------

    def definer_of(self, ctx_class_fq: Optional[str], attr: str
                   ) -> Optional[str]:
        """The MRO class that constructs ``self.<attr>`` as a lock."""
        if ctx_class_fq is None:
            return None
        for c in self.project.mro(ctx_class_fq):
            if (c, attr) in self.lock_defs:
                return c
        return ctx_class_fq

    def qualify(self, tok: Token, ctx_class_fq: Optional[str]) -> str:
        """Globally-unique node name for the lock-order graph."""
        kind, name = tok
        if kind == "attr":
            d = self.definer_of(ctx_class_fq, name)
            leaf = d.rsplit(".", 1)[-1] if d else "?"
            return f"{leaf}.{name}"
        return name

    def reentrant(self, tok: Token, ctx_class_fq: Optional[str]
                  ) -> Optional[bool]:
        """True/False when the lock's constructor is known, else None."""
        kind, name = tok
        if kind != "attr":
            return None
        d = self.definer_of(ctx_class_fq, name)
        lk = self.lock_defs.get((d, name)) if d else None
        if lk is None:
            return None
        return lk == "rlock"


def get_model(project: ProjectIndex) -> LockModel:
    """The per-run shared model, built once and cached on the project."""
    model = getattr(project, "_lock_model", None)
    if model is None:
        model = LockModel(project)
        project._lock_model = model
    return model
