"""Basic-block control-flow graphs for function bodies.

The flow-*insensitive* rules (PR 5 lexical → PR 12 whole-program) can
say *where* a statement sits but not *when* it runs: they cannot see
that a write happens after a lock is released in the same method, cannot
order two acquisitions, and cannot tell a reachable flush from dead code
behind an early return.  This module lowers any ``FunctionDef`` /
``AsyncFunctionDef`` / ``Lambda`` body to a CFG the ``dataflow`` engine
iterates over:

- **blocks** hold the original AST statements in execution order, plus
  two pseudo-statements — :class:`WithEnter` / :class:`WithExit` — that
  mark ``with`` context entry and exit explicitly (the lockset rules'
  acquire/release events).  Compound statements (``If``/``While``/
  ``For``/``Try``/``Match``) appear once, in the block that evaluates
  their test, as a *header marker*; their bodies are lowered into
  successor blocks;
- **edges** cover both branch arms, loop back-edges and exits (``break``
  / ``continue`` unwind through any ``with`` frames they cross, emitting
  the matching ``WithExit``s), ``try`` bodies (every body block gets an
  edge to each handler entry — an exception may occur anywhere),
  ``finally`` routing (normal completion, handler completion, and jumps
  through the ``finally`` all pass through its blocks), and early
  ``return`` / ``raise`` (unwound through open ``with`` frames to the
  exit block or the innermost handler);
- **nested scopes are opaque**: a nested ``def``/``lambda``/``class``
  statement is one ordinary statement of the enclosing CFG — callers
  build a separate CFG per function, exactly like ``ModuleIndex``
  scopes.

Deliberate over-approximations (documented, conservative for the
must-hold lockset analyses that consume this graph): exception edges out
of a ``with`` body do not emit ``WithExit`` (the held-set stays larger,
so a must-analysis claims *fewer* facts, never more), and a shared
``finally`` lowering merges the paths that cross it instead of
duplicating blocks per jump target.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence, Union

__all__ = ["Block", "CFG", "WithEnter", "WithExit", "build_cfg"]


class WithEnter:
    """Pseudo-statement: control entered ``with <item>`` (acquire)."""

    __slots__ = ("node", "item")

    def __init__(self, node: ast.With, item: ast.withitem):
        self.node = node
        self.item = item

    @property
    def lineno(self) -> int:
        return self.node.lineno

    def __repr__(self):  # pragma: no cover - debug aid
        return f"WithEnter@{self.lineno}"


class WithExit:
    """Pseudo-statement: control left ``with <item>`` (release)."""

    __slots__ = ("node", "item")

    def __init__(self, node: ast.With, item: ast.withitem):
        self.node = node
        self.item = item

    @property
    def lineno(self) -> int:
        return self.node.lineno

    def __repr__(self):  # pragma: no cover - debug aid
        return f"WithExit@{self.lineno}"


#: what a block may hold: real statements, header markers (the compound
#: statement node itself), ``except`` handler markers, or pseudo-ops
Stmt = Union[ast.AST, WithEnter, WithExit]


class Block:
    """One basic block: straight-line statements + explicit edges."""

    __slots__ = ("bid", "stmts", "succs", "preds")

    def __init__(self, bid: int):
        self.bid = bid
        self.stmts: List[Stmt] = []
        self.succs: List["Block"] = []
        self.preds: List["Block"] = []

    def __repr__(self):  # pragma: no cover - debug aid
        return (f"Block({self.bid}, stmts={len(self.stmts)}, "
                f"succs={[s.bid for s in self.succs]})")


class CFG:
    """The lowered graph of one function body."""

    __slots__ = ("func", "blocks", "entry", "exit")

    def __init__(self, func: ast.AST, blocks: List[Block],
                 entry: Block, exit_block: Block):
        self.func = func
        self.blocks = blocks
        self.entry = entry
        self.exit = exit_block

    def reachable(self) -> set:
        """Block ids reachable from the entry."""
        seen = {self.entry.bid}
        work = [self.entry]
        while work:
            b = work.pop()
            for s in b.succs:
                if s.bid not in seen:
                    seen.add(s.bid)
                    work.append(s)
        return seen


class _WithFrame:
    __slots__ = ("node", "items")

    kind = "with"

    def __init__(self, node: ast.With, items):
        self.node = node
        self.items = list(items)


class _FinallyFrame:
    __slots__ = ("entry", "deferred")

    kind = "finally"

    def __init__(self, entry: Block):
        self.entry = entry
        #: jump targets routed through this finally; connected from the
        #: finally's last block once it has been lowered
        self.deferred: List[Block] = []


_LOOP_NODES = (ast.While, ast.For, ast.AsyncFor)


class _Builder:
    def __init__(self, func: ast.AST):
        self.func = func
        self.blocks: List[Block] = []
        self.entry = self._block()
        self.exit = self._block()
        #: current insertion block; None right after a jump
        self.cur: Optional[Block] = self.entry
        #: (header, loop exit, context-stack depth at loop entry)
        self.loops: List[tuple] = []
        #: open with/finally frames, innermost last
        self.ctx: List[Union[_WithFrame, _FinallyFrame]] = []
        #: (handler entry blocks, context-stack depth at try entry)
        self.handlers: List[tuple] = []

    # -- plumbing -----------------------------------------------------------

    def _block(self) -> Block:
        b = Block(len(self.blocks))
        self.blocks.append(b)
        return b

    def edge(self, a: Block, b: Block):
        if b not in a.succs:
            a.succs.append(b)
            b.preds.append(a)

    def current(self) -> Block:
        if self.cur is None:
            # statements after a jump: their own (unreachable) block
            self.cur = self._block()
        return self.cur

    def _jump(self, targets: Sequence[Block], depth: int):
        """Route control from the current block to ``targets``,
        unwinding context frames above ``depth``: open ``with`` frames
        emit their ``WithExit``s; a ``finally`` frame captures the
        targets and the jump lands on its entry instead."""
        cur = self.current()
        for frame in reversed(self.ctx[depth:]):
            if frame.kind == "with":
                for item in reversed(frame.items):
                    cur.stmts.append(WithExit(frame.node, item))
            else:  # finally: the jump continues from its last block
                frame.deferred.extend(targets)
                self.edge(cur, frame.entry)
                self.cur = None
                return
        for t in targets:
            self.edge(cur, t)
        self.cur = None

    # -- statement lowering -------------------------------------------------

    def lower(self, stmts: Sequence[ast.stmt]):
        for s in stmts:
            self._lower_stmt(s)

    def _lower_stmt(self, node: ast.stmt):
        if isinstance(node, ast.If):
            self._lower_if(node)
        elif isinstance(node, _LOOP_NODES):
            self._lower_loop(node)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            self._lower_with(node)
        elif isinstance(node, ast.Try):
            self._lower_try(node)
        elif hasattr(ast, "Match") and isinstance(node, ast.Match):
            self._lower_match(node)
        elif isinstance(node, ast.Return):
            self.current().stmts.append(node)
            self._jump([self.exit], 0)
        elif isinstance(node, ast.Raise):
            self.current().stmts.append(node)
            if self.handlers:
                entries, depth = self.handlers[-1]
                self._jump(entries, depth)
            else:
                self._jump([self.exit], 0)
        elif isinstance(node, ast.Break):
            if self.loops:
                _header, loop_exit, depth = self.loops[-1]
                self._jump([loop_exit], depth)
        elif isinstance(node, ast.Continue):
            if self.loops:
                header, _loop_exit, depth = self.loops[-1]
                self._jump([header], depth)
        else:
            # plain statement — nested defs/classes/lambdas included,
            # as opaque single statements of THIS scope
            self.current().stmts.append(node)

    def _lower_if(self, node: ast.If):
        header = self.current()
        header.stmts.append(node)  # header marker (carries the test)
        then = self._block()
        self.edge(header, then)
        self.cur = then
        self.lower(node.body)
        then_end = self.cur
        after = self._block()
        if then_end is not None:
            self.edge(then_end, after)
        if node.orelse:
            els = self._block()
            self.edge(header, els)
            self.cur = els
            self.lower(node.orelse)
            if self.cur is not None:
                self.edge(self.cur, after)
        else:
            self.edge(header, after)
        self.cur = after

    def _lower_loop(self, node):
        header = self._block()
        self.edge(self.current(), header)
        header.stmts.append(node)  # header marker (test / iterator)
        loop_exit = self._block()
        body = self._block()
        self.edge(header, body)
        self.loops.append((header, loop_exit, len(self.ctx)))
        self.cur = body
        self.lower(node.body)
        if self.cur is not None:
            self.edge(self.cur, header)  # back edge
        self.loops.pop()
        if node.orelse:
            els = self._block()
            self.edge(header, els)
            self.cur = els
            self.lower(node.orelse)
            if self.cur is not None:
                self.edge(self.cur, loop_exit)
        else:
            self.edge(header, loop_exit)
        self.cur = loop_exit

    def _lower_with(self, node):
        cur = self.current()
        for item in node.items:
            cur.stmts.append(WithEnter(node, item))
        frame = _WithFrame(node, node.items)
        self.ctx.append(frame)
        self.lower(node.body)
        self.ctx.pop()
        if self.cur is not None:
            cur = self.current()
            for item in reversed(node.items):
                cur.stmts.append(WithExit(node, item))

    def _lower_try(self, node: ast.Try):
        fin_frame: Optional[_FinallyFrame] = None
        if node.finalbody:
            fin_frame = _FinallyFrame(self._block())
            self.ctx.append(fin_frame)
        handler_entries = [self._block() for _ in node.handlers]
        body_start = len(self.blocks)
        start = self._block()
        self.edge(self.current(), start)
        self.cur = start
        if handler_entries:
            self.handlers.append((handler_entries, len(self.ctx)))
        self.lower(node.body)
        if handler_entries:
            self.handlers.pop()
            # an exception may occur anywhere in the body: edge every
            # block lowered for it (nested structure included) to every
            # handler entry
            for b in self.blocks[body_start:]:
                if b in handler_entries:
                    continue
                for h in handler_entries:
                    self.edge(b, h)
        if self.cur is not None and node.orelse:
            self.lower(node.orelse)
        normal_ends = [self.cur] if self.cur is not None else []
        for h_entry, handler in zip(handler_entries, node.handlers):
            self.cur = h_entry
            h_entry.stmts.append(handler)  # handler marker
            self.lower(handler.body)
            if self.cur is not None:
                normal_ends.append(self.cur)
        if fin_frame is not None:
            self.ctx.pop()
            for e in normal_ends:
                self.edge(e, fin_frame.entry)
            if not normal_ends and not fin_frame.deferred:
                # body and handlers all diverged without crossing the
                # finally (e.g. plain raises) — still reachable via the
                # exception path
                for b in self.blocks[body_start:]:
                    if not b.succs and b is not fin_frame.entry:
                        self.edge(b, fin_frame.entry)
            self.cur = fin_frame.entry
            self.lower(node.finalbody)
            after = self._block()
            if self.cur is not None:
                fin_end = self.cur
                self.edge(fin_end, after)
                for tgt in fin_frame.deferred:
                    self.edge(fin_end, tgt)
                # the uncaught-exception continuation re-raises
                self.edge(fin_end, self.exit)
            self.cur = after
        else:
            after = self._block()
            for e in normal_ends:
                self.edge(e, after)
            self.cur = after

    def _lower_match(self, node):
        header = self.current()
        header.stmts.append(node)  # header marker (carries the subject)
        after = self._block()
        for case in node.cases:
            b = self._block()
            self.edge(header, b)
            self.cur = b
            self.lower(case.body)
            if self.cur is not None:
                self.edge(self.cur, after)
        self.edge(header, after)  # no case matched
        self.cur = after


def build_cfg(func: ast.AST) -> CFG:
    """Lower one function def (or lambda) to a CFG.

    Only the function's OWN body is lowered — nested function/class
    definitions appear as single opaque statements; build a separate CFG
    for each (``ModuleIndex.functions`` lists them all).
    """
    b = _Builder(func)
    if isinstance(func, ast.Lambda):
        b.current().stmts.append(func.body)
    else:
        body = getattr(func, "body", None)
        if not isinstance(body, list):
            raise TypeError(
                f"build_cfg expects a function def or lambda, got "
                f"{type(func).__name__}")
        b.lower(body)
    if b.cur is not None:
        b.edge(b.cur, b.exit)
    return CFG(func, b.blocks, b.entry, b.exit)
