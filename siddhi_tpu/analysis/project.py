"""Whole-program index: imports, class hierarchies, and the call graph.

``ModuleIndex`` (``index.py``) resolves names lexically within one file;
that leaves the engine's riskiest constructs invisible — mixin state
(``ConnectRetryMixin`` methods run as ``threading.Timer`` targets of
classes defined two modules away), jitted callables imported from
helper modules, and planner fallback handlers that delegate logging and
counting to functions in other files.  ``ProjectIndex`` layers the
cross-module resolution every rule shares:

- **import maps** — per module, local name → fully-qualified target,
  covering ``import a.b``, ``import a.b as x``, ``from a.b import c``
  (aliased or not) and relative forms (``from . import x``,
  ``from ..pkg.mod import y``), collected from the whole tree so
  function-local imports (the planner's habit) resolve too;
- **symbol chasing** — a name imported from a package ``__init__``
  re-export is followed one hop at a time (cycle-guarded) to the
  defining module;
- **class hierarchy** — C3 linearization (MRO) over *project-local*
  bases, mixins and diamonds included; external bases (``object``,
  stdlib classes) are ignored, keeping the analysis conservative;
- **method resolution** — ``resolve_method(cls, name)`` walks the MRO
  exactly like runtime attribute lookup, so ``self.<method>`` thread
  targets and dispatch edges land on the defining module;
- **call graph** — conservative def→call edges through plain names
  (enclosing-scope chain, module functions, imports), ``self.``/
  ``cls.`` dispatch, imported-module attributes, and
  ``functools.partial``/wrapper first-arguments.

What is deliberately NOT followed (documented contract, mirrored in the
README): attribute calls on arbitrary objects (``engine.make_step()``
— no type inference), values stored into containers, dynamic
``getattr``, and anything outside the indexed package.  Rules stay
conservative-by-construction: an unresolved edge is a skipped edge,
never a guess.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .index import ModuleIndex


def module_name_of(rel: str) -> str:
    """Dotted module name of a repo-relative path
    (``siddhi_tpu/core/stream.py`` → ``siddhi_tpu.core.stream``;
    ``pkg/__init__.py`` → ``pkg``)."""
    parts = rel.split("/")
    if parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def plain_dotted(node: ast.AST) -> Optional[str]:
    """Dotted chain WITHOUT the ``self``/``cls`` elision of
    ``index.dotted_name`` — callers that need receiver identity
    (call-graph edges) must distinguish ``self.m`` from plain ``m``."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _c3_merge(seqs: List[List[str]]) -> Optional[List[str]]:
    """C3 linearization merge; None when inconsistent."""
    result: List[str] = []
    seqs = [list(s) for s in seqs if s]
    while seqs:
        for seq in seqs:
            head = seq[0]
            if not any(head in s[1:] for s in seqs):
                break
        else:
            return None  # inconsistent hierarchy
        result.append(head)
        seqs = [[x for x in s if x != head] for s in seqs]
        seqs = [s for s in seqs if s]
    return result


class ProjectIndex:
    """Cross-module resolution over a set of ``ModuleIndex``es."""

    def __init__(self, indexes: Sequence[ModuleIndex]):
        self.indexes: List[ModuleIndex] = list(indexes)
        #: dotted module name -> ModuleIndex
        self.by_module: Dict[str, ModuleIndex] = {}
        #: ModuleIndex id -> dotted module name
        self._mod_of: Dict[int, str] = {}
        for idx in self.indexes:
            mod = module_name_of(idx.rel)
            self.by_module[mod] = idx
            self._mod_of[id(idx)] = mod
        #: fully-qualified function name -> (index, def node)
        self.functions: Dict[str, Tuple[ModuleIndex, ast.AST]] = {}
        #: fully-qualified class name -> (index, ClassDef)
        self.classes: Dict[str, Tuple[ModuleIndex, ast.ClassDef]] = {}
        for mod, idx in self.by_module.items():
            for qual, fn in idx.functions.items():
                self.functions[f"{mod}.{qual}"] = (idx, fn)
            for qual, cls in idx.classes.items():
                self.classes[f"{mod}.{qual}"] = (idx, cls)
        #: module -> {local name -> fully-qualified target}
        self.imports: Dict[str, Dict[str, str]] = {
            mod: self._collect_imports(mod, idx)
            for mod, idx in self.by_module.items()
        }
        self._mro_cache: Dict[str, List[str]] = {}
        self._methods_cache: Dict[
            str, Dict[str, Tuple[ModuleIndex, ast.AST, str]]] = {}

    # -- imports --------------------------------------------------------------

    def module_of(self, idx: ModuleIndex) -> str:
        return self._mod_of[id(idx)]

    def _collect_imports(self, mod: str, idx: ModuleIndex
                         ) -> Dict[str, str]:
        is_pkg = idx.rel.endswith("__init__.py")
        pkg_parts = mod.split(".") if is_pkg else mod.split(".")[:-1]
        out: Dict[str, str] = {}
        for node in ast.walk(idx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        out[alias.asname] = alias.name
                    else:
                        # `import a.b.c` binds `a`; dotted chains resolve
                        # through the identity mapping of the root
                        root = alias.name.split(".")[0]
                        out.setdefault(root, root)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base_parts = pkg_parts[:len(pkg_parts)
                                           - (node.level - 1)]
                    if node.level - 1 > len(pkg_parts):
                        continue  # beyond the indexed root
                else:
                    base_parts = []
                if node.module:
                    base_parts = base_parts + node.module.split(".")
                base = ".".join(base_parts)
                for alias in node.names:
                    if alias.name == "*":
                        continue  # star imports are not followed
                    local = alias.asname or alias.name
                    out[local] = f"{base}.{alias.name}" if base \
                        else alias.name
        return out

    # -- symbol resolution ----------------------------------------------------

    def expand(self, mod: str, dotted: str) -> str:
        """Fully-qualified form of ``dotted`` as seen from ``mod``
        (import map applied to the head; module-local otherwise)."""
        parts = dotted.split(".")
        imp = self.imports.get(mod, {})
        if parts[0] in imp:
            return ".".join([imp[parts[0]]] + parts[1:])
        return f"{mod}.{dotted}"

    def _chase(self, fq: str, seen: Set[str]):
        """('function'|'class', fq) following one re-export hop at a
        time; None when the symbol leaves the project."""
        if fq in seen:
            return None
        seen.add(fq)
        if fq in self.functions:
            return ("function", fq)
        if fq in self.classes:
            return ("class", fq)
        parts = fq.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:i])
            if mod in self.by_module:
                rest = parts[i:]
                imp = self.imports.get(mod, {})
                if rest and rest[0] in imp:
                    new = ".".join([imp[rest[0]]] + rest[1:])
                    return self._chase(new, seen)
                return None
        return None

    def resolve_symbol(self, mod: str, dotted: str):
        """('function'|'class', fq) for a dotted name as seen from
        ``mod``, or None."""
        hit = self._chase(self.expand(mod, dotted), set())
        if hit is None and "." not in dotted:
            # maybe a module-level name shadowed by the expand() head
            # rule — nothing else to try
            return None
        return hit

    def resolve_function_name(self, idx: ModuleIndex, scope: str,
                              name: str
                              ) -> Optional[Tuple[ModuleIndex, ast.AST, str]]:
        """Resolve a bare ``name(...)`` call made inside ``scope`` of
        module ``idx``: enclosing-scope chain first (nested defs), then
        module level, then imports.  Returns (index, def, fq)."""
        mod = self.module_of(idx)
        parts = scope.split(".") if scope != "<module>" else []
        while True:
            qual = ".".join(parts + [name]) if parts else name
            fn = idx.functions.get(qual)
            if fn is not None:
                return (idx, fn, f"{mod}.{qual}")
            if not parts:
                break
            parts.pop()
        hit = self.resolve_symbol(mod, name)
        if hit is not None and hit[0] == "function":
            f_idx, fn = self.functions[hit[1]]
            return (f_idx, fn, hit[1])
        return None

    def resolve_dotted_function(self, idx: ModuleIndex, dotted: str
                                ) -> Optional[Tuple[ModuleIndex, ast.AST, str]]:
        """Resolve an ``a.b.f(...)`` receiver chain rooted at an import
        (``plane_pack.pack_bits``); None for plain names (use
        ``resolve_function_name``) and unresolvable roots."""
        hit = self.resolve_symbol(self.module_of(idx), dotted)
        if hit is not None and hit[0] == "function":
            f_idx, fn = self.functions[hit[1]]
            return (f_idx, fn, hit[1])
        return None

    # -- class hierarchy ------------------------------------------------------

    def resolve_class(self, mod: str, dotted: str) -> Optional[str]:
        hit = self.resolve_symbol(mod, dotted)
        return hit[1] if hit is not None and hit[0] == "class" else None

    def bases_of(self, fq_class: str) -> List[str]:
        idx, cls = self.classes[fq_class]
        mod = self.module_of(idx)
        out = []
        for b in cls.bases:
            name = plain_dotted(b)
            if not name:
                continue
            fq = self.resolve_class(mod, name)
            if fq is not None:
                out.append(fq)
        return out

    def mro(self, fq_class: str) -> List[str]:
        """C3 linearization over project-local bases (falls back to a
        left-to-right DFS dedup when C3 rejects the hierarchy)."""
        cached = self._mro_cache.get(fq_class)
        if cached is not None:
            return cached
        self._mro_cache[fq_class] = [fq_class]  # cycle guard
        parents = [p for p in self.bases_of(fq_class) if p != fq_class]
        merged = _c3_merge(
            [[fq_class]] + [list(self.mro(p)) for p in parents]
            + [list(parents)])
        if merged is None:  # inconsistent: conservative DFS dedup
            merged, seen = [fq_class], {fq_class}
            for p in parents:
                for c in self.mro(p):
                    if c not in seen:
                        seen.add(c)
                        merged.append(c)
        self._mro_cache[fq_class] = merged
        return merged

    def class_methods(self, fq_class: str
                      ) -> Dict[str, Tuple[ModuleIndex, ast.AST, str]]:
        """name -> (index, def, owner class fq), merged over the MRO
        (most-derived definition wins, like runtime lookup)."""
        cached = self._methods_cache.get(fq_class)
        if cached is not None:
            return cached
        out: Dict[str, Tuple[ModuleIndex, ast.AST, str]] = {}
        for c in reversed(self.mro(fq_class)):
            idx, cls = self.classes[c]
            for n in cls.body:
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out[n.name] = (idx, n, c)
        self._methods_cache[fq_class] = out
        return out

    def resolve_method(self, fq_class: str, name: str
                       ) -> Optional[Tuple[ModuleIndex, ast.AST, str]]:
        return self.class_methods(fq_class).get(name)

    def enclosing_class_fq(self, idx: ModuleIndex, node: ast.AST
                           ) -> Optional[str]:
        cls = idx.enclosing(node, (ast.ClassDef,))
        if cls is None:
            return None
        return f"{self.module_of(idx)}.{idx.def_qualname(cls)}"

    # -- call graph -----------------------------------------------------------

    def resolve_call(self, idx: ModuleIndex, call: ast.Call
                     ) -> Optional[Tuple[ModuleIndex, ast.AST, str]]:
        """(index, def, fq) of the function a call statically dispatches
        to; None when the receiver cannot be resolved without type
        inference.  ``functools.partial(f, ...)`` resolves to ``f``."""
        func = call.func
        if isinstance(func, ast.Name):
            hit = self.resolve_function_name(
                idx, idx.qualname(call), func.id)
            if hit is not None:
                leaf = hit[2].rsplit(".", 1)[-1]
                if leaf == "partial" and call.args:
                    return self._resolve_value(idx, call, call.args[0])
                return hit
            # partial imported from functools resolves outside the
            # project; still follow its first argument
            if func.id == "partial" and call.args:
                return self._resolve_value(idx, call, call.args[0])
            return None
        if isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name) and \
                    func.value.id in ("self", "cls"):
                owner = self.enclosing_class_fq(idx, call)
                if owner is not None:
                    return self.resolve_method(owner, func.attr)
                return None
            dotted = plain_dotted(func)
            if dotted is None:
                return None
            if dotted.endswith(".partial") and call.args:
                return self._resolve_value(idx, call, call.args[0])
            return self.resolve_dotted_function(idx, dotted)
        return None

    def _resolve_value(self, idx: ModuleIndex, site: ast.AST,
                       value: ast.AST
                       ) -> Optional[Tuple[ModuleIndex, ast.AST, str]]:
        """Resolve a callable VALUE (partial/wrapper argument)."""
        if isinstance(value, ast.Lambda):
            return (idx, value, f"{self.module_of(idx)}."
                                f"{idx.qualname(value)}.<lambda>")
        if isinstance(value, ast.Call):
            if value.args:
                return self._resolve_value(idx, site, value.args[0])
            return None
        if isinstance(value, ast.Name):
            return self.resolve_function_name(
                idx, idx.qualname(site), value.id)
        if isinstance(value, ast.Attribute):
            dotted = plain_dotted(value)
            if dotted is None:
                return None
            if isinstance(value.value, ast.Name) and \
                    value.value.id in ("self", "cls"):
                owner = self.enclosing_class_fq(idx, site)
                if owner is not None:
                    return self.resolve_method(owner, value.attr)
                return None
            return self.resolve_dotted_function(idx, dotted)
        return None

    def iter_calls_reachable(self, idx: ModuleIndex,
                             roots: Sequence[ast.AST],
                             max_defs: int = 200
                             ) -> Iterator[Tuple[ModuleIndex, ast.Call]]:
        """Every call lexically inside ``roots`` plus, transitively,
        inside the bodies of project-resolved callees — the shared BFS
        behind reachability rules (fallback-discipline, jit-purity's
        helper following).  Yields ``(defining index, call)`` pairs;
        ``max_defs`` bounds runaway closures."""
        work: List[Tuple[ModuleIndex, ast.AST]] = [
            (idx, r) for r in roots]
        visited: Set[Tuple[int, int]] = set()
        expanded = 0
        while work:
            cur_idx, node = work.pop()
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                yield (cur_idx, sub)
                hit = self.resolve_call(cur_idx, sub)
                if hit is None:
                    continue
                t_idx, t_fn, t_fq = hit
                key = (id(t_idx), id(t_fn))
                if key in visited or expanded >= max_defs:
                    continue
                visited.add(key)
                expanded += 1
                work.append((t_idx, t_fn))


def build_project(indexes: Sequence[ModuleIndex]) -> ProjectIndex:
    return ProjectIndex(indexes)
