"""Module indexing: one parse per file, shared by every rule.

The three retired guard tests each re-implemented file walking, AST
parsing and qualified-name ("``Class.method``") scope resolution.  This
module does that work once: a ``ModuleIndex`` parses a file a single
time and precomputes the structures every rule needs —

- ``qualname(node)``  — the dotted function/class scope enclosing any
  AST node (``DeviceQueryRuntime.process_stream_batch``), resolved from
  a parent map rather than per-rule visitor stacks;
- ``dotted(call)``    — the receiver chain of a call as a dotted string
  (``self.jax.jit`` → ``jax.jit`` with the leading ``self`` elided, so
  rules match engines holding jax as an attribute and plain imports
  alike);
- ``functions``       — every function/lambda def keyed by qualified
  name, for rules that resolve a callable argument to its definition.

Rules receive the index and never re-parse; ``index_package`` walks a
package root once and yields indexes sorted by path so reports are
deterministic.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Tuple

_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)


def dotted_name(node: ast.AST) -> Optional[str]:
    """Dotted receiver chain of an expression (``a.b.c``), or None when
    any link is not a plain name/attribute (calls, subscripts, ...).
    A leading ``self``/``cls`` is elided so ``self.jax.jit`` and
    ``jax.jit`` compare equal."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        if node.id not in ("self", "cls"):
            parts.append(node.id)
    elif not parts:
        return None
    elif not isinstance(node, ast.Name):
        return None
    return ".".join(reversed(parts)) if parts else None


class ModuleIndex:
    """One parsed module plus the lookups every rule shares."""

    #: process-wide count of actual ``ast.parse`` runs — tests assert
    #: the (path, mtime, size) cache keeps this at one per file
    parse_count: int = 0

    def __init__(self, path: Path, rel: str, source: Optional[str] = None):
        ModuleIndex.parse_count += 1
        self.path = Path(path)
        self.rel = rel  # repo-relative posix path used in findings
        self.source = self.path.read_text() if source is None else source
        self.tree = ast.parse(self.source)
        self._parents: Dict[ast.AST, ast.AST] = {}
        self._qualnames: Dict[ast.AST, str] = {}
        #: qualified name -> FunctionDef/AsyncFunctionDef (module scope
        #: and nested defs alike; lambdas are not named so not listed)
        self.functions: Dict[str, ast.AST] = {}
        #: qualified name -> ClassDef
        self.classes: Dict[str, ast.ClassDef] = {}
        self._build(self.tree, ())

    def _build(self, node: ast.AST, scope: Tuple[str, ...]):
        for child in ast.iter_child_nodes(node):
            self._parents[child] = node
            child_scope = scope
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                child_scope = scope + (child.name,)
                qual = ".".join(child_scope)
                self._qualnames[child] = qual
                if isinstance(child, ast.ClassDef):
                    self.classes[qual] = child
                else:
                    self.functions[qual] = child
            self._build(child, child_scope)

    # -- scope resolution ---------------------------------------------------

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def def_qualname(self, node: ast.AST) -> str:
        """Qualified name OF a function/class def node itself (falls
        back to the enclosing scope for lambdas and other nodes)."""
        return self._qualnames.get(node) or self.qualname(node)

    def qualname(self, node: ast.AST) -> str:
        """Qualified name of the innermost function/class scope that
        contains ``node`` (``"<module>"`` at module level)."""
        cur = self._parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                return self._qualnames[cur]
            cur = self._parents.get(cur)
        return "<module>"

    def enclosing(self, node: ast.AST, kinds=_SCOPES) -> Optional[ast.AST]:
        """Innermost enclosing node of the given AST types."""
        cur = self._parents.get(node)
        while cur is not None:
            if isinstance(cur, kinds):
                return cur
            cur = self._parents.get(cur)
        return None

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    # -- shared predicates --------------------------------------------------

    def dotted(self, node: ast.AST) -> Optional[str]:
        return dotted_name(node)

    def calls(self) -> Iterator[ast.Call]:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                yield node

    def under_lock(self, node: ast.AST) -> bool:
        """True when ``node`` sits lexically inside a ``with`` block
        whose context expression is a dotted name ending in ``lock``
        (``self._lock``, ``ctx.process_lock``, ``cls._retry_lock``...).
        The lexical check is deliberately conservative: lock handoffs a
        rule cannot see (e.g. "caller always holds the lock") belong in
        that rule's allowlist with a written justification."""
        for anc in self.ancestors(node):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    name = dotted_name(item.context_expr)
                    if name and name.split(".")[-1].lower().endswith("lock"):
                        return True
        return False


#: (absolute path, rel) -> (mtime_ns, size, ModuleIndex) — one parse
#: per file per analyzer run: the CLI, the clean-package test, and any
#: rule-subset re-run inside one process share parsed indexes as long
#: as the file on disk is byte-identical (mtime+size key).
_PARSE_CACHE: dict = {}


def index_package(root: Path, rel_base: Optional[Path] = None,
                  exclude: Tuple[str, ...] = ("analysis",),
                  cache: bool = True) -> List[ModuleIndex]:
    """Parse every ``*.py`` under ``root`` once, sorted by path.

    ``exclude`` names top-level subpackages to skip, repo-relative to
    ``root`` — the analysis package itself is excluded by default (its
    fixture strings and banned-call tables would trip the very rules
    they implement).  Parses are memoized on (path, mtime, size) so
    repeated runs in one process re-use one ``ModuleIndex`` per file;
    ``cache=False`` forces a fresh parse."""
    root = Path(root)
    rel_base = Path(rel_base) if rel_base is not None else root.parent
    out: List[ModuleIndex] = []
    for path in sorted(root.rglob("*.py")):
        parts = path.relative_to(root).parts
        if parts and parts[0] in exclude:
            continue
        rel = path.relative_to(rel_base).as_posix()
        st = path.stat()
        key = (str(path), rel)
        hit = _PARSE_CACHE.get(key)
        if cache and hit is not None and \
                hit[0] == st.st_mtime_ns and hit[1] == st.st_size:
            out.append(hit[2])
            continue
        mi = ModuleIndex(path, rel)
        _PARSE_CACHE[key] = (st.st_mtime_ns, st.st_size, mi)
        out.append(mi)
    return out
