"""Curated per-rule allowlists — every entry says WHY it is sanctioned.

Keys are line-number-free (``<relpath>:<Class.method-or-attr>``) so
unrelated edits don't churn the lists, and entries EXPIRE: one that no
longer matches a finding fails the run as ``stale-allowlist`` (see
``framework.Allowlist.split``), so these lists only ever shrink when
the code improves.

Bucket vocabulary carried over from the retired guard tests:

- ``host-sync-hazard``: *ingest* — converting HOST inputs (cols/ts/
  keys) before device_put; *drain* — the coalesced fetch +
  deferred-emit materializers; *barrier* — snapshot/restore/timer
  paths, already behind drain(); *stats* — slow-polled gauges.
- ``ingest-put-bypass``: *staging* — the sanctioned wrapper itself;
  *mesh* — sharding helpers placing STATE rows (one-time/barrier
  placement, not per-batch event data); *state* — engine state init /
  re-anchor barriers (arming ``ingest.put`` there would skew the
  injector's per-batch fault cadence).
"""

_E = "siddhi_tpu/core/emit_queue.py"
_DS = "siddhi_tpu/core/device_single.py"
_DP = "siddhi_tpu/core/dense_pattern.py"
_DQ = "siddhi_tpu/ops/device_query.py"
_DN = "siddhi_tpu/ops/dense_nfa.py"
_SH = "siddhi_tpu/parallel/device_shard.py"
_M = "siddhi_tpu/parallel/mesh.py"

ALLOWLISTS = {
    "host-sync-hazard": {
        f"{_E}:fetch_coalesced":
            "drain: THE sanctioned coalesced device→host fetch",
        f"{_DS}:DeviceQueryRuntime.process_stream_batch":
            "ingest: converts HOST batch cols/ts before staged_put",
        f"{_DS}:DeviceQueryRuntime.snapshot":
            "barrier: snapshot path, behind drain()",
        f"{_DS}:DeviceQueryRuntime.restore":
            "barrier: restore path, behind drain()",
        f"{_DP}:DensePatternRuntime.intern_keys":
            "ingest: host-side key interning before device routing",
        f"{_DP}:DensePatternRuntime._intern_keys_dict":
            "ingest: host-side key interning before device routing",
        f"{_DP}:DensePatternRuntime._rebuild_key_index":
            "ingest: host-side key-index rebuild on purge",
        f"{_DP}:DensePatternRuntime.process_stream_batch":
            "ingest: converts HOST batch cols/ts before staged_put",
        f"{_DP}:DensePatternRuntime.purge_idle":
            "barrier: idle purge, behind drain()",
        f"{_DP}:DensePatternRuntime.on_time":
            "barrier: timer step, behind drain()",
        f"{_DP}:DensePatternRuntime.snapshot":
            "barrier: snapshot path, behind drain()",
        f"{_DP}:DensePatternRuntime.restore":
            "barrier: restore path, behind drain()",
        f"{_DP}:DensePatternRuntime.stats":
            "stats: slow-polled pattern_state gauge",
        f"{_DQ}:_split_i64":
            "ingest: splits HOST int64 cols into device i32 lanes",
        f"{_DQ}:DeviceQueryEngine._host_env":
            "ingest: HOST lane view for the null-safe probe",
        f"{_DQ}:DeviceQueryEngine._intern_groups":
            "ingest: host-side group interning",
        f"{_DQ}:DeviceQueryEngine._intern_wgroups":
            "ingest: host-side window-group interning",
        f"{_DQ}:DeviceQueryEngine.host_lane_cols":
            "ingest: HOST lane materialization for host fallbacks",
        f"{_DQ}:DeviceQueryEngine._pad":
            "ingest: pads HOST cols to the pow-2 batch shape",
        f"{_DQ}:DeviceQueryEngine._host_filter_mask":
            "ingest: null-safe HOST filter probe",
        f"{_DQ}:DeviceQueryEngine.process_batch_deferred":
            "ingest: converts HOST batch inputs before staged_put",
        f"{_DQ}:DeviceQueryEngine._deferred_chunk":
            "ingest: converts HOST chunk inputs before staged_put",
        f"{_DQ}:DeviceQueryEngine._acc_segment":
            "ingest: converts HOST segment inputs before the acc step",
        f"{_DQ}:DeviceQueryEngine._out_columns":
            "drain: deferred-emit column materializer",
        f"{_DQ}:DeviceQueryEngine._flush_cols":
            "barrier: pane flush, behind drain()",
        f"{_DQ}:DeviceQueryEngine.purge_idle_keys":
            "barrier: key purge, behind drain()",
        f"{_DQ}:DeviceQueryEngine.host_restore":
            "barrier: restore path, behind drain()",
        f"{_DQ}:DeferredDeviceEmit.materialize":
            "drain: deferred-emit materializer (runs on fetched host arrays)",
        f"{_DQ}:DeferredDeviceEmit._concat_parts":
            "drain: deferred-emit materializer (runs on fetched host arrays)",
        f"{_DQ}:DeferredDeviceEmit.resolve":
            "drain: deferred-emit materializer (runs on fetched host arrays)",
        f"{_DN}:DensePatternEngine.prepare_cols":
            "ingest: converts HOST batch cols before staged_put",
        f"{_DN}:DensePatternEngine.process_deferred":
            "ingest: converts HOST batch inputs before staged_put",
        f"{_DN}:DensePatternEngine.on_time_state":
            "barrier: deadline-timer step, behind drain()",
        f"{_DN}:DensePatternEngine.maybe_re_anchor":
            "barrier: ts re-anchor, behind drain()",
        f"{_DN}:DeferredDenseEmit.materialize":
            "drain: deferred-emit materializer (runs on fetched host arrays)",
        f"{_DN}:DeferredDenseEmit.resolve":
            "drain: deferred-emit materializer (runs on fetched host arrays)",
        f"{_SH}:ShardedDeviceQueryEngine.init_state":
            "ingest: builds HOST state rows before mesh placement",
        f"{_SH}:ShardedDeviceQueryEngine.put_state":
            "barrier: state (re)placement on the mesh",
        f"{_SH}:ShardedDeviceQueryEngine.process_batch_deferred":
            "ingest: converts HOST batch inputs before staged_put",
        f"{_SH}:ShardedDeviceQueryEngine._deferred_chunk":
            "ingest: converts HOST chunk inputs before staged_put",
        f"{_SH}:ShardedDeviceQueryEngine._sliding_chunk":
            "ingest: converts HOST chunk inputs before staged_put",
        f"{_SH}:ShardedDeviceQueryEngine._acc_segment":
            "ingest: converts HOST segment inputs before the acc step",
        f"{_M}:make_mesh":
            "ingest: host-side mesh construction",
        f"{_M}:route_to_shards":
            "ingest: host-side shard routing of HOST batches",
        f"{_M}:ShardedPatternEngine.route":
            "ingest: host-side shard routing of HOST batches",
        f"{_M}:ShardedPatternEngine.process_deferred":
            "ingest: converts HOST batch inputs before device placement",
    },
    "ingest-put-bypass": {
        "siddhi_tpu/core/ingest_stage.py:staged_put":
            "staging: the sanctioned wrapper itself (arms ingest.put)",
        f"{_M}:ShardedPatternEngine._put":
            "mesh: STATE-row placement; batch-path faults still flow "
            "through staged_put in parallel/device_shard.py",
        f"{_DN}:DensePatternEngine.init_state":
            "state: one-time engine state initialization, not ingest",
        f"{_DN}:DensePatternEngine.maybe_re_anchor":
            "state: ts re-anchor barrier; arming ingest.put here would "
            "skew the injector's per-batch fault cadence",
    },
    "broad-except-swallow": {
        # empty: every broad swallow on the processing path logs,
        # counts, or re-routes today
    },
    "lock-discipline": {
        # empty: every conflict the lexical pass can see is also seen —
        # and reported once — by the flow-sensitive lockset-race rule
        # below (the wrapper stands down on shared keys); entries live
        # under "lockset-race" now, with the same key shape
    },
    "lockset-race": {
        "siddhi_tpu/core/app_runtime.py:SiddhiAppRuntime._snapshot_svc":
            "replan() clears the lazy cache from the main path, but "
            "only inside the process-lock barrier with sources paused, "
            "device emits drained, and the persist daemon flushed — no "
            "thread entry can race the clear; the lazy re-init itself "
            "is idempotent (same service rebuilt from the same parts)",
        "siddhi_tpu/core/app_runtime.py:SiddhiAppRuntime._durab_stats":
            "replan() clears the lazy cache from the main path, but "
            "only inside the process-lock barrier with the persist "
            "daemon flushed; re-init is idempotent",
        "siddhi_tpu/core/app_runtime.py:SiddhiAppRuntime._ckpt_writer":
            "replan() clears the lazy cache from the main path, but "
            "only inside the process-lock barrier with the persist "
            "daemon flushed; re-init is idempotent",
        "siddhi_tpu/robustness/watchdog.py:Watchdog._last_progress":
            "single-writer lifecycle handshake: start() stamps it once "
            "BEFORE the daemon thread exists (Thread.start is the "
            "happens-before edge), and every later write is from the "
            "daemon thread itself (_tick/_trip) — there is never a "
            "concurrent second writer, and a float store is GIL-atomic",
        "siddhi_tpu/core/stream.py:StreamJunction._running":
            "GIL-atomic monotonic bool handshake: the worker only ever "
            "clears it (sentinel mid-coalesce), lifecycle writes happen "
            "before thread start / after join; no compound "
            "read-modify-write on either side, and taking a lock in "
            "send() would serialize the hot fan-out path",
    },
    "lock-order-deadlock": {
        # empty: the global acquisition-order graph is acyclic today
        # (process_lock strictly outermost, component locks leaf-only)
    },
    "barrier-flush-completeness": {
        # empty: StreamJunction.stop drains _queue, Sink.shutdown
        # flushes _spool (final-barrier flush added with this rule)
    },
    "jit-purity": {
        # the cross-module helper scan reaches host-level dispatchers
        # and kernel builders whose int()/float()/bool() casts act on
        # STATIC values (shapes, python scalars, config sets) — legal
        # at trace time; the cast heuristic cannot prove staticness
        # without type inference, so each is sanctioned by hand:
        "siddhi_tpu/kernels/bank_scatter.py:segmented_reduce":
            "int(rows.shape[0]) / int(r_pad): static shape + python int "
            "forming the compile-cache key, not tracer material",
        "siddhi_tpu/kernels/scan_chain.py:_build":
            "float(neg) of a python scalar at build time — deliberately "
            "a weak python float so Pallas sees a literal, not a const",
        "siddhi_tpu/kernels/scan_chain.py:fused_scan":
            "int(H)/int(n)/int(S)/float(neg): static shape unpack + "
            "python scalar forming the compile-cache key",
        "siddhi_tpu/ops/device_query.py:DeviceQueryEngine.make_step.step":
            "bool(kinds & {...}) on a python set of aggregation kinds — "
            "static config closed over at trace time, not a tracer",
    },
    "retrace-hazard": {
        # hot-sounding names that are actually plan-time, one-shot:
        "siddhi_tpu/planner/kernels.py:try_enable_scan_kernel":
            "smoke_lower() jits once per app creation to validate the "
            "Pallas lowering before committing the packed step — plan "
            "time, never on the batch path",
        "siddhi_tpu/planner/kernels.py:try_enable_bank_kernel":
            "smoke_lower() jits once per app creation to validate the "
            "Pallas lowering before committing the segmented reduce — "
            "plan time, never on the batch path",
    },
    "fallback-discipline": {
        "siddhi_tpu/planner/monitor.py:PlanMonitor.decide":
            "the skipped candidate was already log.warning'd AND "
            "counted (record_planner_fallback) at plan time by "
            "costmodel.build_plan_record; decide() re-checks the same "
            "static composability every tick only to keep infeasible "
            "paths out of the re-score — repeating the count each tick "
            "would inflate the fallback counters without new events",
        "siddhi_tpu/planner/fusion.py:_try_lower_chain":
            "delegates to the `fallback` callback built in "
            "plan_fused_chains (log.warning + record_fused_fallback) "
            "and passed as a parameter — parameter-passed callables are "
            "outside the call graph's documented resolution scope",
    },
    "thread-lifecycle": {
        # empty: every spawn site is daemon=True or joined/cancelled on
        # a shutdown path today
    },
    "bounded-queue-discipline": {
        # empty: every deque/Queue in core/, transport/ and robustness/
        # states its bound at the construction site today
    },
}
