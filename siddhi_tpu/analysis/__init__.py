"""siddhi_tpu.analysis — unified static-analysis pass for the engine's
un-typeable invariants.

The engine's performance and crash-recovery guarantees rest on contracts
the type system cannot see: device→host transfers only through the
count-gated drain (``core/emit_queue.py``), H2D puts only through
``staged_put`` (``core/ingest_stage.py``), no fault swallowed without a
log line or counter, no host clock / logging / materialization inside a
jitted step, no compile-cache churn on the per-batch path, no
cross-thread attribute write outside the engine lock, every planner
fallback both logged and counted, and every thread daemon-or-joined.

This package enforces them as one reusable pass — the compile-time
analog of the paper's query-validation phase:

- ``index``      — single-parse-per-module ``ModuleIndex`` with
                   qualified-name scope resolution shared by every rule,
                   memoized on ``(path, mtime, size)``
- ``project``    — whole-program ``ProjectIndex``: import maps, C3 MRO
                   over project-local classes, conservative call graph
- ``framework``  — ``Rule`` base class + registry, ``Finding``,
                   allowlists with required justifications, stale-entry
                   expiry
- ``rules/``     — one module per rule (eight registered today)
- ``reporting``  — text / JSON / SARIF 2.1.0 reporters, ``--baseline``
                   support
- ``__main__``   — ``python -m siddhi_tpu.analysis`` CLI (also exposed
                   as the ``siddhi-tpu-analysis`` console script)

Run ``python -m siddhi_tpu.analysis --list-rules`` for the catalog.
"""

from .framework import (  # noqa: F401
    Allowlist,
    Finding,
    Rule,
    all_rules,
    get_rule,
    register,
    run_rules,
)
from .index import ModuleIndex, index_package  # noqa: F401

# importing the subpackage registers every built-in rule
from . import rules  # noqa: F401,E402

__all__ = [
    "Allowlist",
    "Finding",
    "ModuleIndex",
    "Rule",
    "all_rules",
    "get_rule",
    "index_package",
    "register",
    "run_rules",
]
