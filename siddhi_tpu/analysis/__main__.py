"""``python -m siddhi_tpu.analysis`` — run the invariant pass.

Exit status 0 when the package is clean (zero unbaselined findings,
no stale allowlist entries), 1 otherwise, 2 on usage errors.

Examples::

    python -m siddhi_tpu.analysis                  # whole package, text
    python -m siddhi_tpu.analysis --format json
    python -m siddhi_tpu.analysis --format sarif   # SARIF 2.1.0 for CI
    python -m siddhi_tpu.analysis --list-rules
    python -m siddhi_tpu.analysis --rules jit-purity,retrace-hazard
    python -m siddhi_tpu.analysis --baseline analysis_baseline.json
    python -m siddhi_tpu.analysis --write-baseline analysis_baseline.json
    python -m siddhi_tpu.analysis --changed-only origin/main  # pre-push

``--changed-only GITREF`` is the pre-push check: the whole package is
still indexed (the parse cache makes that one parse per file, and the
whole-program rules need the full call graph anyway), but only
findings in modules that differ from ``GITREF`` are reported, and
allowlist staleness — a whole-list property — is not judged.
"""

from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path

from .framework import all_rules, get_rule, run_rules
from .index import index_package
from . import reporting


def changed_rels(rel_base: Path, gitref: str):
    """Repo-relative paths that differ from ``gitref`` (committed,
    staged, or worktree changes) plus untracked files."""
    out = set()
    for cmd in (["git", "diff", "--name-only", gitref],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        proc = subprocess.run(
            cmd, cwd=rel_base, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"{' '.join(cmd)} failed: {proc.stderr.strip()}")
        out.update(line.strip() for line in proc.stdout.splitlines()
                   if line.strip())
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m siddhi_tpu.analysis",
        description=("Unified static-analysis pass for siddhi_tpu's "
                     "device-contract, concurrency, and jit-purity "
                     "invariants."))
    parser.add_argument(
        "--root", default=None,
        help="package directory to scan (default: the installed "
             "siddhi_tpu package)")
    parser.add_argument(
        "--rules", default=None,
        help="comma-separated subset of rule names (default: all)")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit")
    parser.add_argument(
        "--format", choices=("text", "json", "sarif"), default=None,
        help="report format on stdout (default: text)")
    parser.add_argument(
        "--json", action="store_true",
        help="alias for --format json (kept for compatibility)")
    parser.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="JSON file of acknowledged finding identities to subtract")
    parser.add_argument(
        "--write-baseline", default=None, metavar="FILE",
        help="write current unallowlisted findings as a baseline and "
             "exit 0")
    parser.add_argument(
        "--changed-only", default=None, metavar="GITREF",
        help="report only findings in modules that differ from GITREF "
             "(the cheap pre-push check; stale-allowlist enforcement "
             "is skipped — staleness is a whole-package property)")
    args = parser.parse_args(argv)

    rules = all_rules()
    if args.rules:
        try:
            rules = [get_rule(n.strip()) for n in args.rules.split(",")
                     if n.strip()]
        except KeyError as e:
            parser.error(str(e))
    if args.list_rules:
        for r in rules:
            print(f"{r.name}: {r.description}")
        return 0

    if args.root is not None:
        root = Path(args.root)
        rel_base = root.parent
    else:
        root = Path(__file__).resolve().parent.parent
        rel_base = root.parent
    if not root.is_dir():
        parser.error(f"--root {root} is not a directory")

    indexes = index_package(root, rel_base)
    result = run_rules(indexes, rules)
    findings = result["findings"]
    suppressed = result["suppressed"]

    if args.changed_only:
        try:
            changed = changed_rels(rel_base, args.changed_only)
        except (OSError, RuntimeError) as e:
            parser.error(f"--changed-only: {e}")
        findings = [f for f in findings
                    if f.rel in changed and f.rule != "stale-allowlist"]

    if args.write_baseline:
        reporting.write_baseline(args.write_baseline, findings)
        print(f"wrote {len(findings)} finding identity(ies) to "
              f"{args.write_baseline}")
        return 0

    baselined_count = 0
    stale_baseline = ()
    if args.baseline:
        baseline = reporting.load_baseline(args.baseline)
        findings, baselined, stale_baseline = \
            reporting.apply_baseline(findings, baseline)
        baselined_count = len(baselined)

    fmt = args.format or ("json" if args.json else "text")
    if fmt == "json":
        print(reporting.render_json(
            findings, rules, suppressed, baselined_count,
            stale_baseline, modules=len(indexes)))
    elif fmt == "sarif":
        print(reporting.render_sarif(findings, rules))
    else:
        print(reporting.render_text(
            findings, rules, len(suppressed), baselined_count,
            stale_baseline, modules=len(indexes)))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
