"""host-sync-hazard: no stray synchronous device→host transfers.

The async emit pipeline's contract is that jit outputs leave the device
ONLY through the sanctioned drain path (``core/emit_queue.py``
``fetch_coalesced`` / ``EmitQueue.drain``) or an explicit barrier
(snapshot/restore, timer steps).  An edit that sneaks a
``np.asarray(...)`` / ``jax.device_get(...)`` onto the hot batch path
re-introduces the per-batch transfer stall the pipeline removed — and
does so silently, because results stay correct.

The rule scans the device runtime modules and reports every
materializing call whose enclosing function is not allowlisted.
Host-side ingest conversions (interning, routing, padding) also use
``np.asarray`` on genuine numpy inputs; those functions are allowlisted
explicitly (bucket justifications in ``allowlists.py``) so NEW call
sites still trip the rule.  ``tests/test_device_single_integration.py``
/ ``test_dense_integration.py`` / ``test_sharded_windows.py`` pin the
same contract dynamically with ``jax.transfer_guard('disallow')``.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..framework import Finding, Rule, register
from ..index import ModuleIndex

#: the modules owning device-resident state; everything else in the
#: package is host-side and free to use numpy
SCANNED = (
    "siddhi_tpu/core/emit_queue.py",
    "siddhi_tpu/core/device_single.py",
    "siddhi_tpu/core/dense_pattern.py",
    "siddhi_tpu/ops/device_query.py",
    "siddhi_tpu/ops/dense_nfa.py",
    "siddhi_tpu/parallel/device_shard.py",
    "siddhi_tpu/parallel/mesh.py",
    "siddhi_tpu/ops/fused_graph.py",
    "siddhi_tpu/core/fused_graph.py",
    "siddhi_tpu/ops/hotkey_scan.py",
    "siddhi_tpu/core/hotkey_router.py",
    # durability: frozen device-array references may only materialize
    # through util.faults.host_copy (the injector-aware D2H choke point)
    # on the checkpoint writer thread — never inline under the barrier
    "siddhi_tpu/durability/capture.py",
    "siddhi_tpu/durability/writer.py",
    "siddhi_tpu/durability/store.py",
    "siddhi_tpu/durability/spill.py",
    # observability: span hooks ride the ingest/step/emit hot path —
    # they may timestamp and append to the ring, never materialize a
    # device array (a tracer that fetches would reintroduce the stall
    # it exists to measure)
    "siddhi_tpu/observability/trace.py",
    "siddhi_tpu/observability/recorder.py",
    "siddhi_tpu/observability/histograms.py",
    "siddhi_tpu/observability/prometheus.py",
    # Pallas kernels: the hottest device code in the tree — a
    # materialization inside a kernel wrapper would sync every step
    "siddhi_tpu/kernels/probe.py",
    "siddhi_tpu/kernels/plane_pack.py",
    "siddhi_tpu/kernels/bank_scatter.py",
    "siddhi_tpu/kernels/scan_chain.py",
    "siddhi_tpu/kernels/dense_step.py",
    # device tables: columnar HBM storage + join probes — mutations may
    # only touch the device through staged_put and leave it through the
    # count-gated fetch_coalesced drain (demotion rebuilds included)
    "siddhi_tpu/devtable/__init__.py",
    "siddhi_tpu/devtable/storage.py",
    "siddhi_tpu/devtable/join.py",
    "siddhi_tpu/devtable/planner.py",
)

MATERIALIZERS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array",
                 "jax.device_get"}


@register
class HostSyncHazardRule(Rule):
    name = "host-sync-hazard"
    description = (
        "device→host materialization outside the sanctioned count-gated "
        "emit drain / barrier paths in the device runtime modules")

    def begin(self):
        self._seen: set = set()

    def check(self, index: ModuleIndex) -> Iterable[Finding]:
        if index.rel not in SCANNED:
            return
        self._seen.add(index.rel)
        for call in index.calls():
            name = index.dotted(call.func)
            if name in MATERIALIZERS:
                yield Finding(
                    rule=self.name,
                    rel=index.rel,
                    line=call.lineno,
                    scope=index.qualname(call),
                    message=(
                        f"synchronous {name} outside the sanctioned "
                        "async-emit drain path — route it through the "
                        "runtime's EmitQueue, or allowlist it WITH a "
                        "bucket justification"),
                )

    def finish(self) -> Iterable[Finding]:
        out: List[Finding] = []
        for rel in SCANNED:
            if rel not in self._seen:
                out.append(Finding(
                    rule=self.name, rel=rel, line=0, scope="<module>",
                    message=("scanned-module list is stale: file moved "
                             "or was not analyzed"),
                    key=f"{rel}:<missing>"))
        return out
