"""jit-purity: jitted callables stay pure.

A callable handed to ``jax.jit`` / ``shard_map`` is traced once per
shape signature and replayed as compiled XLA: any host-side effect in
its body either runs only at trace time (logging, stats counters, fault
hooks — silently NOT per batch, which is worse than failing) or
materializes a tracer and breaks/stalls compilation (``float()`` /
``int()`` / ``np.asarray`` on traced values, host clocks).  The
engine's steps therefore keep every effect — fault checks, EmitStats /
IngestStats increments, logging, wall-clock reads — on the host side of
the step boundary.

The rule finds ``jax.jit(...)`` / ``shard_map(...)`` call sites (incl.
``self.jax.jit`` receivers and ``get_shard_map()(...)``), resolves the
callable argument to a function definition, and reports banned
constructs anywhere in the resolved body:

- host clocks: ``time.time`` / ``time.monotonic`` / ``time.perf_counter``
  / ``datetime.now``
- logging / printing: any call on a ``log`` / ``logger`` / ``logging``
  receiver, bare ``print``
- fault hooks: ``.check(...)`` on a fault-injector receiver
  (``fi`` / ``faults`` / ``fault_injector`` / ``injector``)
- stats counters: writes to a ``*.stats.*`` attribute chain
- tracer materialization: ``np.asarray`` / ``np.array`` /
  ``jax.device_get``, and bare ``float()`` / ``int()`` / ``bool()`` on
  a non-literal argument

Resolution is lexical (same module, enclosing scopes outward) when the
rule runs without a ``ProjectIndex``; with one — the normal
whole-program run — the callable argument additionally resolves through
the import map (``from .steps import scan_step``), through
``self.``/``cls.`` method dispatch along the MRO, and into other
modules, and the scan follows project-resolved **helper calls**
transitively: everything the jitted callable calls is traced with it,
so a ``time.time()`` two hops away in another file is the same bug as
one written inline.  Findings on a helper are attributed to the
helper's own file and scope.  Callables/edges the project cannot
resolve statically (arbitrary object attributes, container lookups)
are skipped — conservative, never guessed.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator, List, Optional, Set, Tuple

from ..framework import Finding, Rule, register
from ..index import ModuleIndex

JIT_NAMES = {"jax.jit", "jit"}
SHARD_NAMES = {"shard_map", "jax.shard_map"}

_CLOCKS = {"time.time", "time.monotonic", "time.perf_counter",
           "time.perf_counter_ns", "datetime.now", "datetime.datetime.now"}
_LOG_RECEIVERS = {"log", "logger", "logging"}
_FAULT_RECEIVERS = {"fi", "faults", "fault_injector", "injector"}
_MATERIALIZERS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                  "jax.device_get"}
_CASTS = {"float", "int", "bool"}


def jit_call_sites(index: ModuleIndex) -> List[Tuple[ast.Call, ast.AST]]:
    """(call, callable-arg) for every jit/shard_map wrapping site."""
    out = []
    for call in index.calls():
        name = index.dotted(call.func)
        is_wrapper = name in JIT_NAMES or name in SHARD_NAMES
        if not is_wrapper and isinstance(call.func, ast.Call):
            # get_shard_map()(step, ...): the wrapper is itself a call
            inner = index.dotted(call.func.func)
            is_wrapper = inner is not None and \
                inner.split(".")[-1] == "get_shard_map"
        if is_wrapper and call.args:
            out.append((call, call.args[0]))
    return out


def resolve_callable(index: ModuleIndex, site: ast.Call,
                     arg: ast.AST) -> Optional[ast.AST]:
    """The function definition a jit argument refers to, searching the
    enclosing scopes outward; None when not statically resolvable
    within the module (the project layer picks those up)."""
    if isinstance(arg, ast.Lambda):
        return arg
    if isinstance(arg, ast.Call):
        # functools.partial(f, ...) / shard_map(f, ...): recurse on the
        # wrapped callable
        if arg.args:
            return resolve_callable(index, site, arg.args[0])
        return None
    if not isinstance(arg, ast.Name):
        return None
    scope = index.qualname(site)
    parts = scope.split(".") if scope != "<module>" else []
    while True:
        qual = ".".join(parts + [arg.id]) if parts else arg.id
        fn = index.functions.get(qual)
        if fn is not None:
            return fn
        if not parts:
            return None
        parts.pop()


def resolve_callable_project(project, index: ModuleIndex, site: ast.Call,
                             arg: ast.AST
                             ) -> Optional[Tuple[ModuleIndex, ast.AST]]:
    """Cross-module fallback when lexical resolution fails: plain names
    through the import map, ``self.``/``cls.`` methods through the MRO,
    dotted receivers into their defining module."""
    if isinstance(arg, ast.Call):
        if arg.args:
            return resolve_callable_project(project, index, site, arg.args[0])
        return None
    hit = project._resolve_value(index, site, arg)
    if hit is None:
        return None
    return (hit[0], hit[1])


def impure_constructs(index: ModuleIndex, fn: ast.AST
                      ) -> List[Tuple[int, str]]:
    """(line, description) for every banned construct in a jitted
    callable's subtree (nested local defs are traced too, so the whole
    subtree counts)."""
    hits: List[Tuple[int, str]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = index.dotted(node.func)
            if name is None:
                continue
            base = name.split(".")[0]
            leaf = name.split(".")[-1]
            if name in _CLOCKS:
                hits.append((node.lineno, f"host clock {name}()"))
            elif base in _LOG_RECEIVERS and base != leaf:
                hits.append((node.lineno, f"logging call {name}()"))
            elif name == "print":
                hits.append((node.lineno, "print()"))
            elif leaf == "check" and base in _FAULT_RECEIVERS:
                hits.append((node.lineno, f"fault hook {name}()"))
            elif name in _MATERIALIZERS:
                hits.append((node.lineno, f"tracer materialization {name}()"))
            elif name in _CASTS and node.args and \
                    not isinstance(node.args[0], ast.Constant):
                hits.append((node.lineno,
                             f"tracer materialization {name}() on a "
                             "non-literal"))
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                tname = index.dotted(t) if isinstance(t, ast.Attribute) \
                    else None
                if tname and "stats" in tname.split(".")[:-1]:
                    hits.append((t.lineno,
                                 f"stats counter write {tname}"))
    return hits


@register
class JitPurityRule(Rule):
    name = "jit-purity"
    description = (
        "host clock / logging / fault hook / stats counter / tracer "
        "materialization inside a callable passed to jax.jit or shard_map")

    #: transitive helper-following cap per jitted root
    MAX_HELPER_DEFS = 50

    def begin(self):
        # (rel, scope, line) already reported — one helper reached from
        # jit sites in several modules is one finding
        self._reported: Set[Tuple[str, str, int]] = set()

    def check(self, index: ModuleIndex) -> Iterable[Finding]:
        reported = getattr(self, "_reported", None)
        if reported is None:
            reported = self._reported = set()
        for site, arg in jit_call_sites(index):
            fn = resolve_callable(index, site, arg)
            fn_idx = index
            if fn is None and self.project is not None:
                hit = resolve_callable_project(self.project, index, site, arg)
                if hit is not None:
                    fn_idx, fn = hit
            if fn is None:
                continue
            for d_idx, d_fn in self._traced_defs(fn_idx, fn):
                d_qual = d_idx.def_qualname(d_fn)
                for line, what in impure_constructs(d_idx, d_fn):
                    key = (d_idx.rel, d_qual, line)
                    if key in reported:
                        continue  # same fn jitted/reached repeatedly
                    reported.add(key)
                    inline = d_idx is fn_idx and d_fn is fn
                    yield Finding(
                        rule=self.name,
                        rel=d_idx.rel,
                        line=line,
                        scope=d_qual,
                        message=(
                            f"{what} inside a jitted callable"
                            + ("" if inline else
                               " (helper reached from a jitted callable)")
                            + " — effects run at trace time only (or "
                            "break tracing); hoist to the host side of "
                            "the step boundary, or allowlist with a "
                            "justification"),
                    )

    def _traced_defs(self, fn_idx: ModuleIndex, fn: ast.AST
                     ) -> Iterator[Tuple[ModuleIndex, ast.AST]]:
        """The jitted callable plus — in project mode — every
        project-resolved helper its body (transitively) calls: they are
        all traced together."""
        yield (fn_idx, fn)
        if self.project is None:
            return
        visited: Set[Tuple[int, int]] = {(id(fn_idx), id(fn))}
        work: List[Tuple[ModuleIndex, ast.AST]] = [(fn_idx, fn)]
        while work and len(visited) <= self.MAX_HELPER_DEFS:
            cur_idx, cur_fn = work.pop()
            for node in ast.walk(cur_fn):
                if not isinstance(node, ast.Call):
                    continue
                hit = self.project.resolve_call(cur_idx, node)
                if hit is None:
                    continue
                t_idx, t_fn, _fq = hit
                key = (id(t_idx), id(t_fn))
                if key in visited:
                    continue
                visited.add(key)
                work.append((t_idx, t_fn))
                yield (t_idx, t_fn)
