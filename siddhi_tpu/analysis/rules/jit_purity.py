"""jit-purity: jitted callables stay pure.

A callable handed to ``jax.jit`` / ``shard_map`` is traced once per
shape signature and replayed as compiled XLA: any host-side effect in
its body either runs only at trace time (logging, stats counters, fault
hooks — silently NOT per batch, which is worse than failing) or
materializes a tracer and breaks/stalls compilation (``float()`` /
``int()`` / ``np.asarray`` on traced values, host clocks).  The
engine's steps therefore keep every effect — fault checks, EmitStats /
IngestStats increments, logging, wall-clock reads — on the host side of
the step boundary.

The rule finds ``jax.jit(...)`` / ``shard_map(...)`` call sites (incl.
``self.jax.jit`` receivers and ``get_shard_map()(...)``), resolves the
callable argument to a function definition in the same module (local
``def step(...)`` / ``lambda``), and reports banned constructs anywhere
in the resolved body:

- host clocks: ``time.time`` / ``time.monotonic`` / ``time.perf_counter``
  / ``datetime.now``
- logging / printing: any call on a ``log`` / ``logger`` / ``logging``
  receiver, bare ``print``
- fault hooks: ``.check(...)`` on a fault-injector receiver
  (``fi`` / ``faults`` / ``fault_injector`` / ``injector``)
- stats counters: writes to a ``*.stats.*`` attribute chain
- tracer materialization: ``np.asarray`` / ``np.array`` /
  ``jax.device_get``, and bare ``float()`` / ``int()`` / ``bool()`` on
  a non-literal argument

Callables the rule cannot resolve statically (attributes, imports from
other modules) are skipped — the differential suites cover those paths
dynamically.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

from ..framework import Finding, Rule, register
from ..index import ModuleIndex

JIT_NAMES = {"jax.jit", "jit"}
SHARD_NAMES = {"shard_map", "jax.shard_map"}

_CLOCKS = {"time.time", "time.monotonic", "time.perf_counter",
           "time.perf_counter_ns", "datetime.now", "datetime.datetime.now"}
_LOG_RECEIVERS = {"log", "logger", "logging"}
_FAULT_RECEIVERS = {"fi", "faults", "fault_injector", "injector"}
_MATERIALIZERS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                  "jax.device_get"}
_CASTS = {"float", "int", "bool"}


def jit_call_sites(index: ModuleIndex) -> List[Tuple[ast.Call, ast.AST]]:
    """(call, callable-arg) for every jit/shard_map wrapping site."""
    out = []
    for call in index.calls():
        name = index.dotted(call.func)
        is_wrapper = name in JIT_NAMES or name in SHARD_NAMES
        if not is_wrapper and isinstance(call.func, ast.Call):
            # get_shard_map()(step, ...): the wrapper is itself a call
            inner = index.dotted(call.func.func)
            is_wrapper = inner is not None and \
                inner.split(".")[-1] == "get_shard_map"
        if is_wrapper and call.args:
            out.append((call, call.args[0]))
    return out


def resolve_callable(index: ModuleIndex, site: ast.Call,
                     arg: ast.AST) -> Optional[ast.AST]:
    """The function definition a jit argument refers to, searching the
    enclosing scopes outward; None when not statically resolvable."""
    if isinstance(arg, ast.Lambda):
        return arg
    if isinstance(arg, ast.Call):
        # functools.partial(f, ...) / shard_map(f, ...): recurse on the
        # wrapped callable
        if arg.args:
            return resolve_callable(index, site, arg.args[0])
        return None
    if not isinstance(arg, ast.Name):
        return None
    scope = index.qualname(site)
    parts = scope.split(".") if scope != "<module>" else []
    while True:
        qual = ".".join(parts + [arg.id]) if parts else arg.id
        fn = index.functions.get(qual)
        if fn is not None:
            return fn
        if not parts:
            return None
        parts.pop()


def impure_constructs(index: ModuleIndex, fn: ast.AST
                      ) -> List[Tuple[int, str]]:
    """(line, description) for every banned construct in a jitted
    callable's subtree (nested local defs are traced too, so the whole
    subtree counts)."""
    hits: List[Tuple[int, str]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = index.dotted(node.func)
            if name is None:
                continue
            base = name.split(".")[0]
            leaf = name.split(".")[-1]
            if name in _CLOCKS:
                hits.append((node.lineno, f"host clock {name}()"))
            elif base in _LOG_RECEIVERS and base != leaf:
                hits.append((node.lineno, f"logging call {name}()"))
            elif name == "print":
                hits.append((node.lineno, "print()"))
            elif leaf == "check" and base in _FAULT_RECEIVERS:
                hits.append((node.lineno, f"fault hook {name}()"))
            elif name in _MATERIALIZERS:
                hits.append((node.lineno, f"tracer materialization {name}()"))
            elif name in _CASTS and node.args and \
                    not isinstance(node.args[0], ast.Constant):
                hits.append((node.lineno,
                             f"tracer materialization {name}() on a "
                             "non-literal"))
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                tname = index.dotted(t) if isinstance(t, ast.Attribute) \
                    else None
                if tname and "stats" in tname.split(".")[:-1]:
                    hits.append((t.lineno,
                                 f"stats counter write {tname}"))
    return hits


@register
class JitPurityRule(Rule):
    name = "jit-purity"
    description = (
        "host clock / logging / fault hook / stats counter / tracer "
        "materialization inside a callable passed to jax.jit or shard_map")

    def check(self, index: ModuleIndex) -> Iterable[Finding]:
        reported: Set[Tuple[str, int]] = set()
        for site, arg in jit_call_sites(index):
            fn = resolve_callable(index, site, arg)
            if fn is None:
                continue
            fn_qual = index.def_qualname(fn)
            for line, what in impure_constructs(index, fn):
                if (fn_qual, line) in reported:
                    continue  # same fn jitted at several sites
                reported.add((fn_qual, line))
                yield Finding(
                    rule=self.name,
                    rel=index.rel,
                    line=line,
                    scope=fn_qual,
                    message=(
                        f"{what} inside a jitted callable — effects run "
                        "at trace time only (or break tracing); hoist "
                        "to the host side of the step boundary, or "
                        "allowlist with a justification"),
                )
