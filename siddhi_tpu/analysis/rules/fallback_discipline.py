"""fallback-discipline: graceful fallbacks are logged AND counted.

The planner's contract since the sharded/fused/kernel work: when an
eligibility gate rejects a query — ``SiddhiAppCreationError`` raised by
a probe, the dense/device/multiplex path declined — the engine falls
back silently in terms of RESULTS but never in terms of OBSERVABILITY.
Every such gate must reach, on the fallback path, both

1. a ``log.warning`` (or ``error``/``exception``/``critical``) — the
   user asked for an accelerated path and is not getting it, which must
   be visible in the log; ``log.info`` does not satisfy the contract —
   fallbacks are warnings by definition; and
2. a counted stats write — a ``record_*_fallback(...)`` call on the
   ``StatisticsManager`` (which maintains the ``*Fallbacks`` /
   ``*FallbackReason`` feed keys served over REST), or a direct
   ``*fallback*`` counter write.

The rule anchors on ``except SiddhiAppCreationError`` handlers (the
engine's single fallback currency) and checks both obligations over
the calls **reachable** from the handler through the project call
graph — the planner's habit of delegating to ``self._fallback(...)``
or a module helper two files away is followed, not guessed at.
Handlers that re-raise are exempt: propagating the error is the
other legitimate response to a failed gate.

Without a ``ProjectIndex`` only the handler's lexical body is
searched (fixture mode).  Handlers that delegate through edges the
call graph cannot resolve (callbacks passed as parameters) belong in
the allowlist with a justification naming where the logging/counting
actually happens.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Set, Tuple

from ..framework import Finding, Rule, register
from ..index import ModuleIndex

_EXC_NAME = "SiddhiAppCreationError"
_LOG_METHODS = {"warning", "error", "exception", "critical"}
_COUNTER_RE = re.compile(r"^record_\w*fallback\w*$")
_FALLBACK_SEG_RE = re.compile(r"fallback", re.IGNORECASE)


def _handler_catches(handler: ast.ExceptHandler) -> bool:
    """Does the handler type mention SiddhiAppCreationError?"""
    t = handler.type
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for node in types:
        if isinstance(node, ast.Name) and node.id == _EXC_NAME:
            return True
        if isinstance(node, ast.Attribute) and node.attr == _EXC_NAME:
            return True
    return False


def _reraises(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise) for n in ast.walk(handler))


def _is_log_call(call: ast.Call) -> bool:
    """`.warning()/.error()/...` on any receiver — including the
    chained ``logging.getLogger(...).warning(...)`` form."""
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr in _LOG_METHODS)


def _is_counter_call(call: ast.Call) -> bool:
    func = call.func
    leaf = func.attr if isinstance(func, ast.Attribute) else (
        func.id if isinstance(func, ast.Name) else None)
    return leaf is not None and _COUNTER_RE.match(leaf) is not None


def _counter_writes(index: ModuleIndex, node: ast.AST) -> bool:
    """Direct ``*.somethingFallback* = / += `` counter writes."""
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Assign, ast.AugAssign)):
            targets = (sub.targets if isinstance(sub, ast.Assign)
                       else [sub.target])
            for t in targets:
                name = index.dotted(t) if isinstance(
                    t, (ast.Attribute, ast.Name)) else None
                if name and any(_FALLBACK_SEG_RE.search(seg)
                                for seg in name.split(".")):
                    return True
    return False


@register
class FallbackDisciplineRule(Rule):
    name = "fallback-discipline"
    description = (
        "except SiddhiAppCreationError fallback gate that does not reach "
        "both a log.warning and a counted record_*_fallback stats write")

    def check(self, index: ModuleIndex) -> Iterable[Finding]:
        seen: Set[Tuple[str, str]] = set()
        for node in ast.walk(index.tree):
            if not isinstance(node, ast.ExceptHandler) or \
                    not _handler_catches(node):
                continue
            if _reraises(node):
                continue  # propagating the gate failure is fine
            logged, counted = self._obligations(index, node)
            if logged and counted:
                continue
            missing = []
            if not logged:
                missing.append("no log.warning")
            if not counted:
                missing.append("no record_*_fallback stats write")
            scope = index.qualname(node)
            key = (scope, ", ".join(missing))
            if key in seen:
                continue
            seen.add(key)
            yield Finding(
                rule=self.name,
                rel=index.rel,
                line=node.lineno,
                scope=scope,
                message=(
                    f"fallback gate ({', '.join(missing)} reachable from "
                    "the handler) — a declined accelerated path must be "
                    "both visible in the log and counted on the "
                    "statistics feed, or allowlisted with a "
                    "justification naming where that happens"),
            )

    def _obligations(self, index: ModuleIndex,
                     handler: ast.ExceptHandler) -> Tuple[bool, bool]:
        logged = counted = False
        if self.project is not None:
            calls = self.project.iter_calls_reachable(index, [handler])
        else:
            calls = ((index, c) for c in ast.walk(handler)
                     if isinstance(c, ast.Call))
        for c_idx, call in calls:
            if _is_log_call(call):
                logged = True
            if _is_counter_call(call):
                counted = True
            if logged and counted:
                return True, True
        if not counted:
            counted = _counter_writes(index, handler)
        return logged, counted
