"""ingest-put-bypass: every ingest-path H2D transfer goes through staging.

The ingest pipeline's contract is that host→device puts of BATCH data
happen ONLY through ``core/ingest_stage.py`` ``staged_put`` — the one
wrapper that arms the ``ingest.put`` fault-injection site (bounded
retry-with-backoff, crash-journal semantics) and counts
``IngestStats.device_puts``.  A direct ``jax.device_put`` on a batch
path silently bypasses both the fault harness and the staging counters:
chaos runs stop covering that transfer and the overlap evidence
under-reports.

The rule scans the whole package and reports every ``*.device_put(...)``
call — regardless of the receiver chain (``jax.device_put``,
``self.jax.device_put``, ...) — whose enclosing function is not
allowlisted (buckets: staging / mesh / state, see ``allowlists.py``).
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..framework import Finding, Rule, register
from ..index import ModuleIndex


@register
class IngestPutBypassRule(Rule):
    name = "ingest-put-bypass"
    description = (
        "direct device_put outside the sanctioned staging/mesh/state "
        "sites — batch ingest must go through core/ingest_stage.staged_put")

    def check(self, index: ModuleIndex) -> Iterable[Finding]:
        for call in index.calls():
            f = call.func
            if isinstance(f, ast.Attribute) and f.attr == "device_put":
                yield Finding(
                    rule=self.name,
                    rel=index.rel,
                    line=call.lineno,
                    scope=index.qualname(call),
                    message=(
                        "direct device_put — route batch ingest through "
                        "core/ingest_stage.staged_put (fault site + "
                        "counters), or allowlist it WITH a bucket "
                        "justification"),
                )
