"""lockset-race: flow-sensitive cross-thread write check (Eraser).

The lexical ``lock-discipline`` rule asks "is the write *inside* a
``with <lock>:`` block?" — a question with two blind spots this rule
closes:

1. **released-then-write**: an explicit ``lock.release()`` (or a
   ``with``-exit) before the write leaves the statement lexically
   inside the block but dynamically unprotected;
2. **disjoint locks**: the thread side holding ``self._a`` and the main
   path holding ``self._b`` are both "locked", yet nothing orders the
   two writes.

Per class (MRO-merged, as PR 12's whole-program pass resolves it):

1. **thread entries** come from ``threading.Thread(target=...)`` /
   ``Timer(..., fn)`` calls anywhere in the class's methods;
2. the thread side is closed over ``self.m()`` calls, each callee's
   **entry lockset seeded with the lockset held at the call site**
   (intersected over sites, so a helper called both locked and
   unlocked starts empty) — strictly more precise than the lexical
   rule's "a locked call does not extend the closure";
3. every reachable direct ``self.<attr>`` write on either side is
   recorded with the must-hold lockset ``dataflow.solve`` computed for
   its statement (main-path privates inherit the project-wide
   interprocedural seeds, so a helper only ever called under
   ``process_lock`` is not misread as unlocked);
4. an attribute written on both sides whose locksets have an **empty
   intersection** is a finding — same ``Class.attr`` key the lexical
   rule used, so existing allowlist entries stay valid and only
   shrink.

Constructors (``__init__``/``__new__``/transport ``init``/``_init*``)
are excluded: construction happens-before thread start.  Functions
whose fixpoint did not converge (none today — the CFG corpus sweep
pins this) are skipped rather than guessed at.

The rule is whole-program by construction (thread roots and MRO
merging need the ``ProjectIndex``); in fixture mode build a project
over the fixture files, as ``tests/test_analysis_flow.py`` does.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterable, List, Tuple

from ..dataflow import TOP
from ..framework import Finding, Rule, register
from ..index import ModuleIndex
from ..locksets import (
    Token,
    get_model,
    shallow_calls,
    stmt_writes,
    thread_target_of,
)

#: an access record: (scope qualname, line, lockset, rel)
_Access = Tuple[str, int, FrozenSet[Token], str]

_CTOR_NAMES = ("__init__", "__new__", "init")


def _is_ctor(name: str) -> bool:
    return name in _CTOR_NAMES or name.startswith("_init")


@register
class LocksetRaceRule(Rule):
    name = "lockset-race"
    description = (
        "attribute written from both a thread entry and the main path "
        "with an empty must-hold lockset intersection (flow-sensitive)")

    def begin(self):
        # (attr, site identity) -> [(class fq, Finding)], for base-most
        # dedup of conflicts inherited through several subclasses
        self._candidates: Dict[Tuple[str, frozenset],
                               List[Tuple[str, Finding]]] = {}

    def check(self, index: ModuleIndex) -> Iterable[Finding]:
        return ()  # whole-program only: everything happens in finish()

    def finish(self) -> Iterable[Finding]:
        if self.project is None:
            return ()
        model = get_model(self.project)
        for fq_class in sorted(self.project.classes):
            idx, cls = self.project.classes[fq_class]
            self._check_class(model, fq_class, idx, cls)
        findings = list(self._dedup_candidates())
        # lexical lock-discipline consults this to stand down on
        # conflicts the flow-sensitive pass already covers (raw keys,
        # pre-allowlist: a suppressed lockset finding still wins)
        self.project._lockset_keys = {f.key for f in findings}
        return findings

    # -- per-class analysis --------------------------------------------------

    def _thread_roots(self, model, fq_class: str,
                      methods) -> List[Tuple[str, ModuleIndex, ast.AST]]:
        roots = []
        for _mname, (m_idx, m, _owner) in methods.items():
            for node in ast.walk(m):
                if not isinstance(node, ast.Call):
                    continue
                tgt = thread_target_of(node, m_idx)
                if tgt is None:
                    continue
                kind, tname = tgt
                if kind == "method" and tname in methods:
                    t_idx, t_fn, _ = methods[tname]
                    roots.append((tname, t_idx, t_fn))
                elif kind == "local":
                    scope = m_idx.qualname(node)
                    fn = m_idx.functions.get(f"{scope}.{tname}")
                    if fn is not None:
                        roots.append((tname, m_idx, fn))
        return roots

    def _closure_with_seeds(self, model, methods, roots):
        """label -> (index, fn, entry seed): thread-side functions with
        call-site-seeded entry locksets.  Seeds only shrink (intersected
        across call sites), so the worklist terminates."""
        fns: Dict[str, Tuple[ModuleIndex, ast.AST]] = {}
        seeds: Dict[str, FrozenSet[Token]] = {}
        work = [(label, idx, fn, frozenset())
                for label, idx, fn in roots]
        while work:
            label, idx, fn, seed = work.pop()
            if label in fns:
                old = seeds[label]
                seed = old & seed
                if seed == old:
                    continue
            fns[label] = (idx, fn)
            seeds[label] = seed
            ff = model.facts(idx, fn, seed)
            if not ff.result.converged:
                continue
            for stmt, fact in ff.statements():
                if fact is TOP:
                    continue
                for call in shallow_calls(stmt):
                    if not (isinstance(call.func, ast.Attribute) and
                            isinstance(call.func.value, ast.Name) and
                            call.func.value.id in ("self", "cls")):
                        continue
                    callee = call.func.attr
                    if callee in methods:
                        c_idx, c_fn, _ = methods[callee]
                        work.append((callee, c_idx, c_fn,
                                     frozenset(fact)))
        return {label: (idx, fn, seeds[label])
                for label, (idx, fn) in fns.items()}

    def _check_class(self, model, fq_class: str, cls_index: ModuleIndex,
                     cls: ast.ClassDef):
        methods = self.project.class_methods(fq_class)
        roots = self._thread_roots(model, fq_class, methods)
        if not roots:
            return
        thread_fns = self._closure_with_seeds(model, methods, roots)
        thread_writes = self._collect_writes(model, thread_fns)
        main_fns: Dict[str, Tuple[ModuleIndex, ast.AST, FrozenSet[Token]]]
        main_fns = {}
        for mname, (m_idx, m, _owner) in methods.items():
            if mname in thread_fns or _is_ctor(mname):
                continue
            main_fns[mname] = (m_idx, m, model.seed_of(m))
        main_writes = self._collect_writes(model, main_fns)
        cls_qual = cls_index.def_qualname(cls)
        for attr in sorted(set(thread_writes) & set(main_writes)):
            sites = thread_writes[attr] + main_writes[attr]
            common = frozenset.intersection(*[s[2] for s in sites])
            if common:
                continue
            unlocked = [(q, ln, rel) for q, ln, ls, rel in sites
                        if not ls]
            witness = unlocked or [(q, ln, rel)
                                   for q, ln, _ls, rel in sites[:2]]
            where = ", ".join(f"{q}:{ln}" for q, ln, _rel in witness)
            finding = Finding(
                rule=self.name,
                rel=cls_index.rel,
                line=witness[0][1],
                scope=f"{cls_qual}.{attr}",
                message=(
                    f"'{attr}' is written from both a thread entry "
                    f"({', '.join(sorted(thread_fns))}) and the main "
                    f"path with no common lock held across the writes "
                    f"(empty lockset intersection; e.g. {where}) — hold "
                    "one lock over every write, or allowlist with a "
                    "justification"),
            )
            ident = frozenset((rel, ln) for _q, ln, _ls, rel in sites)
            self._candidates.setdefault(
                (attr, ident), []).append((fq_class, finding))

    def _collect_writes(self, model, fns) -> Dict[str, List[_Access]]:
        """attr -> access records with locksets, across ``fns``
        (label -> (index, fn, entry seed))."""
        out: Dict[str, List[_Access]] = {}
        for _label, (idx, fn, seed) in fns.items():
            ff = model.facts(idx, fn, seed)
            if not ff.result.converged:
                continue
            for stmt, fact in ff.statements():
                if fact is TOP:
                    continue
                for attr, line in stmt_writes(stmt):
                    out.setdefault(attr, []).append(
                        (ff.qual, line, frozenset(fact), idx.rel))
        return out

    def _dedup_candidates(self) -> Iterable[Finding]:
        """One finding per (attr, site set): mixin state seen through
        several subclasses reports once, on the base-most class."""
        out: List[Finding] = []
        for (_attr, _ident), group in sorted(
                self._candidates.items(),
                key=lambda kv: (kv[1][0][1].rel, kv[1][0][1].scope)):
            if len(group) == 1:
                out.append(group[0][1])
                continue
            base = None
            for fq, finding in group:
                if all(fq in self.project.mro(other)
                       for other, _f in group):
                    base = finding
                    break
            if base is None:
                base = sorted(group,
                              key=lambda g: (g[1].rel, g[1].scope))[0][1]
            out.append(base)
        return out
