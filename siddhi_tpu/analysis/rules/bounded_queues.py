"""bounded-queue-discipline: hot-path buffers carry an explicit bound.

An unbounded queue between a producer and a slower consumer is the
canonical overload failure: memory grows until the process dies,
usually long after the real problem started.  The engine's ingest and
transport layers (``core/``, ``transport/``, ``robustness/``) are
exactly where load arrives faster than it drains — every buffer there
must state its bound at the construction site:

- ``collections.deque(...)`` needs a ``maxlen=`` keyword (or the
  second positional argument) that is not the literal ``None``;
- ``queue.Queue`` / ``LifoQueue`` / ``PriorityQueue`` need a
  ``maxsize=`` keyword (or the first positional argument) that is not
  the literal ``0`` or ``None`` — 0 is the stdlib's spelling of
  "infinite";
- ``queue.SimpleQueue`` is unbounded by construction and is always a
  finding.

A bound passed as a variable or expression is accepted: the rule
enforces that a bound was CHOSEN, not what its value is.  Buffers that
are genuinely unbounded by design belong in the allowlist with a
justification (analysis/allowlists.py).
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..framework import Finding, Rule, register
from ..index import ModuleIndex

_SCOPES = ("siddhi_tpu/core/", "siddhi_tpu/transport/",
           "siddhi_tpu/robustness/")

#: ctor dotted name -> (bound kwarg, positional index of the bound)
_BOUNDED_CTORS = {
    "deque": ("maxlen", 1),
    "collections.deque": ("maxlen", 1),
    "queue.Queue": ("maxsize", 0),
    "Queue": ("maxsize", 0),
    "queue.LifoQueue": ("maxsize", 0),
    "LifoQueue": ("maxsize", 0),
    "queue.PriorityQueue": ("maxsize", 0),
    "PriorityQueue": ("maxsize", 0),
}

_ALWAYS_UNBOUNDED = {"queue.SimpleQueue", "SimpleQueue"}


def _is_unbounded_literal(node: ast.AST) -> bool:
    """The stdlib's 'no limit' spellings: ``None`` (deque) / ``0``
    (queue.Queue family)."""
    return isinstance(node, ast.Constant) and (
        node.value is None or node.value == 0)


@register
class BoundedQueueRule(Rule):
    name = "bounded-queue-discipline"
    description = (
        "deque/Queue in core/, transport/ or robustness/ without an "
        "explicit bound (maxlen=/maxsize=)")

    def check(self, index: ModuleIndex) -> Iterable[Finding]:
        if not index.rel.startswith(_SCOPES):
            return
        for site in index.calls():
            name = index.dotted(site.func)
            if name in _ALWAYS_UNBOUNDED:
                yield self._finding(
                    index, site,
                    f"{name}() is unbounded by construction — use "
                    "queue.Queue(maxsize=N), or allowlist with a "
                    "justification")
                continue
            spec = _BOUNDED_CTORS.get(name)
            if spec is None:
                continue
            kwarg, pos = spec
            bound = None
            for kw in site.keywords:
                if kw.arg == kwarg:
                    bound = kw.value
                    break
            if bound is None and len(site.args) > pos:
                bound = site.args[pos]
            if bound is not None and not _is_unbounded_literal(bound):
                continue
            yield self._finding(
                index, site,
                f"{name}() without an explicit bound — pass "
                f"{kwarg}=N at the construction site (ingest/transport "
                "buffers must not grow without limit under overload), "
                "or allowlist with a justification")

    def _finding(self, index: ModuleIndex, site: ast.Call,
                 message: str) -> Finding:
        return Finding(
            rule=self.name,
            rel=index.rel,
            line=site.lineno,
            scope=index.qualname(site),
            message=message,
        )
