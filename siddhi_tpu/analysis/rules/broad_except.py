"""broad-except-swallow: no fault may vanish without a log line or counter.

The fault-injection work replaced every silent ``except Exception:
pass`` swallow on the processing path with handlers that log, count, or
route through the @OnError machinery.  This rule scans
``siddhi_tpu/core/`` and ``siddhi_tpu/transport/`` (the layers events
and faults actually traverse) and reports a handler catching
``Exception`` (or a bare ``except:``) whose body is only ``pass``/a
constant — the signature of a fault disappearing without trace.

Narrow handlers (``except queue.Empty: pass``) are fine: swallowing a
SPECIFIC expected condition is control flow, not fault masking.  A
genuinely sanctioned broad swallow goes in the allowlist with a
justification — the rule keeps the decision visible in review.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..framework import Finding, Rule, register
from ..index import ModuleIndex

SCANNED_DIRS = ("siddhi_tpu/core/", "siddhi_tpu/transport/",
                "siddhi_tpu/durability/", "siddhi_tpu/observability/",
                "siddhi_tpu/kernels/", "siddhi_tpu/devtable/")

BROAD = {"Exception", "BaseException"}


def is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare `except:`
        return True
    if isinstance(t, ast.Name):
        return t.id in BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in BROAD for e in t.elts)
    return False


def is_silent(handler: ast.ExceptHandler) -> bool:
    return all(
        isinstance(s, ast.Pass)
        or (isinstance(s, ast.Expr) and isinstance(s.value, ast.Constant))
        for s in handler.body)


@register
class BroadExceptSwallowRule(Rule):
    name = "broad-except-swallow"
    description = (
        "silent `except Exception: pass` on the processing path — faults "
        "must leave a log line, a counter, or an @OnError route")

    def check(self, index: ModuleIndex) -> Iterable[Finding]:
        if not index.rel.startswith(SCANNED_DIRS):
            return
        for node in ast.walk(index.tree):
            if isinstance(node, ast.ExceptHandler) and is_broad(node) \
                    and is_silent(node):
                yield Finding(
                    rule=self.name,
                    rel=index.rel,
                    line=node.lineno,
                    scope=index.qualname(node),
                    message=(
                        "silent broad except — faults must leave a log "
                        "line, a counter, or an @OnError route (or be "
                        "allowlisted with a justification)"),
                )
