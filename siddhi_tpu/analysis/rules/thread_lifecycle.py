"""thread-lifecycle: every Thread/Timer is daemon or joined.

A non-daemon thread that nobody joins keeps the interpreter alive after
``shutdown()`` — the engine appears to exit and hangs in atexit; a
joined-nowhere ``threading.Timer`` re-arms forever.  The engine's
convention is explicit on every spawn site: either the thread is marked
``daemon=True`` (the junction worker, the scheduler, the stats
reporter, the persist daemon, the reconnect Timer chain), or the owner
class joins/cancels it on a shutdown path (the checkpoint writer's
``stop()``).

Per ``threading.Thread(...)`` / ``threading.Timer(...)`` construction
site the rule accepts any of:

- ``daemon=True`` passed to the constructor;
- ``<obj>.daemon = True`` / ``<obj>.setDaemon(True)`` on the
  constructed object (local name or ``self.<attr>``) in the same
  function;
- the object is stored into ``self.<attr>`` and SOME method of the
  owner class calls ``self.<attr>.join()`` or ``self.<attr>.cancel()``
  — the shutdown path.  With a ``ProjectIndex`` the method search runs
  over the MRO-merged method table, so a mixin's Timer joined by the
  subclass's ``shutdown()`` (or vice versa) resolves; without one, only
  the lexical class body is searched;
- a purely local object that is ``join()``ed / ``cancel()``ed in the
  same function (scoped worker pools).

Anything else is a finding on the construction site.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional, Tuple

from ..framework import Finding, Rule, register
from ..index import ModuleIndex

_CTORS = {"threading.Thread", "Thread", "threading.Timer", "Timer"}
_RELEASERS = {"join", "cancel"}


def _is_true(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and node.value is True


def _binding_of(index: ModuleIndex, site: ast.Call
                ) -> Tuple[Optional[str], Optional[str]]:
    """(local name, self attr) the constructed object is bound to —
    either may be None."""
    local = attr = None
    for anc in index.ancestors(site):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
        if isinstance(anc, ast.Assign):
            for t in anc.targets:
                if isinstance(t, ast.Name):
                    local = t.id
                elif isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in ("self", "cls"):
                    attr = t.attr
            break
    return local, attr


def _released_in(index: ModuleIndex, fn: ast.AST, receiver: str) -> bool:
    """``<receiver>.join()`` / ``.cancel()`` / ``.daemon = True`` /
    ``.setDaemon(True)`` anywhere in ``fn`` — receiver is a dotted
    string like ``t`` or ``self._timer`` (self elided by dotted())."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute):
            recv = index.dotted(node.func.value)
            if recv != receiver:
                continue
            if node.func.attr in _RELEASERS:
                return True
            if node.func.attr == "setDaemon" and node.args and \
                    _is_true(node.args[0]):
                return True
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and t.attr == "daemon" \
                        and index.dotted(t.value) == receiver and \
                        _is_true(node.value):
                    return True
    return False


@register
class ThreadLifecycleRule(Rule):
    name = "thread-lifecycle"
    description = (
        "Thread/Timer that is neither daemon nor joined/cancelled on a "
        "shutdown path of its owner class")

    def check(self, index: ModuleIndex) -> Iterable[Finding]:
        for site in index.calls():
            if index.dotted(site.func) not in _CTORS:
                continue
            if any(_is_true(kw.value) for kw in site.keywords
                   if kw.arg == "daemon"):
                continue
            fn = index.enclosing(site, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))
            local, attr = _binding_of(index, site)
            handled = False
            if fn is not None and local is not None and \
                    _released_in(index, fn, local):
                handled = True
            if not handled and attr is not None:
                # dotted() elides the leading self, so the receiver of
                # a `self.<attr>.join()` is just `<attr>`
                handled = self._owner_releases(index, site, attr)
            if handled:
                continue
            yield Finding(
                rule=self.name,
                rel=index.rel,
                line=site.lineno,
                scope=index.qualname(site),
                message=(
                    "Thread/Timer is neither daemon=True nor "
                    "joined/cancelled on a shutdown path of its owner "
                    "class — it outlives shutdown(); mark it daemon or "
                    "join/cancel it, or allowlist with a justification"),
            )

    def _owner_releases(self, index: ModuleIndex, site: ast.Call,
                        attr: str) -> bool:
        """Some method of the owner class releases ``self.<attr>`` —
        MRO-merged in project mode, lexical class body otherwise."""
        cls = index.enclosing(site, (ast.ClassDef,))
        if cls is None:
            return False
        if self.project is not None:
            fq = f"{self.project.module_of(index)}.{index.def_qualname(cls)}"
            methods = [(m_idx, m) for m_idx, m, _owner
                       in self.project.class_methods(fq).values()]
            # a subclass may own the shutdown path for a base's thread
            for other_fq, (o_idx, _o_cls) in self.project.classes.items():
                if other_fq != fq and fq in self.project.mro(other_fq):
                    methods.extend(
                        (m_idx, m) for m_idx, m, _owner
                        in self.project.class_methods(other_fq).values())
        else:
            methods = [(index, n) for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
        return any(_released_in(m_idx, m, attr) for m_idx, m in methods)
