"""lock-order-deadlock: cycles in the global lock-acquisition graph.

Two threads that take the same pair of locks in opposite orders
deadlock the moment their windows overlap — the bug class no amount of
per-lock discipline catches, because every individual critical section
looks correct.  The rule builds one **acquisition-order graph** for the
whole package:

- every lock acquisition (``with``-enter or explicit ``.acquire()``)
  in every function contributes edges ``held -> acquired`` for each
  lock in the must-hold lockset ``dataflow.solve`` computed at that
  statement (interprocedural entry seeds included, so a private helper
  that acquires ``B`` and is only called under ``A`` contributes
  ``A -> B`` even though the two acquisitions sit in different
  functions);
- nodes are project-global lock identities: instance locks qualify by
  their MRO-resolved **defining class** (``ConnectRetryMixin._retry_lock``
  is one node however many sink/source subclasses inherit it), chain
  locks by their normalized last-two-component path
  (``app_context.process_lock``);
- every elementary cycle is a finding, reported once (canonical
  rotation) with one witness per edge — function, file and line of the
  inner acquisition;
- re-acquiring a lock already in the lockset is a self-cycle finding
  when the constructor registry proves the lock non-reentrant
  (``threading.Lock``/``Condition(Lock())``); RLocks and
  unknown-constructor chains are skipped.

Finding keys name only the cycle's lock identities, not lines, so
allowlist entries survive refactors.  The rule is whole-program only
(token identity needs the MRO and the project-wide seeds).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from ..framework import Finding, Rule, register
from ..index import ModuleIndex
from ..locksets import get_model

#: witness for an edge a -> b: (rel, function fq, line of acquiring b)
_Witness = Tuple[str, str, int]

_MAX_CYCLE_LEN = 6
_MAX_CYCLES = 50


@register
class LockOrderRule(Rule):
    name = "lock-order-deadlock"
    description = (
        "cycle in the global lock-acquisition-order graph (AB/BA "
        "deadlock), or re-acquisition of a held non-reentrant lock")

    def check(self, index: ModuleIndex) -> Iterable[Finding]:
        return ()  # whole-program only

    def finish(self) -> Iterable[Finding]:
        if self.project is None:
            return ()
        model = get_model(self.project)
        edges: Dict[Tuple[str, str], _Witness] = {}
        self_cycles: List[Tuple[str, _Witness]] = []
        for fq in sorted(self.project.functions):
            idx, fn = self.project.functions[fq]
            ctx_class = self.project.enclosing_class_fq(idx, fn)
            ff = model.facts(idx, fn, model.seed_of(fn))
            if not ff.result.converged:
                continue
            for tok, held, line in ff.acquisitions():
                t_q = model.qualify(tok, ctx_class)
                for h in held:
                    h_q = model.qualify(h, ctx_class)
                    wit = (idx.rel, fq, line)
                    if h_q == t_q:
                        if model.reentrant(tok, ctx_class) is False:
                            self_cycles.append((t_q, wit))
                        continue
                    edges.setdefault((h_q, t_q), wit)
        findings = []
        seen = set()
        for name, (rel, fq, line) in self_cycles:
            if name in seen:
                continue
            seen.add(name)
            findings.append(Finding(
                rule=self.name,
                rel=rel,
                line=line,
                scope=f"self-cycle:{name}",
                message=(
                    f"non-reentrant lock '{name}' is re-acquired while "
                    f"already held (at {fq}:{line}) — guaranteed "
                    "self-deadlock; make it an RLock or drop the nested "
                    "acquisition"),
            ))
        for cycle in self._cycles(edges):
            path = " -> ".join(cycle + (cycle[0],))
            witnesses = []
            for i, a in enumerate(cycle):
                b = cycle[(i + 1) % len(cycle)]
                rel, fq, line = edges[(a, b)]
                witnesses.append(f"{a}->{b} at {fq} ({rel}:{line})")
            rel0, _fq0, line0 = edges[(cycle[0], cycle[1])]
            findings.append(Finding(
                rule=self.name,
                rel=rel0,
                line=line0,
                scope=f"cycle:{path}",
                message=(
                    f"lock-acquisition-order cycle {path}: "
                    + "; ".join(witnesses)
                    + " — pick one global order for these locks"),
            ))
        return findings

    def _cycles(self, edges: Dict[Tuple[str, str], _Witness]
                ) -> List[Tuple[str, ...]]:
        """Elementary cycles, canonically rotated to start at their
        smallest node, each reported once.  DFS from each start node
        visiting only nodes >= start (the classic enumeration trick:
        every elementary cycle is found exactly once, from its minimal
        node), bounded in length and count."""
        adj: Dict[str, List[str]] = {}
        for a, b in edges:
            adj.setdefault(a, []).append(b)
        for outs in adj.values():
            outs.sort()
        out: List[Tuple[str, ...]] = []
        for start in sorted(adj):
            stack = [(start, (start,))]
            while stack and len(out) < _MAX_CYCLES:
                node, path = stack.pop()
                for nxt in adj.get(node, ()):
                    if nxt == start:
                        out.append(path)
                    elif nxt > start and nxt not in path and \
                            len(path) < _MAX_CYCLE_LEN:
                        stack.append((nxt, path + (nxt,)))
        return out
