"""Built-in rules — importing this package registers them all.

Catalog (one module per rule):

- ``host_sync``       — ``host-sync-hazard``: D2H materialization only
  through the count-gated emit drain (ex ``tests/test_emit_guard.py``)
- ``ingest_put``      — ``ingest-put-bypass``: H2D puts only through
  ``staged_put`` (ex ``tests/test_ingest_guard.py``)
- ``broad_except``    — ``broad-except-swallow``: no fault vanishes
  without a log/counter (ex ``tests/test_except_guard.py``)
- ``lock_discipline`` — ``lock-discipline``: attributes shared between a
  thread-entry function and the main batch path stay under the lock
  (MRO-aware: mixin threads and inherited methods resolve)
- ``jit_purity``      — ``jit-purity``: no host clock / logging / fault
  hooks / tracer materialization inside jitted callables, helpers in
  other modules included
- ``retrace``         — ``retrace-hazard``: no un-memoized
  ``jax.jit``/``shard_map`` on per-batch functions, including builders
  called across modules
- ``fallback_discipline`` — ``fallback-discipline``: every
  ``except SiddhiAppCreationError`` fallback gate reaches both a
  ``log.warning`` and a counted ``record_*_fallback`` stats write
- ``thread_lifecycle`` — ``thread-lifecycle``: every Thread/Timer is
  daemon or joined/cancelled on an owner-class shutdown path
- ``bounded_queues`` — ``bounded-queue-discipline``: every deque/Queue
  in ``core/``, ``transport/`` and ``robustness/`` carries an explicit
  bound (``maxlen=``/``maxsize=``) or an allowlist justification
- ``lockset_race`` — ``lockset-race``: flow-sensitive Eraser-style
  cross-thread write check (per-statement must-hold locksets over the
  CFG; subsumes the lexical lock-discipline pass)
- ``lock_order`` — ``lock-order-deadlock``: cycles in the global
  lock-acquisition-order graph, plus held non-reentrant re-acquires
- ``barrier_flush`` — ``barrier-flush-completeness``: every barrier
  method reaches a flush of every bounded queue its class owns
"""

# NOTE: lockset_race MUST import (= register = run) before
# lock_discipline — the lexical rule consults the flow rule's reported
# keys to emit shared conflicts once (lockset wins).
from . import (  # noqa: F401
    bounded_queues,
    broad_except,
    fallback_discipline,
    host_sync,
    ingest_put,
    jit_purity,
    lockset_race,
    lock_discipline,
    lock_order,
    barrier_flush,
    retrace,
    thread_lifecycle,
)
