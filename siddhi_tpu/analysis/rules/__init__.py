"""Built-in rules — importing this package registers them all.

Catalog (one module per rule):

- ``host_sync``       — ``host-sync-hazard``: D2H materialization only
  through the count-gated emit drain (ex ``tests/test_emit_guard.py``)
- ``ingest_put``      — ``ingest-put-bypass``: H2D puts only through
  ``staged_put`` (ex ``tests/test_ingest_guard.py``)
- ``broad_except``    — ``broad-except-swallow``: no fault vanishes
  without a log/counter (ex ``tests/test_except_guard.py``)
- ``lock_discipline`` — ``lock-discipline``: attributes shared between a
  thread-entry function and the main batch path stay under the lock
  (MRO-aware: mixin threads and inherited methods resolve)
- ``jit_purity``      — ``jit-purity``: no host clock / logging / fault
  hooks / tracer materialization inside jitted callables, helpers in
  other modules included
- ``retrace``         — ``retrace-hazard``: no un-memoized
  ``jax.jit``/``shard_map`` on per-batch functions, including builders
  called across modules
- ``fallback_discipline`` — ``fallback-discipline``: every
  ``except SiddhiAppCreationError`` fallback gate reaches both a
  ``log.warning`` and a counted ``record_*_fallback`` stats write
- ``thread_lifecycle`` — ``thread-lifecycle``: every Thread/Timer is
  daemon or joined/cancelled on an owner-class shutdown path
- ``bounded_queues`` — ``bounded-queue-discipline``: every deque/Queue
  in ``core/``, ``transport/`` and ``robustness/`` carries an explicit
  bound (``maxlen=``/``maxsize=``) or an allowlist justification
"""

from . import (  # noqa: F401
    bounded_queues,
    broad_except,
    fallback_discipline,
    host_sync,
    ingest_put,
    jit_purity,
    lock_discipline,
    retrace,
    thread_lifecycle,
)
