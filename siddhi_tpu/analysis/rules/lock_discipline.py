"""lock-discipline: cross-thread attribute writes stay under the lock.

The engine spawns real threads: the async junction worker
(``core/stream.py``), the scheduler's wall-clock timer
(``util/scheduler.py``), the statistics reporter, the playback
heartbeat, the periodic-persist daemon (``core/app_runtime.py``),
the checkpoint writer (``durability/writer.py``), the service
listener, and the transport reconnect chain (``threading.Timer`` in
``transport/retry.py``).  All of them share
mutable engine state with the main batch path; the convention is that
shared state is touched under the engine lock (``process_lock`` or a
component lock), but nothing enforced it — PRs 1–4 added emit/ingest
queues and scheduler interactions that no guard checked at all.

Per class, the rule:

1. finds **thread entries**: methods or local functions passed as
   ``threading.Thread(target=...)`` / ``threading.Timer(..., fn)``;
2. closes them over ``self.method()`` calls — a call made inside a
   ``with <...lock>`` block does NOT extend the closure (the callee runs
   lock-protected there, like ``Scheduler._loop`` →
   ``advance`` under ``process_lock``);
3. collects direct ``self.<attr>`` writes on both sides (constructors
   — ``__init__`` and the transport SPI's ``init``/``_init_*``
   initializers — are excluded: construction happens-before thread
   start);
4. reports every attribute written by BOTH a thread-side function and a
   main-path method where any write site is not lexically under a
   lock-``with``.

With a ``ProjectIndex`` (the normal whole-program run), every step
resolves through the class's **MRO**: a ``threading.Timer`` armed in a
mixin (``ConnectRetryMixin``) is a thread entry of every class that
inherits it, ``self.<method>`` targets and closure calls dispatch to
the defining module, and write sites carry the file that owns them.  A
conflict whose participating sites are identical across several classes
(mixin-internal state seen through each subclass) is reported once, on
the base-most class.  Without a project (fixture mode) the rule is the
original single-module lexical pass.

The lexical lock check is conservative by design: disciplines the rule
cannot see (GIL-atomic monotonic flags, caller-holds-lock contracts)
are allowlisted per attribute with a written justification.

Since the flow-sensitive ``lockset-race`` rule landed, this pass is a
**thin compatibility wrapper**: in a full-catalog run it stands down
entirely — the lockset rule reports the same conflicts under the same
``<rel>:<Class.attr>`` keys with per-statement precision (its
allowlist inherited this rule's entries verbatim) — and only
standalone runs (``--rules lock-discipline``, fixture harnesses)
exercise the original lexical behavior.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from ..framework import Finding, Rule, register
from ..index import ModuleIndex
from ..locksets import thread_target_of as _target_of

#: a write-site record: (scope qualname, line, under_lock, rel)
_Site = Tuple[str, int, bool, str]


@register
class LockDisciplineRule(Rule):
    name = "lock-discipline"
    description = (
        "attribute written from both a thread-entry function and the "
        "main path without the engine lock")

    def begin(self):
        # candidate conflicts across classes, for base-most dedup:
        # (attr, site identity) -> [(class fq, Finding)]
        self._candidates: Dict[Tuple[str, frozenset],
                               List[Tuple[str, Finding]]] = {}

    def check(self, index: ModuleIndex) -> Iterable[Finding]:
        if self.project is not None:
            return  # whole-program pass runs in finish()
        for cls_qual, cls in index.classes.items():
            methods = {
                n.name: (index, n, cls_qual) for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
            yield from self._check_class(index, cls_qual, cls, methods)

    def finish(self) -> Iterable[Finding]:
        if self.project is None:
            return ()
        # compatibility-wrapper mode: when the flow-sensitive
        # lockset-race rule ran earlier in this run (registration order
        # guarantees it in the full catalog), this rule stands down —
        # every conflict the lexical pass can see, the lockset pass
        # sees with strictly better precision (same Class.attr keys),
        # so shared findings emit once and lexical-only candidates are
        # the flow pass's *proven-safe* set, not new signal.  Run
        # standalone (--rules lock-discipline, fixture harnesses) the
        # stash is absent and the full lexical behavior remains.
        if getattr(self.project, "_lockset_keys", None) is not None:
            return ()
        for fq_class in sorted(self.project.classes):
            idx, cls = self.project.classes[fq_class]
            methods = self.project.class_methods(fq_class)
            for f in self._check_class(
                    idx, idx.def_qualname(cls), cls, methods,
                    fq_class=fq_class):
                pass  # collected in self._candidates
        return self._dedup_candidates()

    # -- per-class analysis -------------------------------------------------

    def _own_nodes(self, index: ModuleIndex, fn: ast.AST, qual: str):
        """Walk ``fn``'s body excluding nested function/class scopes —
        a local ``def loop()`` inside ``start()`` is its own scope."""
        for node in ast.walk(fn):
            if node is fn:
                continue
            if index.qualname(node) == qual:
                yield node

    def _self_writes(self, index: ModuleIndex, fn: ast.AST, qual: str
                     ) -> List[_Site]:
        """Every direct ``self.x = / +=`` in ``fn``'s own scope."""
        out = []
        for node in self._own_nodes(index, fn, qual):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in ("self", "cls"):
                    out.append((t.attr, t.lineno, index.under_lock(t),
                                index.rel))
        return out

    def _self_calls(self, index: ModuleIndex, fn: ast.AST, qual: str
                    ) -> List[Tuple[str, bool]]:
        """(method name, under_lock) for every ``self.m(...)`` call in
        ``fn``'s own scope."""
        out = []
        for node in self._own_nodes(index, fn, qual):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id in ("self", "cls"):
                out.append((node.func.attr, index.under_lock(node)))
        return out

    def _check_class(self, cls_index: ModuleIndex, cls_qual: str,
                     cls: ast.ClassDef,
                     methods: Dict[str, Tuple[ModuleIndex, ast.AST, str]],
                     fq_class: Optional[str] = None) -> Iterable[Finding]:
        """``methods`` carries (defining index, def node, owner) per
        name — the single-class dict lexically, the MRO-merged table in
        project mode."""
        # 1. thread entries
        roots: List[Tuple[str, ModuleIndex, ast.AST, str]] = []
        for mname, (m_idx, m, _owner) in methods.items():
            # thread ctors may sit inside a local def, so scan the full
            # method subtree (not just its own scope)
            for node in ast.walk(m):
                if not isinstance(node, ast.Call):
                    continue
                tgt = _target_of(node, m_idx)
                if tgt is None:
                    continue
                kind, tname = tgt
                if kind == "method" and tname in methods:
                    t_idx, t_fn, _ = methods[tname]
                    roots.append((tname, t_idx, t_fn,
                                  t_idx.def_qualname(t_fn)))
                elif kind == "local":
                    # resolve the local function def by qualified name,
                    # searching outward from the launching scope
                    scope = m_idx.qualname(node)
                    fn = m_idx.functions.get(f"{scope}.{tname}")
                    if fn is not None:
                        roots.append((tname, m_idx, fn, f"{scope}.{tname}"))
        if not roots:
            return
        # 2. closure over unlocked self.method() calls
        thread_fns: Dict[str, Tuple[ModuleIndex, ast.AST, str]] = {}
        work = list(roots)
        while work:
            label, f_idx, fn, qual = work.pop()
            if label in thread_fns:
                continue
            thread_fns[label] = (f_idx, fn, qual)
            for callee, locked in self._self_calls(f_idx, fn, qual):
                if locked:
                    continue  # callee runs under the lock at this site
                if callee in methods and callee not in thread_fns:
                    c_idx, c_fn, _ = methods[callee]
                    work.append((callee, c_idx, c_fn,
                                 c_idx.def_qualname(c_fn)))
        # 3. writes on each side
        thread_writes: Dict[str, List[_Site]] = {}
        for label, (f_idx, fn, qual) in thread_fns.items():
            for site in self._self_writes(f_idx, fn, qual):
                thread_writes.setdefault(site[0], []).append(
                    (qual,) + site[1:])
        main_writes: Dict[str, List[_Site]] = {}
        for mname, (m_idx, m, _owner) in methods.items():
            if mname in thread_fns or mname in ("__init__", "__new__",
                                                "init") \
                    or mname.startswith("_init"):
                continue
            mqual = m_idx.def_qualname(m)
            for site in self._self_writes(m_idx, m, mqual):
                main_writes.setdefault(site[0], []).append(
                    (mqual,) + site[1:])
        # 4. conflicts: one finding per attribute, keyed Class.attr
        for attr in sorted(set(thread_writes) & set(main_writes)):
            sites = thread_writes[attr] + main_writes[attr]
            unlocked = [(q, ln, rel) for q, ln, locked, rel in sites
                        if not locked]
            if not unlocked:
                continue
            where = ", ".join(f"{q}:{ln}" for q, ln, _rel in unlocked)
            finding = Finding(
                rule=self.name,
                rel=cls_index.rel,
                line=unlocked[0][1],
                scope=f"{cls_qual}.{attr}",
                message=(
                    f"'{attr}' is written from both a thread entry "
                    f"({', '.join(sorted(thread_fns))}) and the main "
                    f"path, with unlocked write(s) at {where} — guard "
                    "every write with the engine/component lock, or "
                    "allowlist with a justification"),
            )
            if fq_class is None:
                yield finding
            else:
                ident = frozenset((rel, ln) for _q, ln, _lk, rel in sites)
                self._candidates.setdefault(
                    (attr, ident), []).append((fq_class, finding))

    def _dedup_candidates(self) -> Iterable[Finding]:
        """One finding per (attr, site set): inherited mixin state
        shows the same conflict through every subclass — report it on
        the base-most class in the group."""
        out: List[Finding] = []
        for (_attr, _ident), group in sorted(
                self._candidates.items(),
                key=lambda kv: (kv[1][0][1].rel, kv[1][0][1].scope)):
            if len(group) == 1:
                out.append(group[0][1])
                continue
            base = None
            for fq, finding in group:
                if all(fq in self.project.mro(other)
                       for other, _f in group):
                    base = finding
                    break
            if base is None:
                base = sorted(group,
                              key=lambda g: (g[1].rel, g[1].scope))[0][1]
            out.append(base)
        return out
