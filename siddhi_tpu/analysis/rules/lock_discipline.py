"""lock-discipline: cross-thread attribute writes stay under the lock.

The engine spawns real threads: the async junction worker
(``core/stream.py``), the scheduler's wall-clock timer
(``util/scheduler.py``), the statistics reporter, the playback
heartbeat, the periodic-persist daemon (``core/app_runtime.py``),
the checkpoint writer (``durability/writer.py``), the service
listener, and the transport reconnect chain (``threading.Timer`` in
``transport/retry.py``).  All of them share
mutable engine state with the main batch path; the convention is that
shared state is touched under the engine lock (``process_lock`` or a
component lock), but nothing enforced it — PRs 1–4 added emit/ingest
queues and scheduler interactions that no guard checked at all.

Per class, the rule:

1. finds **thread entries**: methods or local functions passed as
   ``threading.Thread(target=...)`` / ``threading.Timer(..., fn)``;
2. closes them over ``self.method()`` calls — a call made inside a
   ``with <...lock>`` block does NOT extend the closure (the callee runs
   lock-protected there, like ``Scheduler._loop`` →
   ``advance`` under ``process_lock``);
3. collects direct ``self.<attr>`` writes on both sides (constructors
   — ``__init__`` and the transport SPI's ``init``/``_init_*``
   initializers — are excluded: construction happens-before thread
   start);
4. reports every attribute written by BOTH a thread-side function and a
   main-path method where any write site is not lexically under a
   lock-``with``.

The lexical lock check is conservative by design: disciplines the rule
cannot see (GIL-atomic monotonic flags, caller-holds-lock contracts)
are allowlisted per attribute with a written justification.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..framework import Finding, Rule, register
from ..index import ModuleIndex

_THREAD_CTORS = {"threading.Thread", "Thread"}
_TIMER_CTORS = {"threading.Timer", "Timer"}


def _target_of(call: ast.Call, index: ModuleIndex):
    """(kind, node) for a thread-launching call: kind 'method' with the
    method name, or 'local' with the Name node of a local function."""
    name = index.dotted(call.func)
    target = None
    if name in _THREAD_CTORS:
        for kw in call.keywords:
            if kw.arg == "target":
                target = kw.value
    elif name in _TIMER_CTORS:
        if len(call.args) >= 2:
            target = call.args[1]
        else:
            for kw in call.keywords:
                if kw.arg == "function":
                    target = kw.value
    if target is None:
        return None
    if isinstance(target, ast.Attribute) and \
            isinstance(target.value, ast.Name) and \
            target.value.id in ("self", "cls"):
        return ("method", target.attr)
    if isinstance(target, ast.Name):
        return ("local", target.id)
    return None


@register
class LockDisciplineRule(Rule):
    name = "lock-discipline"
    description = (
        "attribute written from both a thread-entry function and the "
        "main path without the engine lock")

    def check(self, index: ModuleIndex) -> Iterable[Finding]:
        for cls_qual, cls in index.classes.items():
            yield from self._check_class(index, cls_qual, cls)

    # -- per-class analysis -------------------------------------------------

    def _methods(self, cls: ast.ClassDef) -> Dict[str, ast.AST]:
        return {n.name: n for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}

    def _own_nodes(self, index: ModuleIndex, fn: ast.AST, qual: str):
        """Walk ``fn``'s body excluding nested function/class scopes —
        a local ``def loop()`` inside ``start()`` is its own scope."""
        for node in ast.walk(fn):
            if node is fn:
                continue
            if index.qualname(node) == qual:
                yield node

    def _self_writes(self, index: ModuleIndex, fn: ast.AST, qual: str
                     ) -> List[Tuple[str, int, bool]]:
        """(attr, line, under_lock) for every direct ``self.x = / +=``
        in ``fn``'s own scope."""
        out = []
        for node in self._own_nodes(index, fn, qual):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in ("self", "cls"):
                    out.append((t.attr, t.lineno, index.under_lock(t)))
        return out

    def _self_calls(self, index: ModuleIndex, fn: ast.AST, qual: str
                    ) -> List[Tuple[str, bool]]:
        """(method name, under_lock) for every ``self.m(...)`` call in
        ``fn``'s own scope."""
        out = []
        for node in self._own_nodes(index, fn, qual):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id in ("self", "cls"):
                out.append((node.func.attr, index.under_lock(node)))
        return out

    def _check_class(self, index: ModuleIndex, cls_qual: str,
                     cls: ast.ClassDef) -> Iterable[Finding]:
        methods = self._methods(cls)
        # 1. thread entries
        roots: List[Tuple[str, ast.AST, str]] = []  # (label, fn, qual)
        for mname, m in methods.items():
            # thread ctors may sit inside a local def, so scan the full
            # method subtree (not just its own scope)
            for node in ast.walk(m):
                if not isinstance(node, ast.Call):
                    continue
                tgt = _target_of(node, index)
                if tgt is None:
                    continue
                kind, tname = tgt
                if kind == "method" and tname in methods:
                    roots.append((tname, methods[tname],
                                  f"{cls_qual}.{tname}"))
                elif kind == "local":
                    # resolve the local function def by qualified name,
                    # searching outward from the launching scope
                    scope = index.qualname(node)
                    fn = index.functions.get(f"{scope}.{tname}")
                    if fn is not None:
                        roots.append((tname, fn, f"{scope}.{tname}"))
        if not roots:
            return
        # 2. closure over unlocked self.method() calls
        thread_fns: Dict[str, Tuple[ast.AST, str]] = {}
        work = list(roots)
        while work:
            label, fn, qual = work.pop()
            if label in thread_fns:
                continue
            thread_fns[label] = (fn, qual)
            for callee, locked in self._self_calls(index, fn, qual):
                if locked:
                    continue  # callee runs under the lock at this site
                if callee in methods and callee not in thread_fns:
                    work.append((callee, methods[callee],
                                 f"{cls_qual}.{callee}"))
        # 3. writes on each side
        thread_writes: Dict[str, List[Tuple[str, int, bool]]] = {}
        for label, (fn, qual) in thread_fns.items():
            for attr, line, locked in self._self_writes(index, fn, qual):
                thread_writes.setdefault(attr, []).append(
                    (qual, line, locked))
        main_writes: Dict[str, List[Tuple[str, int, bool]]] = {}
        for mname, m in methods.items():
            if mname in thread_fns or mname in ("__init__", "__new__",
                                                "init") \
                    or mname.startswith("_init"):
                continue
            mqual = f"{cls_qual}.{mname}"
            for attr, line, locked in self._self_writes(index, m, mqual):
                main_writes.setdefault(attr, []).append(
                    (mqual, line, locked))
        # 4. conflicts: one finding per attribute, keyed Class.attr
        for attr in sorted(set(thread_writes) & set(main_writes)):
            sites = thread_writes[attr] + main_writes[attr]
            unlocked = [(q, ln) for q, ln, locked in sites if not locked]
            if not unlocked:
                continue
            where = ", ".join(f"{q}:{ln}" for q, ln in unlocked)
            yield Finding(
                rule=self.name,
                rel=index.rel,
                line=unlocked[0][1],
                scope=f"{cls_qual}.{attr}",
                message=(
                    f"'{attr}' is written from both a thread entry "
                    f"({', '.join(sorted(thread_fns))}) and the main "
                    f"path, with unlocked write(s) at {where} — guard "
                    "every write with the engine/component lock, or "
                    "allowlist with a justification"),
            )
