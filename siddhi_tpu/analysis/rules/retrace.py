"""retrace-hazard: no compile-cache churn on the per-batch path.

``jax.jit`` / ``shard_map`` wrapping is cheap, but every FRESH wrapper
carries its own trace cache: wrapping inside a per-batch/per-event
function and calling the result re-traces and re-compiles on every
batch — a silent 100-1000x slowdown that still produces correct
results.  The engine's discipline is that step builders memoize their
compiled callables (``self._step``, ``self._step_cache[key]``,
``self._kernels[(B, W)]``) so the hot path only ever LOOKS UP.

The rule finds jit/shard_map wrapping sites whose enclosing function
name matches the per-batch pattern (``process*``, ``*_chunk``,
``*_step``, ``receive``, ``advance``...) and reports them unless the
wrapped callable escapes into an instance attribute — directly
(``self._fn = jax.jit(f)``), through a subscript cache
(``self._cache[k] = jax.jit(f)``), or via a local binding that is then
stored (``k = jit(f); self._kernels[key] = k``).  Builders called from
``__init__`` only are not matched; a genuinely-sanctioned per-batch
wrap goes in the allowlist with a justification.

With a ``ProjectIndex`` the rule additionally follows one call-graph
hop: a hot-named function that calls a project-resolved **builder in
another scope** whose body wraps ``jax.jit``/``shard_map`` without
memoizing — neither inside the builder (``self._step_cache[...] =``
makes it safe; ``make_flush_step`` is the engine's canonical example)
nor at the call site (``self._fn = make_step(...)``) — re-compiles per
batch just the same, only with the wrap hidden a file away.  The
finding lands on the hot caller's call site.  Builders whose own name
matches the hot pattern are skipped there (the direct pass already
owns them).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Set

from ..framework import Finding, Rule, register
from ..index import ModuleIndex
from .jit_purity import jit_call_sites

#: function names that run once per batch/event/pane — the hot path.
HOT_NAME_RE = re.compile(
    r"(?:^|_)(process|receive|send|dispatch|deliver|publish|advance|fire|"
    r"drain|flush|submit|finish|emit|step|chunk|segment|scatter|reduce|"
    r"kernel|acc|on_time|sweep)(?:$|_)")


def _self_attr_target(node: ast.AST) -> bool:
    """True for ``self.x`` or ``self.x[...]`` assignment targets."""
    if isinstance(node, ast.Subscript):
        node = node.value
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id in ("self", "cls"))


def _escapes_to_instance(index: ModuleIndex, site: ast.Call,
                         hot_fn: ast.AST) -> bool:
    """Does the jit wrapper produced at ``site`` get memoized on the
    instance inside ``hot_fn``?"""
    # direct: an ancestor assignment whose target is self.x / self.x[k]
    local_names: Set[str] = set()
    for anc in index.ancestors(site):
        if anc is hot_fn:
            break
        if isinstance(anc, ast.Assign):
            for t in anc.targets:
                if _self_attr_target(t):
                    return True
                if isinstance(t, ast.Name):
                    local_names.add(t.id)
        elif isinstance(anc, (ast.AugAssign, ast.AnnAssign)) and \
                _self_attr_target(anc.target):
            return True
    if not local_names:
        return False
    # indirect: a local bound from the site is later stored on self
    for node in ast.walk(hot_fn):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Name) and \
                node.value.id in local_names:
            if any(_self_attr_target(t) for t in node.targets):
                return True
    return False


@register
class RetraceHazardRule(Rule):
    name = "retrace-hazard"
    description = (
        "un-memoized jax.jit/shard_map wrapping inside a per-batch "
        "function — compile cache churn on the hot path")

    def check(self, index: ModuleIndex) -> Iterable[Finding]:
        sites = jit_call_sites(index)
        site_nodes = {s for s, _ in sites}
        for site, _arg in sites:
            fn = index.enclosing(site, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))
            if fn is None:
                continue  # module-level wrap compiles once at import
            if not HOT_NAME_RE.search(fn.name):
                continue
            # a shard_map(...) nested inside jax.jit(shard_map(...)) is
            # covered by the outer wrapping site's escape analysis
            if any(anc in site_nodes for anc in index.ancestors(site)):
                continue
            if _escapes_to_instance(index, site, fn):
                continue
            yield Finding(
                rule=self.name,
                rel=index.rel,
                line=site.lineno,
                scope=index.def_qualname(fn),
                message=(
                    "jax.jit/shard_map wrapped inside a per-batch "
                    "function without memoizing the result on the "
                    "instance — every call re-traces and re-compiles; "
                    "hoist to a builder / cache it, or allowlist with "
                    "a justification"),
            )
        if self.project is not None:
            yield from self._cross_module(index)

    def _cross_module(self, index: ModuleIndex) -> Iterable[Finding]:
        """One call-graph hop: hot caller → builder (any scope) whose
        jit wrap neither memoizes internally nor at the call site."""
        seen: Set[tuple] = set()
        for qual, fn in index.functions.items():
            if not HOT_NAME_RE.search(fn.name):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if index.enclosing(node, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)) is not fn:
                    continue  # nested defs get their own hot-name check
                hit = self.project.resolve_call(index, node)
                if hit is None:
                    continue
                t_idx, t_fn, t_fq = hit
                if not isinstance(t_fn, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if t_fn is fn or HOT_NAME_RE.search(t_fn.name):
                    continue  # direct pass owns hot-named callees
                if not self._builds_fresh_jit(t_idx, t_fn):
                    continue
                if _escapes_to_instance(index, node, fn):
                    continue  # caller memoizes the built wrapper
                key = (index.rel, qual, t_fq)
                if key in seen:
                    continue
                seen.add(key)
                yield Finding(
                    rule=self.name,
                    rel=index.rel,
                    line=node.lineno,
                    scope=qual,
                    message=(
                        f"per-batch call to {t_fq}() which wraps "
                        "jax.jit/shard_map without memoizing — a fresh "
                        "trace cache per call; memoize the built "
                        "callable (builder-side cache or instance "
                        "attribute at this call site), or allowlist "
                        "with a justification"),
                )

    def _builds_fresh_jit(self, t_idx: ModuleIndex,
                          t_fn: ast.AST) -> bool:
        """Does ``t_fn`` contain a jit/shard_map wrap that does NOT
        escape into an instance cache (i.e. a new wrapper per call)?"""
        for site, _arg in jit_call_sites(t_idx):
            if t_idx.enclosing(site, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) is not t_fn:
                continue
            if not _escapes_to_instance(t_idx, site, t_fn):
                return True
        return False
