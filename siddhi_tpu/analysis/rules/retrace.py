"""retrace-hazard: no compile-cache churn on the per-batch path.

``jax.jit`` / ``shard_map`` wrapping is cheap, but every FRESH wrapper
carries its own trace cache: wrapping inside a per-batch/per-event
function and calling the result re-traces and re-compiles on every
batch — a silent 100-1000x slowdown that still produces correct
results.  The engine's discipline is that step builders memoize their
compiled callables (``self._step``, ``self._step_cache[key]``,
``self._kernels[(B, W)]``) so the hot path only ever LOOKS UP.

The rule finds jit/shard_map wrapping sites whose enclosing function
name matches the per-batch pattern (``process*``, ``*_chunk``,
``*_step``, ``receive``, ``advance``...) and reports them unless the
wrapped callable escapes into an instance attribute — directly
(``self._fn = jax.jit(f)``), through a subscript cache
(``self._cache[k] = jax.jit(f)``), or via a local binding that is then
stored (``k = jit(f); self._kernels[key] = k``).  Builders called from
``__init__`` only are not matched; a genuinely-sanctioned per-batch
wrap goes in the allowlist with a justification.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional, Set

from ..framework import Finding, Rule, register
from ..index import ModuleIndex
from .jit_purity import jit_call_sites

#: function names that run once per batch/event/pane — the hot path.
HOT_NAME_RE = re.compile(
    r"(?:^|_)(process|receive|send|dispatch|deliver|publish|advance|fire|"
    r"drain|flush|submit|finish|emit|step|chunk|segment|scatter|reduce|"
    r"kernel|acc|on_time|sweep)(?:$|_)")


def _self_attr_target(node: ast.AST) -> bool:
    """True for ``self.x`` or ``self.x[...]`` assignment targets."""
    if isinstance(node, ast.Subscript):
        node = node.value
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id in ("self", "cls"))


def _escapes_to_instance(index: ModuleIndex, site: ast.Call,
                         hot_fn: ast.AST) -> bool:
    """Does the jit wrapper produced at ``site`` get memoized on the
    instance inside ``hot_fn``?"""
    # direct: an ancestor assignment whose target is self.x / self.x[k]
    local_names: Set[str] = set()
    for anc in index.ancestors(site):
        if anc is hot_fn:
            break
        if isinstance(anc, ast.Assign):
            for t in anc.targets:
                if _self_attr_target(t):
                    return True
                if isinstance(t, ast.Name):
                    local_names.add(t.id)
        elif isinstance(anc, (ast.AugAssign, ast.AnnAssign)) and \
                _self_attr_target(anc.target):
            return True
    if not local_names:
        return False
    # indirect: a local bound from the site is later stored on self
    for node in ast.walk(hot_fn):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Name) and \
                node.value.id in local_names:
            if any(_self_attr_target(t) for t in node.targets):
                return True
    return False


@register
class RetraceHazardRule(Rule):
    name = "retrace-hazard"
    description = (
        "un-memoized jax.jit/shard_map wrapping inside a per-batch "
        "function — compile cache churn on the hot path")

    def check(self, index: ModuleIndex) -> Iterable[Finding]:
        sites = jit_call_sites(index)
        site_nodes = {s for s, _ in sites}
        for site, _arg in sites:
            fn = index.enclosing(site, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))
            if fn is None:
                continue  # module-level wrap compiles once at import
            if not HOT_NAME_RE.search(fn.name):
                continue
            # a shard_map(...) nested inside jax.jit(shard_map(...)) is
            # covered by the outer wrapping site's escape analysis
            if any(anc in site_nodes for anc in index.ancestors(site)):
                continue
            if _escapes_to_instance(index, site, fn):
                continue
            yield Finding(
                rule=self.name,
                rel=index.rel,
                line=site.lineno,
                scope=index.def_qualname(fn),
                message=(
                    "jax.jit/shard_map wrapped inside a per-batch "
                    "function without memoizing the result on the "
                    "instance — every call re-traces and re-compiles; "
                    "hoist to a builder / cache it, or allowlist with "
                    "a justification"),
            )
