"""barrier-flush-completeness: barriers drain every queue they own.

The engine's correctness barriers — shutdown/stop teardown,
snapshot/restore/persist state capture, replan — all carry the same
implicit obligation: any **bounded** staging buffer the component owns
must be empty (or explicitly handed off) when the barrier completes,
or events are silently stranded behind it (the rung-survival and
stale-TableCache bug shape PRs kept fixing by hand).  This rule makes
the obligation checkable:

- the **queue registry** derives from the bounded-queue-discipline
  rule's construction-site scan: every ``self.<attr> = deque(maxlen=)``
  / ``Queue(maxsize=)`` in its scopes (``core/``, ``transport/``,
  ``robustness/``) registers ``(owner class, attr)``;
- the owner class's **barrier methods** (any method named ``stop``,
  ``shutdown``, ``close``, ``snapshot``, ``restore``, ``persist`` or
  ``replan``, MRO-resolved; empty SPI stubs skipped) must each reach a
  **flush** of every registered queue;
- "reach" is CFG reachability through the call graph: a statement only
  counts if its basic block is reachable from the barrier's entry (a
  flush parked after an early ``return`` does not), and the walk
  follows project-resolved callees (``resolve_call``) up to the same
  closure bound the other reachability rules use;
- a "flush" is a drain call on the queue (``get``/``get_nowait``/
  ``popleft``/``pop``/``clear``) or a rebind of the owning attribute —
  receiver chains are matched on the queue's attribute leaf after
  expanding single-assignment local aliases (``sp = self._spool``),
  which is also the rule's resolution limit: a queue drained through a
  differently-named alias handle needs an allowlist entry saying so.

Cross-class barriers compose modularly: ``SiddhiAppRuntime.shutdown``
calls ``junction.stop()`` / ``sink.shutdown()`` through dynamically
typed registries the call graph cannot resolve, but each owner's own
barrier is verified to flush its own queues, which is exactly the
obligation the runtime delegates.  Whole-program only.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from ..framework import Finding, Rule, register
from ..index import ModuleIndex
from ..locksets import get_model, shallow_calls
from ..project import plain_dotted
from .bounded_queues import _BOUNDED_CTORS, _SCOPES

_BARRIER_NAMES = ("stop", "shutdown", "close", "snapshot", "restore",
                  "persist", "replan")

_DRAIN_OPS = {"get", "get_nowait", "popleft", "pop", "clear"}

_MAX_DEFS = 200


def _bounded_ctor(value: ast.AST, index: ModuleIndex) -> bool:
    """RHS constructs a bounded queue (conditional ctors — ``X if cond
    else None`` — count via either arm)."""
    if isinstance(value, ast.IfExp):
        return _bounded_ctor(value.body, index) or \
            _bounded_ctor(value.orelse, index)
    if not isinstance(value, ast.Call):
        return False
    name = index.dotted(value.func)
    spec = _BOUNDED_CTORS.get(name)
    if spec is None:
        return False
    kwarg, pos = spec
    if any(kw.arg == kwarg for kw in value.keywords):
        return True
    return len(value.args) > pos


def _is_stub(fn: ast.AST) -> bool:
    """SPI placeholder bodies (``pass``/docstring/``...``/``raise
    NotImplementedError``) carry no flush obligation — overriders do."""
    for stmt in fn.body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Constant):
            continue
        if isinstance(stmt, ast.Raise):
            continue
        return False
    return True


@register
class BarrierFlushRule(Rule):
    name = "barrier-flush-completeness"
    description = (
        "a barrier method (stop/shutdown/snapshot/...) does not reach "
        "a flush of a bounded queue its class owns")

    def check(self, index: ModuleIndex) -> Iterable[Finding]:
        return ()  # whole-program only

    def finish(self) -> Iterable[Finding]:
        if self.project is None:
            return ()
        model = get_model(self.project)
        findings: List[Finding] = []
        for fq_class in sorted(self.project.classes):
            idx, cls = self.project.classes[fq_class]
            if not idx.rel.startswith(_SCOPES):
                continue
            queues = self._owned_queues(idx, cls)
            if not queues:
                continue
            cls_qual = idx.def_qualname(cls)
            methods = self.project.class_methods(fq_class)
            barriers = [
                (n,) + methods[n] for n in _BARRIER_NAMES
                if n in methods and not _is_stub(methods[n][1])]
            if not barriers:
                for attr, line in queues:
                    findings.append(Finding(
                        rule=self.name,
                        rel=idx.rel,
                        line=line,
                        scope=f"{cls_qual}.{attr}",
                        message=(
                            f"'{cls_qual}' owns bounded queue "
                            f"'{attr}' but declares no barrier method "
                            f"({'/'.join(_BARRIER_NAMES)}) that could "
                            "flush it — add a teardown path, or "
                            "allowlist with a justification"),
                    ))
                continue
            for bname, b_idx, b_fn, _owner in barriers:
                flushed = self._flushed_attrs(model, b_idx, b_fn)
                for attr, line in queues:
                    if attr in flushed:
                        continue
                    findings.append(Finding(
                        rule=self.name,
                        rel=idx.rel,
                        line=line,
                        scope=f"{cls_qual}.{bname}:{attr}",
                        message=(
                            f"barrier '{cls_qual}.{bname}' never "
                            f"reaches a flush of bounded queue "
                            f"'{attr}' (no reachable "
                            f"{'/'.join(sorted(_DRAIN_OPS))} or rebind "
                            "through the call graph) — drain it on "
                            "this path, or allowlist with a "
                            "justification"),
                    ))
        return findings

    # -- registry ------------------------------------------------------------

    def _owned_queues(self, idx: ModuleIndex, cls: ast.ClassDef
                      ) -> List[Tuple[str, int]]:
        out = []
        seen: Set[str] = set()
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            if not _bounded_ctor(node.value, idx):
                continue
            for t in node.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in ("self", "cls") and \
                        t.attr not in seen:
                    seen.add(t.attr)
                    out.append((t.attr, node.lineno))
        return out

    # -- reachability --------------------------------------------------------

    def _flushed_attrs(self, model, idx: ModuleIndex, root: ast.AST
                       ) -> Set[str]:
        """Queue-attribute leaves drained on some CFG-reachable path
        from ``root``, following resolved callees."""
        flushed: Set[str] = set()
        work: List[Tuple[ModuleIndex, ast.AST]] = [(idx, root)]
        seen: Set[int] = set()
        while work and len(seen) < _MAX_DEFS:
            f_idx, fn = work.pop()
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            try:
                cfg = model.cfg_of(fn)
            except (TypeError, SyntaxError):  # pragma: no cover
                continue
            live = cfg.reachable()
            aliases = model.aliases_of(f_idx, fn)
            for block in cfg.blocks:
                if block.bid not in live:
                    continue
                for stmt in block.stmts:
                    self._scan_stmt(f_idx, stmt, aliases, flushed, work)
        return flushed

    def _scan_stmt(self, idx: ModuleIndex, stmt, aliases,
                   flushed: Set[str], work):
        # rebinding the attribute is a flush (restore-style barriers)
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in ("self", "cls"):
                    flushed.add(t.attr)
        for call in shallow_calls(stmt):
            func = call.func
            if isinstance(func, ast.Attribute) and \
                    func.attr in _DRAIN_OPS:
                leaf = self._receiver_leaf(func.value, aliases)
                if leaf is not None:
                    flushed.add(leaf)
                continue
            hit = self.project.resolve_call(idx, call)
            if hit is not None:
                work.append((hit[0], hit[1]))

    @staticmethod
    def _receiver_leaf(value: ast.AST, aliases) -> str:
        """Last attribute component of the drain receiver, aliases
        expanded (``sp`` -> ``self._spool`` -> ``_spool``)."""
        p = plain_dotted(value)
        if p is None:
            return None
        parts = p.split(".")
        if parts[0] in aliases:
            parts = aliases[parts[0]].split(".") + parts[1:]
        leaf = parts[-1]
        if leaf in ("self", "cls"):
            return None
        return leaf
