"""Rule registry, findings, and allowlist discipline.

A ``Rule`` visits pre-parsed ``ModuleIndex`` objects and yields
``Finding``s.  Every finding carries a stable **key**
(``<relpath>:<scope-or-attribute>``) that allowlists and baselines match
on — keys deliberately exclude line numbers so unrelated edits above a
sanctioned site don't churn the lists.

Allowlist contract (enforced, not advisory):

- every entry MUST carry a non-empty written justification — the
  decision to sanction a violation stays visible in review;
- entries expire: after a run, an allowlisted key that no longer
  matches any finding becomes a ``stale-allowlist`` finding itself, so
  the lists can only shrink when the code improves (the old guard
  tests' ``test_allowlist_not_stale`` generalized).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence

from .index import ModuleIndex

#: rule name -> Rule instance, in registration order
_REGISTRY: Dict[str, "Rule"] = {}


@dataclass(frozen=True)
class Finding:
    rule: str
    rel: str          # repo-relative posix path
    line: int
    scope: str        # qualified enclosing scope ("Class.method")
    message: str
    #: allowlist/baseline key; defaults to "<rel>:<scope>"
    key: str = field(default="")

    def __post_init__(self):
        if not self.key:
            object.__setattr__(self, "key", f"{self.rel}:{self.scope}")

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "file": self.rel,
            "line": self.line,
            "scope": self.scope,
            "key": self.key,
            "message": self.message,
        }

    def render(self) -> str:
        return f"{self.rel}:{self.line} [{self.rule}] {self.scope}: {self.message}"


class Allowlist:
    """Per-rule sanctioned findings: key -> written justification."""

    def __init__(self, rule: str, entries: Optional[Dict[str, str]] = None):
        self.rule = rule
        self.entries: Dict[str, str] = dict(entries or {})
        for key, why in self.entries.items():
            if not (isinstance(why, str) and why.strip()):
                raise ValueError(
                    f"allowlist entry {rule}:{key!r} has no justification "
                    "— every sanctioned violation must say why")

    def __contains__(self, key: str) -> bool:
        return key in self.entries

    def split(self, findings: Sequence[Finding]):
        """(kept, suppressed, stale) — ``stale`` are synthetic findings
        for entries that matched nothing (expiry)."""
        kept: List[Finding] = []
        suppressed: List[Finding] = []
        matched = set()
        for f in findings:
            if f.key in self.entries:
                matched.add(f.key)
                suppressed.append(f)
            else:
                kept.append(f)
        stale = [
            Finding(
                rule="stale-allowlist",
                rel=key.split(":", 1)[0],
                line=0,
                scope=key.split(":", 1)[1] if ":" in key else key,
                message=(f"allowlisted for rule '{self.rule}' but no "
                         "longer trips it — prune the entry "
                         f"(justification was: {self.entries[key]!r})"),
                key=f"{self.rule}:{key}",
            )
            for key in sorted(set(self.entries) - matched)
        ]
        return kept, suppressed, stale


class Rule:
    """One invariant.  Subclasses set ``name``/``description`` and
    implement ``check(index) -> Iterable[Finding]``; the default
    allowlist ships in ``allowlists.py`` and can be overridden per run
    (tests exercise rules against fixture allowlists this way)."""

    name: str = ""
    description: str = ""
    #: the whole-program ``ProjectIndex`` for the current run, set by
    #: ``run_rules`` before ``begin()``.  ``None`` = single-module
    #: lexical mode (rules degrade to their pre-cross-module behavior;
    #: fixture tests pin both resolutions this way).
    project = None

    def begin(self):
        """Hook: called once per run before any module is visited —
        cross-module rules reset their accumulated state here (rule
        instances are registry singletons shared across runs)."""

    def check(self, index: ModuleIndex) -> Iterable[Finding]:
        raise NotImplementedError

    def finish(self) -> Iterable[Finding]:
        """Hook for cross-module rules: called once after every index
        has been visited."""
        return ()

    def default_allowlist(self) -> Allowlist:
        from . import allowlists

        return Allowlist(self.name, allowlists.ALLOWLISTS.get(self.name, {}))


def register(cls):
    """Class decorator: instantiate and register a rule by name."""
    rule = cls()
    if not rule.name:
        raise ValueError(f"{cls.__name__} has no rule name")
    if rule.name in _REGISTRY:
        raise ValueError(f"duplicate rule name {rule.name!r}")
    _REGISTRY[rule.name] = rule
    return cls


def all_rules() -> List[Rule]:
    return list(_REGISTRY.values())


def get_rule(name: str) -> Rule:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown rule {name!r}; registered: {sorted(_REGISTRY)}"
        ) from None


def run_rules(indexes: Sequence[ModuleIndex],
              rules: Optional[Sequence[Rule]] = None,
              allowlists: Optional[Dict[str, Allowlist]] = None,
              project=None,
              ) -> Dict[str, List[Finding]]:
    """Run rules over pre-parsed modules.

    A ``ProjectIndex`` over ``indexes`` is built (or taken from
    ``project``) and handed to every rule as ``rule.project`` — the
    cross-module resolution layer for imports, class hierarchies, and
    the call graph.

    Returns ``{"findings": unsuppressed (stale entries included),
    "suppressed": allowlisted}`` — the caller applies any baseline."""
    from .project import ProjectIndex

    rules = list(rules) if rules is not None else all_rules()
    if project is None:
        project = ProjectIndex(indexes)
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    for rule in rules:
        rule.project = project
        rule.begin()
        raw: List[Finding] = []
        for index in indexes:
            raw.extend(rule.check(index))
        raw.extend(rule.finish())
        al = (allowlists or {}).get(rule.name) or rule.default_allowlist()
        kept, supp, stale = al.split(raw)
        findings.extend(kept)
        findings.extend(stale)
        suppressed.extend(supp)
        # registry rules are singletons: drop the project reference so
        # a later direct rule.check() (fixture tests) runs lexically
        rule.project = None
    findings.sort(key=lambda f: (f.rel, f.line, f.rule))
    return {"findings": findings, "suppressed": suppressed}
