"""Generic forward/backward dataflow over ``cfg.CFG`` graphs.

A worklist fixpoint engine rules plug into by subclassing
:class:`Analysis` — a join-semilattice of facts plus a per-statement
transfer function.  The engine is direction-agnostic (``forward`` walks
successor edges from the entry, ``backward`` predecessor edges from the
exit), iterates to a fixpoint under a hard iteration bound, and applies
``widen`` once a block has been revisited more than ``WIDEN_AFTER``
times — for the finite lock-token lattices used today widening never
fires, but the bound keeps a buggy transfer function from hanging the
analyzer (the CFG corpus sweep in ``tests/test_analysis_cfg.py`` pins
``converged`` over every function in the package).

Must-analyses (held locksets) use ``TOP`` as the not-yet-reached value:
``join(TOP, x) == x``, so unreached predecessors don't erase facts, and
a block whose input is still ``TOP`` after the fixpoint is simply
unreachable — rules skip findings there.
"""

from __future__ import annotations

from typing import Dict, Iterator, Tuple

from .cfg import CFG, Block

__all__ = ["Analysis", "Result", "TOP", "solve", "stmt_facts"]


class _Top:
    """Sentinel: 'every fact' — the identity of a must-join."""

    __slots__ = ()

    def __repr__(self):  # pragma: no cover - debug aid
        return "TOP"


TOP = _Top()

#: revisits of one block before ``widen`` kicks in
WIDEN_AFTER = 8


class Analysis:
    """One dataflow problem: subclass and implement the lattice."""

    #: "forward" or "backward"
    direction = "forward"

    def initial(self, cfg: CFG):
        """Fact at the entry (forward) / exit (backward) boundary."""
        raise NotImplementedError

    def join(self, a, b):
        """Least upper bound of two facts (handle ``TOP``)."""
        raise NotImplementedError

    def equals(self, a, b) -> bool:
        return a == b

    def transfer(self, stmt, fact):
        """Fact after one statement given the fact before it."""
        raise NotImplementedError

    def widen(self, old, new):
        """Accelerate convergence after ``WIDEN_AFTER`` revisits; the
        default keeps the new fact (finite lattices converge anyway)."""
        return new

    # -- derived ------------------------------------------------------------

    def transfer_block(self, block: Block, fact):
        if fact is TOP:
            return TOP
        for s in block.stmts:
            fact = self.transfer(s, fact)
        return fact


class Result:
    """Fixpoint facts: ``block_in[bid]`` / ``block_out[bid]``."""

    __slots__ = ("block_in", "block_out", "converged", "steps")

    def __init__(self, block_in: Dict[int, object],
                 block_out: Dict[int, object],
                 converged: bool, steps: int):
        self.block_in = block_in
        self.block_out = block_out
        self.converged = converged
        self.steps = steps


def solve(cfg: CFG, analysis: Analysis, max_steps: int = 0) -> Result:
    """Iterate ``analysis`` over ``cfg`` to a fixpoint.

    ``max_steps`` bounds total block evaluations (0 = the default bound,
    proportional to graph size); on overrun the result is returned
    as-is with ``converged=False`` — callers treat that as 'no facts'
    (the conservative answer for a must-analysis).
    """
    forward = analysis.direction == "forward"
    start = cfg.entry if forward else cfg.exit
    if not max_steps:
        max_steps = 256 + 16 * len(cfg.blocks) * max(
            1, sum(len(b.succs) for b in cfg.blocks))
    block_in: Dict[int, object] = {b.bid: TOP for b in cfg.blocks}
    block_out: Dict[int, object] = {b.bid: TOP for b in cfg.blocks}
    block_in[start.bid] = analysis.initial(cfg)
    visits: Dict[int, int] = {}
    work = [start]
    queued = {start.bid}
    steps = 0
    converged = True
    while work:
        steps += 1
        if steps > max_steps:
            converged = False
            break
        block = work.pop(0)
        queued.discard(block.bid)
        out = analysis.transfer_block(block, block_in[block.bid])
        old = block_out[block.bid]
        if old is not TOP and not (out is TOP or
                                   analysis.equals(old, out)):
            visits[block.bid] = visits.get(block.bid, 0) + 1
            if visits[block.bid] > WIDEN_AFTER:
                out = analysis.widen(old, out)
        if old is not TOP and (out is TOP or analysis.equals(old, out)):
            continue
        block_out[block.bid] = out
        nexts = block.succs if forward else block.preds
        for nxt in nexts:
            cur = block_in[nxt.bid]
            if cur is TOP:
                joined = out
            elif out is TOP:
                joined = cur
            else:
                joined = analysis.join(cur, out)
            if cur is TOP or not analysis.equals(cur, joined):
                block_in[nxt.bid] = joined
                if nxt.bid not in queued:
                    queued.add(nxt.bid)
                    work.append(nxt)
    return Result(block_in, block_out, converged, steps)


def stmt_facts(cfg: CFG, analysis: Analysis, result: Result
               ) -> Iterator[Tuple[Block, object, object]]:
    """Replay the transfer inside each block, yielding
    ``(block, stmt, fact_before_stmt)`` — the per-statement view the
    lockset rules consume.  Blocks whose input is ``TOP`` (unreachable)
    yield ``TOP`` facts; rules skip them.  Forward direction only."""
    for block in cfg.blocks:
        fact = result.block_in[block.bid]
        for s in block.stmts:
            yield (block, s, fact)
            if fact is not TOP:
                fact = analysis.transfer(s, fact)
