"""Watchdog: stall detection and self-healing restore-and-replay.

A per-app daemon thread (PlanMonitor-style lifecycle) that watches the
app's progress beat — a counter every junction dispatch and journaled
ingest bumps — against the pending-work gauges (async junction queues,
staged ingest windows, pending emit drains).  Liveness contract:

- **progress**  — the beat advanced since the last tick: healthy.
- **near-miss** — work is pending and the beat is older than half the
  deadline: counted once per episode, feeds the degradation ladder.
- **stall**     — work is pending and the beat is older than the full
  deadline (a wedged batch cycle or emit drain): the watchdog fires
  the ``watchdog.trip`` fault site, freezes a FlightRecorder dump
  (``tracer.dump('watchdog-trip')``), and self-heals by forcing
  ``runtime.replan`` with the current pins — pause, rebuild the whole
  engine set (fresh junction workers replace any wedged ones), and
  replay the journal's full history through the suppressing output
  ledger.  Recovery is bit-identical by the replan contract; without a
  full-coverage journal it is REFUSED loudly (logged + counted), never
  attempted on a prayer.

The trip path acquires the process lock with a timeout first: if the
wedge HOLDS the lock, a replan would deadlock the watchdog too, so
that state is reported (counted recovery failure) instead of healed.
"""

from __future__ import annotations

import logging
import threading
import time

log = logging.getLogger("siddhi_tpu")


class Watchdog:
    def __init__(self, runtime, stats, deadline_ms: int, ladder=None,
                 interval_ms: int = 0):
        self.runtime = runtime
        self.stats = stats
        self.deadline_ms = int(deadline_ms)
        self.ladder = ladder
        self.interval_s = (interval_ms or max(self.deadline_ms // 4, 10)
                           ) / 1000.0
        self._stop = threading.Event()
        self._thread = None
        self._last_beats = -1
        self._last_progress = time.monotonic()
        self._last_shed = 0
        self._in_near_miss = False
        #: health-endpoint state
        self.wedged = False
        self.last_trip_wall = 0.0

    # -- lifecycle ----------------------------------------------------

    def start(self):
        if self._thread is not None:
            return
        self._stop.clear()
        self._last_progress = time.monotonic()
        self._thread = threading.Thread(
            target=self._loop,
            name=f"watchdog-{self.runtime.app_context.name}",
            daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        t = self._thread
        self._thread = None
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5)

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — daemon must survive a bad tick
                log.exception(
                    "app '%s': watchdog tick failed",
                    self.runtime.app_context.name)
            except BaseException:
                # injected crash (SimulatedCrashError) kills the daemon,
                # same contract as the scheduler/persist daemons
                break

    # -- detection ----------------------------------------------------

    def _tick(self):
        self.stats.watchdog_ticks += 1
        ctx = self.runtime.app_context
        beats = ctx.progress.beats
        now = time.monotonic()
        if beats != self._last_beats:
            self._last_beats = beats
            self._last_progress = now
            self.wedged = False
            self._in_near_miss = False
        pending = self.runtime._pending_work()
        age_ms = (now - self._last_progress) * 1000.0
        stalled = pending > 0 and age_ms >= self.deadline_ms
        near = pending > 0 and not stalled and \
            age_ms >= self.deadline_ms / 2.0
        if near and not self._in_near_miss:
            self.stats.watchdog_near_misses += 1
            self._in_near_miss = True
        if self.ladder is not None:
            shed_total = self.stats.events_shed
            shed_delta = shed_total - self._last_shed
            self._last_shed = shed_total
            pressure = max(
                self.runtime._queue_fill(),
                1.0 if shed_delta > 0 else 0.0,
                1.0 if (near or stalled) else 0.0,
            )
            self.ladder.observe(pressure)
        if stalled:
            self._trip(age_ms, pending)

    # -- recovery -----------------------------------------------------

    def _trip(self, age_ms: float, pending: int):
        ctx = self.runtime.app_context
        self.stats.watchdog_trips += 1
        self.wedged = True
        self.last_trip_wall = time.monotonic()
        # back off a full deadline before re-tripping, whatever happens
        # below — a failed heal must not spin the trip counter
        self._last_progress = time.monotonic()
        fi = getattr(ctx, "fault_injector", None)
        if fi is not None:
            # choke point: a transient here aborts THIS trip (the loop
            # catches it and the still-stalled app re-trips next
            # deadline); a crash kills the daemon like any other
            fi.check("watchdog.trip")
        tracer = getattr(ctx, "tracer", None)
        if tracer is not None:
            tracer.dump("watchdog-trip")
        log.error(
            "app '%s': watchdog tripped — no batch progress for %.0f ms "
            "with %d unit(s) of work pending", ctx.name, age_ms, pending)
        jr = getattr(ctx, "input_journal", None)
        if jr is None or not jr.covers_from_start():
            self.stats.watchdog_recovery_failures += 1
            log.error(
                "app '%s': watchdog self-heal REFUSED — %s; restart the "
                "app manually", ctx.name,
                "no input journal (@app:faults(journal='N') required)"
                if jr is None else
                "journal no longer covers the full input history")
            return
        # a wedge that HOLDS the process lock cannot be replanned away —
        # probe with a bounded acquire instead of deadlocking the daemon
        if not ctx.process_lock.acquire(
                timeout=max(self.deadline_ms / 1000.0, 0.05)):
            self.stats.watchdog_recovery_failures += 1
            log.error(
                "app '%s': watchdog self-heal REFUSED — the process lock "
                "is held by the wedged path; replan would deadlock",
                ctx.name)
            return
        ctx.process_lock.release()
        t_heal = time.perf_counter()  # Tracer.clock is this same clock
        try:
            self.runtime.replan(
                dict(ctx.plan_pins), forced=True,
                reason=(f"watchdog self-heal: stalled batch cycle "
                        f"({age_ms:.0f} ms, {pending} pending)"))
        except Exception as e:  # noqa: BLE001 — counted + logged, daemon stays live
            self.stats.watchdog_recovery_failures += 1
            log.error(
                "app '%s': watchdog self-heal failed: %s", ctx.name, e,
                exc_info=e)
            return
        # the replan adopted a REBUILT context — record the heal span on
        # the live tracer, not the discarded pre-heal one (the clock is
        # the shared perf_counter, so spans from both line up)
        ntracer = getattr(self.runtime.app_context, "tracer", None)
        if ntracer is not None:
            # recovery time as a latency distribution (STAGE_WATCHDOG_HEAL)
            ntracer.record_span("watchdog.heal", "robustness",
                                t_heal, ntracer.clock())
        self.stats.watchdog_recoveries += 1
        self.wedged = False
        self._last_beats = ctx.progress.beats
        self._last_progress = time.monotonic()
        log.warning(
            "app '%s': watchdog self-heal complete — engines rebuilt and "
            "journal history replayed", ctx.name)

    def describe(self) -> dict:
        return {
            "deadline_ms": self.deadline_ms,
            "wedged": self.wedged,
            "trips": self.stats.watchdog_trips,
            "near_misses": self.stats.watchdog_near_misses,
            "recoveries": self.stats.watchdog_recoveries,
            "recovery_failures": self.stats.watchdog_recovery_failures,
        }
