"""Admission control: per-stream token-bucket budgets at ingest.

``@app:limits(rate='N/s', burst='M', shed='drop|oldest|block')``
installs one :class:`TokenBucket` per input stream, consulted by
``InputHandler.send``/``send_batch`` BEFORE the batch is journaled —
the input journal records only admitted events, so restore-and-replay
reproduces exactly the admitted set and never re-litigates an
admission decision (replay bypasses the controller via the journal's
``replaying`` flag).

Clocks: in ``@app:playback`` mode the bucket refills on EVENT time
(deterministic — the chaos soak's exact-shed-count differential rides
on this); otherwise on ``time.monotonic()``.

Shed policies over an arriving batch of ``n`` with ``k`` admitted:

- ``drop``   — keep the oldest ``k`` rows (head), shed the overflow
  tail: arrival order wins.
- ``oldest`` — keep the newest ``k`` rows (tail), shed the head:
  freshness wins.
- ``block``  — backpressure: the CALLING thread (for transports, the
  source's delivery thread — that is the propagation path) waits for
  refill on the ``transport/`` retry ladder's interval sequence, up to
  ``block.max``; whatever budget never arrives is shed and counted as
  a block timeout.  In playback mode event time cannot advance while
  the sender is parked, so ``block`` degrades to an immediate counted
  timeout shed.

A shed fires the ``admission.shed`` fault-injection site — chaos runs
can crash/fault the engine at the exact moment it drops load.
"""

from __future__ import annotations

import threading
import time

import numpy as np

SHED_POLICIES = ("drop", "oldest", "block")


class TokenBucket:
    """Classic token bucket with an injected 'now' (seconds, float)."""

    __slots__ = ("rate", "burst", "tokens", "last")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.last = now

    def refill(self, now: float):
        if now > self.last:
            self.tokens = min(
                self.burst, self.tokens + (now - self.last) * self.rate)
            self.last = now

    def take(self, n: int, now: float) -> int:
        """Admit up to ``n`` whole events; returns the admitted count."""
        self.refill(now)
        k = int(min(n, self.tokens))
        self.tokens -= k
        return k

    def eta_s(self, now: float) -> float:
        """Seconds until at least one whole token is available."""
        self.refill(now)
        if self.tokens >= 1.0:
            return 0.0
        return (1.0 - self.tokens) / self.rate


class AdmissionController:
    """Per-stream budgets + shed accounting for one app (one tenant).

    Survives ``replan()`` — the rebuilt app context re-adopts the same
    controller so bucket levels and shed counters carry across a
    watchdog self-heal exactly like the input journal does.
    """

    #: recent-shed window for the health endpoint's "shedding" verdict
    HEALTH_WINDOW_S = 1.0

    def __init__(self, app_context, stats):
        self.app_context = app_context
        self.stats = stats
        self.rate = float(app_context.limits_rate)
        self.burst = float(app_context.limits_burst)
        self.policy = app_context.limits_shed
        self.block_max_ms = int(app_context.limits_block_max_ms)
        self._lock = threading.Lock()
        self._buckets = {}
        self._admitted = {}
        self._shed = {}
        self._last_shed_wall = 0.0

    def _now(self) -> float:
        tg = self.app_context.timestamp_generator
        if tg.playback:
            return tg.current_time() / 1000.0
        return time.monotonic()

    def _bucket(self, stream_id: str, now: float) -> TokenBucket:
        b = self._buckets.get(stream_id)
        if b is None:
            b = self._buckets[stream_id] = TokenBucket(
                self.rate, self.burst, now)
        return b

    def admit(self, stream_id: str, batch):
        """Trim ``batch`` to the admitted rows; ``None`` = fully shed."""
        n = len(batch)
        if n == 0:
            return batch
        with self._lock:
            now = self._now()
            k = self._bucket(stream_id, now).take(n, now)
        if k < n and self.policy == "block":
            k = self._block_for_budget(stream_id, n, k)
        shed = n - k
        with self._lock:
            self._admitted[stream_id] = self._admitted.get(stream_id, 0) + k
            self.stats.events_admitted += k
            if shed:
                self._shed[stream_id] = self._shed.get(stream_id, 0) + shed
                self.stats.events_shed += shed
                if self.policy == "drop":
                    self.stats.shed_drop += shed
                elif self.policy == "oldest":
                    self.stats.shed_oldest += shed
                else:
                    self.stats.shed_block_timeout += shed
                self._last_shed_wall = time.monotonic()
        if shed:
            fi = getattr(self.app_context, "fault_injector", None)
            if fi is not None:
                fi.check("admission.shed")
        if k == n:
            return batch
        if k == 0:
            return None
        if self.policy == "oldest":
            # shed the OLDEST rows: the newest k survive
            return batch.take(np.arange(n - k, n))
        return batch.take(np.arange(k))

    def _block_for_budget(self, stream_id: str, n: int, k: int) -> int:
        """``block`` policy: park the sender on the transport retry
        ladder's interval sequence until budget arrives or ``block.max``
        expires.  Returns the final admitted count."""
        tg = self.app_context.timestamp_generator
        if tg.playback:
            return k  # event time cannot advance while we park
        from siddhi_tpu.transport.retry import _INTERVALS_MS

        deadline = time.monotonic() + self.block_max_ms / 1000.0
        rung = 0
        while k < n:
            with self._lock:
                now = time.monotonic()
                b = self._bucket(stream_id, now)
                eta = b.eta_s(now)
            remaining = deadline - time.monotonic()
            if remaining <= 0.0:
                break
            interval = _INTERVALS_MS[min(rung, len(_INTERVALS_MS) - 1)]
            rung += 1
            wait = min(max(eta, 0.001), interval / 1000.0, remaining)
            self.stats.block_waits += 1
            time.sleep(wait)
            self.stats.block_wait_ms += int(wait * 1000.0)
            with self._lock:
                now = time.monotonic()
                k += self._bucket(stream_id, now).take(n - k, now)
        return k

    # -- health -------------------------------------------------------

    def shedding_now(self) -> bool:
        return (time.monotonic() - self._last_shed_wall
                ) < self.HEALTH_WINDOW_S

    def snapshot(self) -> dict:
        """Per-stream admission detail for ``GET /siddhi-health``."""
        with self._lock:
            streams = {
                sid: {
                    "admitted": self._admitted.get(sid, 0),
                    "shed": self._shed.get(sid, 0),
                    "tokens": round(b.tokens, 3),
                }
                for sid, b in self._buckets.items()
            }
            for sid in set(self._admitted) | set(self._shed):
                streams.setdefault(sid, {
                    "admitted": self._admitted.get(sid, 0),
                    "shed": self._shed.get(sid, 0),
                    "tokens": self.burst,
                })
        return {
            "rate_per_s": self.rate,
            "burst": self.burst,
            "shed_policy": self.policy,
            "shedding": self.shedding_now(),
            "streams": streams,
        }
