"""Unified degradation ladder: trade speed for stability, in order.

Under sustained pressure (deep async junction queues, admission
shedding, watchdog near-misses) the app demotes its OWN lowerings one
rung at a time, in the documented order:

1. ``kernels``   → XLA          (Pallas kernels off)
2. ``devtables`` → host tables  (HBM columns off)
3. ``fuse``      → junction     (fused chains off)

Each demotion (and each re-promotion once pressure clears) is a
counted, forced ``runtime.replan()`` with the CURRENT pins — the same
pause/snapshot/rebuild/replay protocol the planner uses, so outputs
stay bit-identical across every rung.  The ladder only ever steps
through features the app actually enabled; apps with none of them have
a zero-rung ladder and the ladder is inert.

Hysteresis follows the ``PlanMonitor`` discipline: demote when the
pressure signal holds at/above the high-water mark for ``dwell``
consecutive watchdog ticks, re-promote only after it holds at/below
the low-water mark for ``2 * dwell`` ticks — pressure must clear by a
margin and stay clear, so the ladder never flip-flops at the
boundary.
"""

from __future__ import annotations

import logging

log = logging.getLogger("siddhi_tpu")

#: demotion order — attribute names on SiddhiAppContext, most
#: expendable first (kernels→XLA, devtable→host, fused→junction)
DEMOTE_ORDER = ("kernels", "devtables", "fuse")


def apply_degradation(app_context, level: int) -> list:
    """Disable the first ``level`` ENABLED features of ``DEMOTE_ORDER``
    on ``app_context`` (the replacement context a ``replan`` is about
    to build through).  Returns the feature names it turned off.

    Deriving the rung set from the context's own annotation flags keeps
    this deterministic across rebuilds: the same app string always
    yields the same enabled-feature list, so level N always means the
    same demotions.
    """
    demoted = []
    remaining = int(level)
    for feature in DEMOTE_ORDER:
        if remaining <= 0:
            break
        if getattr(app_context, feature, False):
            setattr(app_context, feature, False)
            demoted.append(feature)
            remaining -= 1
    return demoted


class DegradationLadder:
    """Pressure-driven demote/promote controller for one app.

    ``observe(pressure)`` is called once per watchdog tick with a
    normalized pressure signal in ``[0, 1]``; the ladder decides
    whether to move one rung and drives ``runtime.replan`` itself.
    """

    HIGH_WATER = 0.85
    LOW_WATER = 0.25

    def __init__(self, runtime, stats, high=HIGH_WATER, low=LOW_WATER,
                 dwell: int = 3):
        self.runtime = runtime
        self.stats = stats
        self.high = float(high)
        self.low = float(low)
        self.dwell = int(dwell)
        ctx = runtime.app_context
        #: rungs available to THIS app — only annotation-enabled
        #: features.  On a context rebuilt at degrade level > 0 the
        #: demoted flags read False, so the rungs the level consumed
        #: come from the context's degraded_features record instead —
        #: without it a demoted ladder would lose those rungs and never
        #: re-promote.
        demoted = getattr(ctx, "degraded_features", ())
        self.features = [f for f in DEMOTE_ORDER
                         if getattr(ctx, f, False) or f in demoted]
        self._hot_ticks = 0
        self._cool_ticks = 0

    @property
    def level(self) -> int:
        return getattr(self.runtime.app_context, "degrade_level", 0)

    def observe(self, pressure: float) -> bool:
        """One tick: returns True when a rung was taken (either way)."""
        if not self.features:
            return False
        if pressure >= self.high:
            self._hot_ticks += 1
            self._cool_ticks = 0
        elif pressure <= self.low:
            self._cool_ticks += 1
            self._hot_ticks = 0
        else:
            self._hot_ticks = 0
            self._cool_ticks = 0
        if self._hot_ticks >= self.dwell and \
                self.level < len(self.features):
            return self._step(+1, pressure)
        if self._cool_ticks >= 2 * self.dwell and self.level > 0:
            return self._step(-1, pressure)
        return False

    def _step(self, direction: int, pressure: float) -> bool:
        ctx = self.runtime.app_context
        new_level = self.level + direction
        verb = "demote" if direction > 0 else "promote"
        rung = self.features[max(new_level, self.level) - 1]
        try:
            ctx.degrade_level = new_level
            self.runtime.replan(
                dict(ctx.plan_pins), forced=True,
                reason=(f"degradation ladder {verb}: level {new_level} "
                        f"({rung}), pressure {pressure:.2f}"))
        except Exception as e:  # noqa: BLE001 — counted + logged, ladder stays live
            ctx.degrade_level = new_level - direction
            log.warning(
                "app '%s': ladder %s to level %d failed: %s",
                ctx.name, verb, new_level, e)
            sm = ctx.statistics_manager
            if sm is not None:
                sm.record_planner_fallback(
                    ctx.name, f"ladder {verb} failed: {e}")
            return False
        if direction > 0:
            self.stats.ladder_demotions += 1
        else:
            self.stats.ladder_promotions += 1
        self._hot_ticks = 0
        self._cool_ticks = 0
        log.warning(
            "app '%s': degradation ladder %sd to level %d (%s), "
            "pressure %.2f", ctx.name, verb, new_level, rung, pressure)
        return True

    def describe(self) -> dict:
        return {
            "level": self.level,
            "rungs": list(self.features),
            "demoted": self.features[:self.level],
            "high_water": self.high,
            "low_water": self.low,
            "dwell_ticks": self.dwell,
        }
