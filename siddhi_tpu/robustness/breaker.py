"""Circuit breakers for sinks and sources.

Classic closed/open/half-open state machine riding on top of
``ConnectRetryMixin``'s failure signals:

- **closed**    — traffic flows; ``breaker.threshold`` consecutive
  failures open the breaker (firing the ``breaker.open`` fault site).
- **open**      — no publish/connect attempts; sink output spools to a
  BOUNDED buffer (the batches were already counted by the output
  ledger at junction dispatch, so a replay never re-delivers them and
  the flush-on-close never double-emits).  After ``breaker.cooldown``
  the next caller becomes the half-open probe.
- **half-open** — exactly one probe in flight; success closes the
  breaker (the owner flushes its spool), failure re-opens it for
  another cooldown.

The breaker itself is transport-agnostic: ``Sink`` and
``ConnectRetryMixin`` consult ``allow()`` and report
``record_success``/``record_failure``; all transitions are counted on
:class:`~siddhi_tpu.robustness.RobustnessStats`.
"""

from __future__ import annotations

import threading
import time

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class CircuitBreaker:
    def __init__(self, name: str, threshold: int, cooldown_ms: int,
                 stats=None, fault_injector=None, clock=time.monotonic):
        self.name = name
        self.threshold = int(threshold)
        self.cooldown_ms = int(cooldown_ms)
        self.stats = stats
        self.fault_injector = fault_injector
        self.clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._open_until = 0.0
        self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def is_open(self) -> bool:
        return self.state == OPEN

    def allow(self) -> bool:
        """May the caller attempt a publish/connect right now?"""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN and self.clock() >= self._open_until:
                self._state = HALF_OPEN
                self._probing = True
                if self.stats is not None:
                    self.stats.breaker_half_opens += 1
                return True
            if self._state == HALF_OPEN and not self._probing:
                self._probing = True
                return True
            if self.stats is not None:
                self.stats.breaker_short_circuits += 1
            return False

    def record_success(self) -> bool:
        """Returns True when this success CLOSED the breaker — the
        caller should flush anything it spooled while open."""
        with self._lock:
            was = self._state
            self._state = CLOSED
            self._failures = 0
            self._probing = False
            if was != CLOSED:
                if self.stats is not None:
                    self.stats.breaker_closes += 1
                return True
            return False

    def record_failure(self):
        opened = False
        with self._lock:
            self._probing = False
            self._failures += 1
            if self._state == HALF_OPEN or \
                    self._failures >= self.threshold:
                if self._state != OPEN:
                    opened = True
                    if self.stats is not None:
                        self.stats.breaker_opens += 1
                self._state = OPEN
                self._open_until = self.clock() + self.cooldown_ms / 1000.0
                self._failures = 0
        if opened and self.fault_injector is not None:
            # choke point: chaos runs fault/crash the engine at the
            # exact open transition
            self.fault_injector.check("breaker.open")

    def describe(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "state": self._state,
                "threshold": self.threshold,
                "cooldown_ms": self.cooldown_ms,
                "consecutive_failures": self._failures,
            }
