"""Overload protection and self-healing.

The engine's defense-in-depth against sustained overload, threaded
through the existing runtime (nothing here runs unless ``@app:limits``
is present — without the annotation behavior is bit-identical):

- ``admission``  — per-stream token-bucket budgets at ``InputHandler``
  ingest with counted, policy-driven shedding (``drop``/``oldest``/
  ``block``); under ``@app:multiplex`` each tenant app carries its own
  budget, so per-app limits ARE per-tenant/seat backpressure.
- ``watchdog``   — a daemon thread that detects stalled batch cycles
  (no ingest→dispatch progress within a deadline while work is
  pending) and wedged emit drains, freezes a FlightRecorder dump, and
  self-heals by restore-and-replay over the ``runtime.replan``
  machinery — bit-identical recovery, refused loudly without a
  journal.
- ``breaker``    — closed/open/half-open circuit breakers on sinks and
  sources atop ``ConnectRetryMixin``; while open, sink output spools
  to a bounded buffer behind the output ledger so nothing double-emits
  on close.
- ``ladder``     — the unified degradation ladder: under sustained
  pressure, demote lowerings in documented order (kernels→XLA,
  devtable→host, fused→junction) via counted ``replan`` passes,
  re-promoting under hysteresis.

Every decision is counted on ``RobustnessStats`` (surfaced on the
statistics feed and ``GET /siddhi-health/<app>``) and choke-pointed
through the ``util/faults.py`` sites ``admission.shed``,
``watchdog.trip`` and ``breaker.open``.
"""

from __future__ import annotations


class RobustnessStats:
    """Counters for every overload-protection decision.

    Owned by the hot paths (admission controller, breakers, watchdog,
    ladder); the statistics layer wraps this object in a thin gauge
    (``StatisticsManager.robustness_tracker``) so metric assembly reads
    the same integers the health endpoint reports — the two can never
    disagree.
    """

    __slots__ = (
        # admission
        "events_admitted",
        "events_shed",
        "shed_drop",
        "shed_oldest",
        "shed_block_timeout",
        "block_waits",
        "block_wait_ms",
        # circuit breakers
        "breaker_opens",
        "breaker_half_opens",
        "breaker_closes",
        "breaker_short_circuits",
        "breaker_spooled_batches",
        "breaker_spool_dropped",
        "breaker_flushed_batches",
        # watchdog
        "watchdog_ticks",
        "watchdog_trips",
        "watchdog_near_misses",
        "watchdog_recoveries",
        "watchdog_recovery_failures",
        # degradation ladder
        "ladder_demotions",
        "ladder_promotions",
    )

    def __init__(self):
        for name in self.__slots__:
            setattr(self, name, 0)

    def as_dict(self) -> dict:
        return {name: getattr(self, name) for name in self.__slots__}


from siddhi_tpu.robustness.admission import (  # noqa: E402
    AdmissionController,
    TokenBucket,
)
from siddhi_tpu.robustness.breaker import CircuitBreaker  # noqa: E402
from siddhi_tpu.robustness.ladder import (  # noqa: E402
    DEMOTE_ORDER,
    DegradationLadder,
    apply_degradation,
)
from siddhi_tpu.robustness.watchdog import Watchdog  # noqa: E402

__all__ = [
    "AdmissionController",
    "CircuitBreaker",
    "DEMOTE_ORDER",
    "DegradationLadder",
    "RobustnessStats",
    "TokenBucket",
    "Watchdog",
    "apply_degradation",
]
