"""Device-resident accumulator rows for incremental-aggregation ingest.

``DeviceBucketBank`` keeps the float base fields (sum/min/max over
FLOAT/DOUBLE arguments) of RUNNING buckets of the finest duration as
device-resident float32 rows.  Ingest scatters each micro-batch into
the rows in place with one jitted ``.at[rows].add/min/max`` — nothing
crosses the device boundary per batch.  Rows materialize to the host
bucket store only at flush barriers: watermark rollover (``_advance``),
pull queries (``find``), snapshot/restore, and row-capacity pressure.

This is the ingest-side completion of the async pipeline: the emit
queue (core/emit_queue.py) keeps match OUTPUT device-resident between
barriers; the bank does the same for aggregation STATE, so tpu-mode
ingest performs no per-batch device→host flush (the former
``_device_reduce`` fetched a [U] reduction every batch).

Precision: rows are float32 — the device lane policy shared with every
other jitted path (ops/device_query.py docstring).  Integer fields
(int sums, bare counts) stay on exact host numpy scatter ufuncs at
native width, with one deliberate exception: when the aggregation is
avg- or stdDev-bearing (avg rewrites to sum + count, stdDev to
sum + sumsq + count — the sumsq row is a DOUBLE "sum"-op field and
banks like any other float sum — and the float numerators are already
banked), the shared count denominator rides the bank too as float32
add rows.  Float32 integer arithmetic is exact below 2**24;
``count_overflow_risk`` lets the runtime force a flush barrier before
any row could cross that bound, and the flush merge casts count values
back to exact ints (aggregation/runtime.py ``_flush_bank``).

Row layout: ``cap`` assignable rows + one dump row (index ``cap``) that
absorbs padded lanes and out-of-order events, which take the host
merge path instead (aggregation/runtime.py ``_merge_out_of_order``).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

_IDENTITY = {"sum": 0.0, "count": 0.0, "min": np.inf, "max": -np.inf}

# float32 holds consecutive integers exactly up to 2**24: the largest
# count any bank row may accumulate between flushes
COUNT_EXACT_MAX = 1 << 24


class DeviceBucketBank:
    """Device rows for the float base fields of running finest buckets.

    ``fields``: the eligible BaseFields (op in sum/min/max over float
    arguments — including the stdDev sumsq row — plus the count
    denominator of avg- or stdDev-bearing selects).
    One [cap+1] float32 device array per field; ``rows`` maps
    (bucket_start, group_key) -> row index.
    """

    def __init__(self, fields, cap: int = 4096):
        self.fields = list(fields)
        self.names: List[str] = [f.name for f in self.fields]
        self.ops: Tuple[str, ...] = tuple(f.op for f in self.fields)
        self.cap = int(cap)
        self.rows: Dict[Tuple[int, Tuple], int] = {}
        self._free: List[int] = list(range(self.cap))
        self._arrays = None  # per-field jnp [cap+1]; lazy (jax import)
        self._scatter = None
        # flush-barrier evidence for tests/bench: ingest batches absorbed
        # on device vs host materializations
        self.scatters = 0
        self.flushes = 0
        # events scattered since the last flush: upper-bounds the count
        # any single row may have accumulated (count rows are float32,
        # exact only below COUNT_EXACT_MAX)
        self._has_count = "count" in self.ops
        self.events_since_flush = 0

    @property
    def dump_row(self) -> int:
        return self.cap

    def count_overflow_risk(self, n: int) -> bool:
        """True when scattering ``n`` more events could push a float32
        count row past exact-integer territory — the caller must flush
        first.  Always False when no count field is banked."""
        return (self._has_count
                and self.events_since_flush + n > COUNT_EXACT_MAX)

    # -- device arrays -------------------------------------------------------

    def _ensure_arrays(self):
        if self._arrays is not None:
            return
        import jax.numpy as jnp

        self._arrays = [
            jnp.full(self.cap + 1, _IDENTITY[op], dtype=jnp.float32)
            for op in self.ops
        ]

    def _scatter_fn(self):
        if self._scatter is None:
            import jax

            ops = self.ops

            def fn(arrays, rows, vals):
                out = []
                for op, a, v in zip(ops, arrays, vals):
                    if op in ("sum", "count"):
                        out.append(a.at[rows].add(v))
                    elif op == "min":
                        out.append(a.at[rows].min(v))
                    else:
                        out.append(a.at[rows].max(v))
                return out

            self._scatter = jax.jit(fn)
        return self._scatter

    # -- row assignment ------------------------------------------------------

    def assign(self, keys) -> bool:
        """Reserve a row per key (idempotent for known keys).  Returns
        False when the free list cannot cover the new keys — the caller
        flushes (a capacity barrier) and retries, or falls back to the
        host path for the batch."""
        fresh = [k for k in keys if k not in self.rows]
        if len(fresh) > len(self._free):
            return False
        for k in fresh:
            self.rows[k] = self._free.pop()
        return True

    def scatter(self, ev_rows: np.ndarray, fvals: Dict[str, np.ndarray]):
        """Accumulate one micro-batch in place: ``ev_rows`` [n] row per
        event (``dump_row`` for events that take the host path),
        ``fvals`` the per-event float columns keyed by field name.  Rows
        are padded to a power of two so the jitted scatter sees a
        bounded shape variety; padded lanes target the dump row with the
        op identity."""
        import jax.numpy as jnp

        self._ensure_arrays()
        n = len(ev_rows)
        n_pad = max(1 << max(n - 1, 1).bit_length(), 256)
        rows_p = np.full(n_pad, self.dump_row, dtype=np.int32)
        rows_p[:n] = ev_rows
        vals = []
        for name, op in zip(self.names, self.ops):
            col = np.full(n_pad, _IDENTITY[op], dtype=np.float32)
            col[:n] = fvals[name].astype(np.float32)
            vals.append(jnp.asarray(col))
        self._arrays = self._scatter_fn()(
            self._arrays, jnp.asarray(rows_p), vals)
        self.scatters += 1
        self.events_since_flush += n

    # -- flush barriers ------------------------------------------------------

    def flush(self) -> Dict[Tuple[int, Tuple], Dict[str, float]]:
        """Materialize every assigned row to host and reset the bank:
        one coalesced device fetch, called only at barriers (rollover,
        find, snapshot, capacity pressure).  Returns
        {bucket_key: {field_name: value}}."""
        if not self.rows:
            return {}
        import jax

        host = [np.asarray(a) for a in jax.device_get(self._arrays)]
        out: Dict[Tuple[int, Tuple], Dict[str, float]] = {}
        for key, row in self.rows.items():
            out[key] = {
                name: float(host[fi][row])
                for fi, name in enumerate(self.names)
            }
        self.flushes += 1
        self.clear()
        return out

    def clear(self):
        """Drop all rows and device arrays (restore path: the host
        snapshot is the single source of truth)."""
        self.rows.clear()
        self._free = list(range(self.cap))
        self._arrays = None
        self.events_since_flush = 0
