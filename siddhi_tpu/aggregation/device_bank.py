"""Device-resident accumulator rows for incremental-aggregation ingest.

``DeviceBucketBank`` keeps the float base fields (sum/min/max over
FLOAT/DOUBLE arguments) of RUNNING buckets of the finest duration as
device-resident float32 rows.  Ingest scatters each micro-batch into
the rows in place with one jitted ``.at[rows].add/min/max`` — nothing
crosses the device boundary per batch.  Rows materialize to the host
bucket store only at flush barriers: watermark rollover (``_advance``),
pull queries (``find``), snapshot/restore, and row-capacity pressure.

This is the ingest-side completion of the async pipeline: the emit
queue (core/emit_queue.py) keeps match OUTPUT device-resident between
barriers; the bank does the same for aggregation STATE, so tpu-mode
ingest performs no per-batch device→host flush (the former
``_device_reduce`` fetched a [U] reduction every batch).

Precision: float rows are float32 — the device lane policy shared with
every other jitted path (ops/device_query.py docstring).  Two integer
shapes ride the bank exactly:

* count denominators of avg- or stdDev-bearing selects (avg rewrites
  to sum + count, stdDev to sum + sumsq + count — the sumsq row is a
  DOUBLE "sum"-op field and banks like any other float sum) ride as
  float32 add rows, exact below 2**24; ``count_overflow_risk`` lets
  the runtime force a flush barrier before any row could cross that
  bound, and the flush merge casts count values back to exact ints
  (aggregation/runtime.py ``_flush_bank``).

* LONG "sum" fields (``sum(intcol)`` widens INT→LONG) ride as a
  hi/lo int32 PAIR of rows: hi accumulates ``v >> 16`` and lo
  ``v & 0xFFFF`` (identities 0), and the flush merge recombines
  ``hi * 65536 + lo`` — exact for signed values because arithmetic
  shift/mask are two's-complement floor-div/mod, so
  ``v == (v >> 16) * 65536 + (v & 0xFFFF)`` and addition distributes
  over the split.  ``long_overflow_risk`` bounds both int32 lanes
  conservatively (lo grows ≤ 65535 per event; hi by the batch's max
  magnitude) and forces a flush barrier — or, for a single batch whose
  values are alone too hot for int32, the exact host path — before
  either lane could wrap.

* INT "min"/"max" fields ride as single int32 rows at native width
  (INT is exactly int32), with the int32 extrema as identities; the
  flush merge reads them back as exact ints.  LONG min/max values can
  exceed int32 and stay on the host path.

* bare "count" fields (no avg/stdDev rewrite) ride exactly like the
  avg/stdDev count denominators — float32 add rows guarded by
  ``count_overflow_risk`` — so a count-only select no longer forces
  the host reduction.

Remaining integer shapes (LONG min/max, last/set) keep the exact host
numpy scatter ufuncs at native width.

Row layout: ``cap`` assignable rows + one dump row (index ``cap``) that
absorbs padded lanes and out-of-order events, which take the host
merge path instead (aggregation/runtime.py ``_merge_out_of_order``).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from siddhi_tpu.query_api import AttrType

_IDENTITY = {"sum": 0.0, "count": 0.0, "min": np.inf, "max": -np.inf}

# int32 lane identities: 0 for the LONG-sum hi/lo pairs, the int32
# extrema for INT min/max rows (padded lanes leave the dump row intact)
_I32_IDENTITY = {"sum": 0, "count": 0,
                 "min": np.iinfo(np.int32).max,
                 "max": np.iinfo(np.int32).min}

# float32 holds consecutive integers exactly up to 2**24: the largest
# count any bank row may accumulate between flushes
COUNT_EXACT_MAX = 1 << 24

# LONG sums split per event into hi = v >> 16 (signed) and
# lo = v & 0xFFFF (in [0, 65535]); each lane accumulates in int32 and
# the flush merge recombines hi * 65536 + lo exactly
_LONG_LO_BITS = 16
_LONG_LO_MAX = (1 << _LONG_LO_BITS) - 1
_I32_MAX = (1 << 31) - 1


class DeviceBucketBank:
    """Device rows for the float base fields of running finest buckets.

    ``fields``: the eligible BaseFields (op in sum/min/max over float
    arguments — including the stdDev sumsq row — LONG sums, plus the
    count denominator of avg- or stdDev-bearing selects).
    One [cap+1] float32 device array per field — except LONG sums,
    which own a hi/lo int32 PAIR of [cap+1] arrays; ``rows`` maps
    (bucket_start, group_key) -> row index shared by every lane.
    """

    def __init__(self, fields, cap: int = 4096):
        self.fields = list(fields)
        self.names: List[str] = [f.name for f in self.fields]
        self.ops: Tuple[str, ...] = tuple(f.op for f in self.fields)
        self.cap = int(cap)
        self.rows: Dict[Tuple[int, Tuple], int] = {}
        self._free: List[int] = list(range(self.cap))
        self._arrays = None  # per-lane jnp [cap+1]; lazy (jax import)
        self._scatter = None
        # lane plan: each field owns one float32 row, except LONG sums
        # which own an exact hi/lo int32 pair (module docstring)
        self._lanes: List[Tuple[str, str]] = []  # (op, "f32"|"i32")
        self._field_lanes: List[Tuple[int, ...]] = []
        for f in self.fields:
            if f.op == "sum" and f.type == AttrType.LONG:
                self._field_lanes.append((len(self._lanes),
                                          len(self._lanes) + 1))
                self._lanes += [("sum", "i32"), ("sum", "i32")]
            elif f.op in ("min", "max") and f.type == AttrType.INT:
                # INT extrema fit int32 natively — exact, no pair split
                self._field_lanes.append((len(self._lanes),))
                self._lanes.append((f.op, "i32"))
            else:
                self._field_lanes.append((len(self._lanes),))
                self._lanes.append((f.op, "f32"))
        self.long_names: List[str] = [
            f.name for f, ln in zip(self.fields, self._field_lanes)
            if len(ln) == 2
        ]
        # flush-barrier evidence for tests/bench: ingest batches absorbed
        # on device vs host materializations
        self.scatters = 0
        self.flushes = 0
        # events scattered since the last flush: upper-bounds the count
        # any single row may have accumulated (count rows are float32,
        # exact only below COUNT_EXACT_MAX) and the lo int32 lane of a
        # LONG sum (each event adds at most _LONG_LO_MAX)
        self._has_count = "count" in self.ops
        self.events_since_flush = 0
        # per-LONG-field conservative bound on |hi| accumulated since
        # the last flush (long_overflow_risk)
        self._long_hi_used: Dict[str, int] = {}

    @property
    def dump_row(self) -> int:
        return self.cap

    def count_overflow_risk(self, n: int) -> bool:
        """True when scattering ``n`` more events could push a float32
        count row past exact-integer territory — the caller must flush
        first.  Always False when no count field is banked."""
        return (self._has_count
                and self.events_since_flush + n > COUNT_EXACT_MAX)

    @staticmethod
    def _hi_bound(v: np.ndarray, n: int) -> int:
        """Conservative bound on the |hi| lane growth one batch can
        cause in any single row: every event at the batch's max
        magnitude landing on one bucket.  Python ints — no int64
        overflow on extreme inputs."""
        m = max(abs(int(v.max())), abs(int(v.min())))
        return n * ((m >> _LONG_LO_BITS) + 1)

    def long_overflow_risk(self, fvals: Dict[str, np.ndarray],
                           n: int) -> bool:
        """True when scattering ``n`` more events with these values
        could wrap either int32 lane of a LONG-sum pair row — the
        caller must flush first (and if one batch is alone too hot,
        fall back to the exact host path for the batch).  Always False
        when no LONG sum is banked."""
        if not self.long_names:
            return False
        if (self.events_since_flush + n) * _LONG_LO_MAX > _I32_MAX:
            return True
        return any(
            self._long_hi_used.get(name, 0)
            + self._hi_bound(fvals[name], n) > _I32_MAX
            for name in self.long_names
        )

    # -- device arrays -------------------------------------------------------

    def _ensure_arrays(self):
        if self._arrays is not None:
            return
        import jax.numpy as jnp

        self._arrays = [
            jnp.full(self.cap + 1, _I32_IDENTITY[op], dtype=jnp.int32)
            if kind == "i32"
            else jnp.full(self.cap + 1, _IDENTITY[op], dtype=jnp.float32)
            for op, kind in self._lanes
        ]

    def _scatter_fn(self):
        if self._scatter is None:
            import jax

            lanes = tuple(self._lanes)

            def fn(arrays, rows, vals):
                out = []
                for (op, _kind), a, v in zip(lanes, arrays, vals):
                    if op in ("sum", "count"):
                        out.append(a.at[rows].add(v))
                    elif op == "min":
                        out.append(a.at[rows].min(v))
                    else:
                        out.append(a.at[rows].max(v))
                return out

            self._scatter = jax.jit(fn)
        return self._scatter

    # -- row assignment ------------------------------------------------------

    def assign(self, keys) -> bool:
        """Reserve a row per key (idempotent for known keys).  Returns
        False when the free list cannot cover the new keys — the caller
        flushes (a capacity barrier) and retries, or falls back to the
        host path for the batch."""
        fresh = [k for k in keys if k not in self.rows]
        if len(fresh) > len(self._free):
            return False
        for k in fresh:
            self.rows[k] = self._free.pop()
        return True

    def scatter(self, ev_rows: np.ndarray, fvals: Dict[str, np.ndarray]):
        """Accumulate one micro-batch in place: ``ev_rows`` [n] row per
        event (``dump_row`` for events that take the host path),
        ``fvals`` the per-event value columns keyed by field name.  Rows
        are padded to a power of two so the jitted scatter sees a
        bounded shape variety; padded lanes target the dump row with the
        op identity."""
        import jax.numpy as jnp

        self._ensure_arrays()
        n = len(ev_rows)
        n_pad = max(1 << max(n - 1, 1).bit_length(), 256)
        rows_p = np.full(n_pad, self.dump_row, dtype=np.int32)
        rows_p[:n] = ev_rows
        vals = []
        for fi, (name, op) in enumerate(zip(self.names, self.ops)):
            lanes = self._field_lanes[fi]
            if len(lanes) == 2:
                # LONG sum: exact signed hi/lo split (padded lanes add
                # the identity 0 to the dump row)
                v = np.asarray(fvals[name]).astype(np.int64)
                hi = np.zeros(n_pad, dtype=np.int32)
                lo = np.zeros(n_pad, dtype=np.int32)
                hi[:n] = (v >> _LONG_LO_BITS).astype(np.int32)
                lo[:n] = (v & _LONG_LO_MAX).astype(np.int32)
                vals += [jnp.asarray(hi), jnp.asarray(lo)]
                self._long_hi_used[name] = (
                    self._long_hi_used.get(name, 0) + self._hi_bound(v, n))
            elif self._lanes[lanes[0]][1] == "i32":
                # single int32 lane (INT min/max): native-width exact
                col = np.full(n_pad, _I32_IDENTITY[op], dtype=np.int32)
                col[:n] = fvals[name].astype(np.int32)
                vals.append(jnp.asarray(col))
            else:
                col = np.full(n_pad, _IDENTITY[op], dtype=np.float32)
                col[:n] = fvals[name].astype(np.float32)
                vals.append(jnp.asarray(col))
        self._arrays = self._scatter_fn()(
            self._arrays, jnp.asarray(rows_p), vals)
        self.scatters += 1
        self.events_since_flush += n

    # -- flush barriers ------------------------------------------------------

    def flush(self) -> Dict[Tuple[int, Tuple], Dict[str, float]]:
        """Materialize every assigned row to host and reset the bank:
        one coalesced device fetch, called only at barriers (rollover,
        find, snapshot, capacity pressure).  Returns
        {bucket_key: {field_name: value}}."""
        if not self.rows:
            return {}
        import jax

        host = [np.asarray(a) for a in jax.device_get(self._arrays)]
        out: Dict[Tuple[int, Tuple], Dict[str, float]] = {}
        for key, row in self.rows.items():
            values: Dict[str, float] = {}
            for fi, name in enumerate(self.names):
                lanes = self._field_lanes[fi]
                if len(lanes) == 2:
                    # exact int recombination of the hi/lo pair
                    values[name] = (
                        int(host[lanes[0]][row]) * (_LONG_LO_MAX + 1)
                        + int(host[lanes[1]][row]))
                elif self._lanes[lanes[0]][1] == "i32":
                    values[name] = int(host[lanes[0]][row])
                else:
                    values[name] = float(host[lanes[0]][row])
            out[key] = values
        self.flushes += 1
        self.clear()
        return out

    def clear(self):
        """Drop all rows and device arrays (restore path: the host
        snapshot is the single source of truth)."""
        self.rows.clear()
        self._free = list(range(self.cap))
        self._arrays = None
        self.events_since_flush = 0
        self._long_hi_used.clear()
