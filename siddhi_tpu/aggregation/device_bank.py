"""Device-resident accumulator rows for incremental-aggregation ingest.

``DeviceBucketBank`` keeps the float base fields (sum/min/max over
FLOAT/DOUBLE arguments) of RUNNING buckets of the finest duration as
device-resident float32 rows.  Ingest scatters each micro-batch into
the rows in place with one jitted ``.at[rows].add/min/max`` — nothing
crosses the device boundary per batch.  Rows materialize to the host
bucket store only at flush barriers: watermark rollover (``_advance``),
pull queries (``find``), snapshot/restore, and row-capacity pressure.

This is the ingest-side completion of the async pipeline: the emit
queue (core/emit_queue.py) keeps match OUTPUT device-resident between
barriers; the bank does the same for aggregation STATE, so tpu-mode
ingest performs no per-batch device→host flush (the former
``_device_reduce`` fetched a [U] reduction every batch).

Precision: float rows are float32 — the device lane policy shared with
every other jitted path (ops/device_query.py docstring).  Two integer
shapes ride the bank exactly:

* count denominators of avg- or stdDev-bearing selects (avg rewrites
  to sum + count, stdDev to sum + sumsq + count — the sumsq row is a
  DOUBLE "sum"-op field and banks like any other float sum) ride as
  float32 add rows, exact below 2**24; ``count_overflow_risk`` lets
  the runtime force a flush barrier before any row could cross that
  bound, and the flush merge casts count values back to exact ints
  (aggregation/runtime.py ``_flush_bank``).

* LONG "sum" fields (``sum(intcol)`` widens INT→LONG) ride as a
  hi/lo int32 PAIR of rows: hi accumulates ``v >> 16`` and lo
  ``v & 0xFFFF`` (identities 0), and the flush merge recombines
  ``hi * 65536 + lo`` — exact for signed values because arithmetic
  shift/mask are two's-complement floor-div/mod, so
  ``v == (v >> 16) * 65536 + (v & 0xFFFF)`` and addition distributes
  over the split.  ``long_overflow_risk`` bounds both int32 lanes
  conservatively (lo grows ≤ 65535 per event; hi by the batch's max
  magnitude) and forces a flush barrier — or, for a single batch whose
  values are alone too hot for int32, the exact host path — before
  either lane could wrap.

* INT "min"/"max" fields ride as single int32 rows at native width
  (INT is exactly int32), with the int32 extrema as identities; the
  flush merge reads them back as exact ints.

* LONG "min"/"max" fields ride as a LEXICOGRAPHIC hi/lo int32 pair:
  hi is the signed high word (``v >> 32``) and lo the bias-signed low
  word (``(v & 0xFFFFFFFF) - 2**31`` — signed int32 compare of the
  biased value equals unsigned compare of the raw low bits), so
  comparing (hi, lo) pairs lexicographically is the exact signed
  64-bit compare.  The scatter updates the pair in two passes — hi
  extrema first, then lo extrema among events whose hi TIES the new
  per-row hi — and the flush merge recombines
  ``hi * 2**32 + (lo + 2**31)`` exactly.  Extrema never accumulate,
  so no overflow guard is needed (``long_overflow_risk`` watches only
  the sum pairs).

* bare "count" fields (no avg/stdDev rewrite) ride exactly like the
  avg/stdDev count denominators — float32 add rows guarded by
  ``count_overflow_risk`` — so a count-only select no longer forces
  the host reduction.

Remaining integer shapes (last/set) keep the exact host numpy scatter
ufuncs at native width.

``use_kernel`` swaps the jitted ``.at[rows].add/min/max`` scatter for
the Pallas segmented-reduce kernel (siddhi_tpu/kernels/bank_scatter.py)
— same per-row results on int and extrema lanes bit-exactly (order-free
ops); f32 SUM lanes may associate differently than the scatter's
collision rounds, within the same documented f32 contract.

Row layout: ``cap`` assignable rows + one dump row (index ``cap``) that
absorbs padded lanes and out-of-order events, which take the host
merge path instead (aggregation/runtime.py ``_merge_out_of_order``).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from siddhi_tpu.query_api import AttrType

_IDENTITY = {"sum": 0.0, "count": 0.0, "min": np.inf, "max": -np.inf}

# int32 lane identities: 0 for the LONG-sum hi/lo pairs, the int32
# extrema for INT min/max rows (padded lanes leave the dump row intact)
_I32_IDENTITY = {"sum": 0, "count": 0,
                 "min": np.iinfo(np.int32).max,
                 "max": np.iinfo(np.int32).min}

# float32 holds consecutive integers exactly up to 2**24: the largest
# count any bank row may accumulate between flushes
COUNT_EXACT_MAX = 1 << 24

# LONG sums split per event into hi = v >> 16 (signed) and
# lo = v & 0xFFFF (in [0, 65535]); each lane accumulates in int32 and
# the flush merge recombines hi * 65536 + lo exactly
_LONG_LO_BITS = 16
_LONG_LO_MAX = (1 << _LONG_LO_BITS) - 1
_I32_MAX = (1 << 31) - 1


class DeviceBucketBank:
    """Device rows for the float base fields of running finest buckets.

    ``fields``: the eligible BaseFields (op in sum/min/max over float
    arguments — including the stdDev sumsq row — LONG sums, plus the
    count denominator of avg- or stdDev-bearing selects).
    One [cap+1] float32 device array per field — except LONG sums,
    which own a hi/lo int32 PAIR of [cap+1] arrays; ``rows`` maps
    (bucket_start, group_key) -> row index shared by every lane.
    """

    def __init__(self, fields, cap: int = 4096, use_kernel: bool = False):
        self.fields = list(fields)
        self.names: List[str] = [f.name for f in self.fields]
        self.ops: Tuple[str, ...] = tuple(f.op for f in self.fields)
        self.cap = int(cap)
        # @app:kernels('bank'): Pallas segmented-reduce scatter instead
        # of .at[rows].add/min/max (module docstring)
        self.use_kernel = bool(use_kernel)
        self.rows: Dict[Tuple[int, Tuple], int] = {}
        self._free: List[int] = list(range(self.cap))
        self._arrays = None  # per-lane jnp [cap+1]; lazy (jax import)
        self._scatter = None
        # lane plan: each field owns one float32 row, except LONG sums
        # and LONG extrema which own an exact hi/lo int32 pair
        # (module docstring)
        self._lanes: List[Tuple[str, str]] = []  # (op, "f32"|"i32")
        self._field_lanes: List[Tuple[int, ...]] = []
        for f in self.fields:
            if f.op == "sum" and f.type == AttrType.LONG:
                self._field_lanes.append((len(self._lanes),
                                          len(self._lanes) + 1))
                self._lanes += [("sum", "i32"), ("sum", "i32")]
            elif f.op in ("min", "max") and f.type == AttrType.LONG:
                # LONG extrema: lexicographic hi/lo int32 pair — exact
                # signed compare at full 64-bit width (module docstring)
                self._field_lanes.append((len(self._lanes),
                                          len(self._lanes) + 1))
                self._lanes += [(f.op, "i32"), (f.op, "i32")]
            elif f.op in ("min", "max") and f.type == AttrType.INT:
                # INT extrema fit int32 natively — exact, no pair split
                self._field_lanes.append((len(self._lanes),))
                self._lanes.append((f.op, "i32"))
            else:
                self._field_lanes.append((len(self._lanes),))
                self._lanes.append((f.op, "f32"))
        # LONG-sum pairs only: extrema pairs never accumulate, so they
        # need no overflow guard and no recombine-by-65536
        self.long_names: List[str] = [
            f.name for f, ln in zip(self.fields, self._field_lanes)
            if len(ln) == 2 and f.op == "sum"
        ]
        # flush-barrier evidence for tests/bench: ingest batches absorbed
        # on device vs host materializations
        self.scatters = 0
        self.flushes = 0
        # events scattered since the last flush: upper-bounds the count
        # any single row may have accumulated (count rows are float32,
        # exact only below COUNT_EXACT_MAX) and the lo int32 lane of a
        # LONG sum (each event adds at most _LONG_LO_MAX)
        self._has_count = "count" in self.ops
        self.events_since_flush = 0
        # per-LONG-field conservative bound on |hi| accumulated since
        # the last flush (long_overflow_risk)
        self._long_hi_used: Dict[str, int] = {}

    @property
    def dump_row(self) -> int:
        return self.cap

    def count_overflow_risk(self, n: int) -> bool:
        """True when scattering ``n`` more events could push a float32
        count row past exact-integer territory — the caller must flush
        first.  Always False when no count field is banked."""
        return (self._has_count
                and self.events_since_flush + n > COUNT_EXACT_MAX)

    @staticmethod
    def _hi_bound(v: np.ndarray, n: int) -> int:
        """Conservative bound on the |hi| lane growth one batch can
        cause in any single row: every event at the batch's max
        magnitude landing on one bucket.  Python ints — no int64
        overflow on extreme inputs."""
        m = max(abs(int(v.max())), abs(int(v.min())))
        return n * ((m >> _LONG_LO_BITS) + 1)

    def long_overflow_risk(self, fvals: Dict[str, np.ndarray],
                           n: int) -> bool:
        """True when scattering ``n`` more events with these values
        could wrap either int32 lane of a LONG-sum pair row — the
        caller must flush first (and if one batch is alone too hot,
        fall back to the exact host path for the batch).  Always False
        when no LONG sum is banked."""
        if not self.long_names:
            return False
        if (self.events_since_flush + n) * _LONG_LO_MAX > _I32_MAX:
            return True
        return any(
            self._long_hi_used.get(name, 0)
            + self._hi_bound(fvals[name], n) > _I32_MAX
            for name in self.long_names
        )

    # -- device arrays -------------------------------------------------------

    def _ensure_arrays(self):
        if self._arrays is not None:
            return
        import jax.numpy as jnp

        self._arrays = [
            jnp.full(self.cap + 1, _I32_IDENTITY[op], dtype=jnp.int32)
            if kind == "i32"
            else jnp.full(self.cap + 1, _IDENTITY[op], dtype=jnp.float32)
            for op, kind in self._lanes
        ]

    def _scatter_fn(self):
        if self._scatter is None:
            import jax
            import jax.numpy as jnp

            lanes = tuple(self._lanes)
            cap1 = self.cap + 1
            # hi-lane index -> op for LONG extrema pairs: their two
            # lanes update together lexicographically, unlike the
            # LONG-sum pairs whose lanes stay independent adds
            pair_ops: Dict[int, str] = {}
            for fi, fl in enumerate(self._field_lanes):
                if len(fl) == 2 and self.ops[fi] in ("min", "max"):
                    pair_ops[fl[0]] = self.ops[fi]

            if self.use_kernel:
                from siddhi_tpu.kernels import bank_scatter, probe

                r_pad = bank_scatter.pad_rows(cap1)
                interp = probe.interpret_mode()

                def reduce_delta(rows, v, op, ident):
                    d = bank_scatter.segmented_reduce(
                        rows, v, r_pad, op, ident, interp)
                    return d[:cap1]

            else:
                reduce_delta = None

            def upd(a, rows, v, op, kind):
                if reduce_delta is not None:
                    ident = (_I32_IDENTITY[op] if kind == "i32"
                             else _IDENTITY[op])
                    d = reduce_delta(rows, v, op, ident)
                    if op in ("sum", "count"):
                        return a + d
                    return jnp.minimum(a, d) if op == "min" else (
                        jnp.maximum(a, d))
                if op in ("sum", "count"):
                    return a.at[rows].add(v)
                return a.at[rows].min(v) if op == "min" else (
                    a.at[rows].max(v))

            def pair_update(a_hi, a_lo, rows, vh, vl, op):
                # lexicographic (hi, lo) extrema: hi decides; lo
                # competes only where its hi TIES the row's new hi
                # winner.  min/max over ints is order-free, so the
                # kernel and scatter paths are bit-identical.
                ident = _I32_IDENTITY[op]
                comb = jnp.minimum if op == "min" else jnp.maximum
                if reduce_delta is not None:
                    new_hi = comb(a_hi, reduce_delta(rows, vh, op, ident))
                elif op == "min":
                    new_hi = a_hi.at[rows].min(vh)
                else:
                    new_hi = a_hi.at[rows].max(vh)
                cand = jnp.where(vh == new_hi[rows], vl, ident)
                base = jnp.where(a_hi == new_hi, a_lo, ident)
                if reduce_delta is not None:
                    new_lo = comb(base, reduce_delta(rows, cand, op, ident))
                elif op == "min":
                    new_lo = base.at[rows].min(cand)
                else:
                    new_lo = base.at[rows].max(cand)
                return new_hi, new_lo

            def fn(arrays, rows, vals):
                out = list(arrays)
                li = 0
                while li < len(lanes):
                    if li in pair_ops:
                        out[li], out[li + 1] = pair_update(
                            arrays[li], arrays[li + 1], rows,
                            vals[li], vals[li + 1], pair_ops[li])
                        li += 2
                        continue
                    op, kind = lanes[li]
                    out[li] = upd(arrays[li], rows, vals[li], op, kind)
                    li += 1
                return out

            self._scatter = jax.jit(fn)
        return self._scatter

    # -- row assignment ------------------------------------------------------

    def assign(self, keys) -> bool:
        """Reserve a row per key (idempotent for known keys).  Returns
        False when the free list cannot cover the new keys — the caller
        flushes (a capacity barrier) and retries, or falls back to the
        host path for the batch."""
        fresh = [k for k in keys if k not in self.rows]
        if len(fresh) > len(self._free):
            return False
        for k in fresh:
            self.rows[k] = self._free.pop()
        return True

    def scatter(self, ev_rows: np.ndarray, fvals: Dict[str, np.ndarray]):
        """Accumulate one micro-batch in place: ``ev_rows`` [n] row per
        event (``dump_row`` for events that take the host path),
        ``fvals`` the per-event value columns keyed by field name.  Rows
        are padded to a power of two so the jitted scatter sees a
        bounded shape variety; padded lanes target the dump row with the
        op identity."""
        import jax.numpy as jnp

        self._ensure_arrays()
        n = len(ev_rows)
        n_pad = max(1 << max(n - 1, 1).bit_length(), 256)
        rows_p = np.full(n_pad, self.dump_row, dtype=np.int32)
        rows_p[:n] = ev_rows
        vals = []
        for fi, (name, op) in enumerate(zip(self.names, self.ops)):
            lanes = self._field_lanes[fi]
            if len(lanes) == 2 and op == "sum":
                # LONG sum: exact signed hi/lo split (padded lanes add
                # the identity 0 to the dump row)
                v = np.asarray(fvals[name]).astype(np.int64)
                hi = np.zeros(n_pad, dtype=np.int32)
                lo = np.zeros(n_pad, dtype=np.int32)
                hi[:n] = (v >> _LONG_LO_BITS).astype(np.int32)
                lo[:n] = (v & _LONG_LO_MAX).astype(np.int32)
                vals += [jnp.asarray(hi), jnp.asarray(lo)]
                self._long_hi_used[name] = (
                    self._long_hi_used.get(name, 0) + self._hi_bound(v, n))
            elif len(lanes) == 2:
                # LONG extrema: lexicographic split — signed high word,
                # bias-signed low word (signed int32 compare of the
                # biased lo == unsigned compare of the raw low bits)
                v = np.asarray(fvals[name]).astype(np.int64)
                hi = np.full(n_pad, _I32_IDENTITY[op], dtype=np.int32)
                lo = np.full(n_pad, _I32_IDENTITY[op], dtype=np.int32)
                hi[:n] = (v >> 32).astype(np.int32)
                lo[:n] = ((v & 0xFFFFFFFF) - (1 << 31)).astype(np.int32)
                vals += [jnp.asarray(hi), jnp.asarray(lo)]
            elif self._lanes[lanes[0]][1] == "i32":
                # single int32 lane (INT min/max): native-width exact
                col = np.full(n_pad, _I32_IDENTITY[op], dtype=np.int32)
                col[:n] = fvals[name].astype(np.int32)
                vals.append(jnp.asarray(col))
            else:
                col = np.full(n_pad, _IDENTITY[op], dtype=np.float32)
                col[:n] = fvals[name].astype(np.float32)
                vals.append(jnp.asarray(col))
        self._arrays = self._scatter_fn()(
            self._arrays, jnp.asarray(rows_p), vals)
        self.scatters += 1
        self.events_since_flush += n

    # -- flush barriers ------------------------------------------------------

    def flush(self) -> Dict[Tuple[int, Tuple], Dict[str, float]]:
        """Materialize every assigned row to host and reset the bank:
        one coalesced device fetch, called only at barriers (rollover,
        find, snapshot, capacity pressure).  Returns
        {bucket_key: {field_name: value}}."""
        if not self.rows:
            return {}
        import jax

        host = [np.asarray(a) for a in jax.device_get(self._arrays)]
        out: Dict[Tuple[int, Tuple], Dict[str, float]] = {}
        for key, row in self.rows.items():
            values: Dict[str, float] = {}
            for fi, name in enumerate(self.names):
                lanes = self._field_lanes[fi]
                if len(lanes) == 2 and self.ops[fi] == "sum":
                    # exact int recombination of the sum hi/lo pair
                    values[name] = (
                        int(host[lanes[0]][row]) * (_LONG_LO_MAX + 1)
                        + int(host[lanes[1]][row]))
                elif len(lanes) == 2:
                    # lexicographic extrema pair: undo the bias split
                    values[name] = (
                        int(host[lanes[0]][row]) * (1 << 32)
                        + (int(host[lanes[1]][row]) + (1 << 31)))
                elif self._lanes[lanes[0]][1] == "i32":
                    values[name] = int(host[lanes[0]][row])
                else:
                    values[name] = float(host[lanes[0]][row])
            out[key] = values
        self.flushes += 1
        self.clear()
        return out

    def clear(self):
        """Drop all rows and device arrays (restore path: the host
        snapshot is the single source of truth)."""
        self.rows.clear()
        self._free = list(range(self.cap))
        self._arrays = None
        self.events_since_flush = 0
        self._long_hi_used.clear()
