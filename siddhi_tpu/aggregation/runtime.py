"""Incremental aggregation: ``define aggregation A from S select ...
group by ... aggregate by ts every sec ... year``.

Re-design of the reference ``core/aggregation/`` (AggregationRuntime.java:81,
IncrementalExecutor.java:48, util/parser/AggregationParser.java:93): instead
of a chain of per-duration IncrementalExecutor objects each holding a
BaseIncrementalValueStore and forwarding expired buckets via linked-list
event chunks, ingestion is **vectorized bucketed reduction**: a micro-batch
is bucketed by truncated timestamp + group key with one ``np.unique`` pass,
base values (sum/count/min/max/last/set) are segment-reduced per bucket, and
completed buckets cascade up the duration ladder (sec -> min -> ... -> year)
by merging base values — the same decomposition the reference's
IncrementalAttributeAggregators perform (avg = sum+count, stdDev =
sum+sumSq+count, AvgIncrementalAttributeAggregator etc.).

Query access (joins ``on ... within ... per ...`` and on-demand queries)
stitches finished buckets with in-memory running buckets of the chosen and
all finer durations, mirroring AggregationRuntime.compileExpression's
table + in-memory union (aggregation/AggregationRuntime.java:181).

Timezone: bucket boundaries are computed in UTC (the reference's default
aggregation timezone is GMT).  Calendar durations (months/years) truncate
via numpy datetime64, matching GregorianCalendar month/year roll.
"""

from __future__ import annotations

import re as _re
from typing import Dict, List, Optional, Tuple

import numpy as np

from siddhi_tpu.core import event as ev
from siddhi_tpu.core.event import EventBatch
from siddhi_tpu.core.exceptions import SiddhiAppCreationError
from siddhi_tpu.core.query import build_env
from siddhi_tpu.planner.expr import (
    AGGREGATOR_NAMES,
    CompiledExpression,
    ExpressionCompiler,
    Scope,
)
from siddhi_tpu.query_api import (
    AggregationDefinition,
    ArithmeticOp,
    AndOp,
    Attribute,
    AttrType,
    CompareOp,
    Expression,
    FunctionCall,
    InOp,
    IsNull,
    NotOp,
    OrOp,
    StreamDefinition,
    Variable,
)

DURATIONS = ["seconds", "minutes", "hours", "days", "weeks", "months", "years"]

_FIXED_MS = {
    "seconds": 1_000,
    "minutes": 60_000,
    "hours": 3_600_000,
    "days": 86_400_000,
    "weeks": 604_800_000,
}

AGG_START_TS = "AGG_TIMESTAMP"


def bucket_starts(ts_ms: np.ndarray, duration: str) -> np.ndarray:
    """Truncate epoch-ms timestamps to their bucket start for a duration.

    Fixed durations use modulo arithmetic (weeks anchor on the epoch-Thursday
    like java.util.Calendar's WEEK truncation anchors are locale-dependent;
    we anchor ISO-style on Monday).  months/years truncate on the UTC
    calendar via datetime64.
    """
    ts_ms = np.asarray(ts_ms, dtype=np.int64)
    if duration in _FIXED_MS:
        w = _FIXED_MS[duration]
        if duration == "weeks":
            # epoch (1970-01-01) was a Thursday; shift so weeks start Monday
            shift = 3 * 86_400_000
            return (ts_ms + shift) // w * w - shift
        return ts_ms // w * w
    dt = ts_ms.astype("datetime64[ms]")
    unit = "M" if duration == "months" else "Y"
    return dt.astype(f"datetime64[{unit}]").astype("datetime64[ms]").astype(np.int64)


def bucket_end(start_ms: int, duration: str) -> int:
    """Exclusive end of the bucket that starts at start_ms."""
    if duration in _FIXED_MS:
        return int(start_ms) + _FIXED_MS[duration]
    dt = np.int64(start_ms).astype("datetime64[ms]")
    unit = "M" if duration == "months" else "Y"
    nxt = dt.astype(f"datetime64[{unit}]") + 1
    return int(nxt.astype("datetime64[ms]").astype(np.int64))


# ---------------------------------------------------------------------------
# Base-field decomposition
# ---------------------------------------------------------------------------


class BaseField:
    """One incrementally-mergeable accumulator column.

    op: 'sum' | 'count' | 'min' | 'max' | 'last' | 'set'
    The merge of two partial buckets is op-specific (add / add / min / max /
    later-wins / union) — this is what makes the sec->year cascade exact.
    """

    __slots__ = ("name", "op", "arg", "type")

    def __init__(self, name: str, op: str, arg: Optional[CompiledExpression], type_: AttrType):
        self.name = name
        self.op = op
        self.arg = arg
        self.type = type_


_NUMERIC_WIDE = {
    AttrType.INT: AttrType.LONG,
    AttrType.LONG: AttrType.LONG,
    AttrType.FLOAT: AttrType.DOUBLE,
    AttrType.DOUBLE: AttrType.DOUBLE,
}


class IncrementalRewrite:
    """Decomposes select-clause aggregator calls into base fields and
    rewrites the expression to reference them (the analog of the reference's
    IncrementalAttributeAggregator.getBaseAttributes /
    getNewMeta rewrite in AggregationParser.java:420-560)."""

    def __init__(self, compiler: ExpressionCompiler, final_scope: Scope):
        self.compiler = compiler
        self.final_scope = final_scope
        self.fields: Dict[str, BaseField] = {}
        # avg decomposes to sum + count, stdDev to sum + sumsq + count;
        # the device bank uses these to decide whether the count
        # denominator should ride the device
        self.saw_avg = False
        self.saw_stddev = False

    def _field(self, op: str, arg_expr: Optional[Expression], type_: AttrType) -> str:
        key = f"__{op}_{'' if arg_expr is None else repr(arg_expr)}"
        if key in self.fields:
            return self.fields[key].name
        name = f"_{op.upper()}{len(self.fields)}"
        arg = self.compiler.compile(arg_expr) if arg_expr is not None else None
        self.fields[key] = BaseField(name, op, arg, type_)
        self.final_scope.add_bare(name, type_)
        return name

    def _one_arg(self, call: FunctionCall) -> Expression:
        if len(call.args) != 1:
            raise SiddhiAppCreationError(
                f"aggregation: '{call.name}' takes exactly one argument"
            )
        return call.args[0]

    def rewrite(self, expr: Expression) -> Expression:
        if isinstance(expr, FunctionCall) and expr.namespace is None and expr.name in AGGREGATOR_NAMES:
            name = expr.name
            if name == "count":
                return Variable(attribute=self._field("count", None, AttrType.LONG))
            if name in ("sum", "avg", "stdDev"):
                a = self._one_arg(expr)
                at = self.compiler.compile(a).type
                if at not in _NUMERIC_WIDE:
                    raise SiddhiAppCreationError(f"aggregation: {name}() needs a numeric argument")
                sum_v = Variable(attribute=self._field("sum", a, _NUMERIC_WIDE[at]))
                if name == "sum":
                    return sum_v
                cnt_v = Variable(attribute=self._field("count", None, AttrType.LONG))
                if name == "avg":
                    self.saw_avg = True
                    return ArithmeticOp("/", sum_v, cnt_v)
                self.saw_stddev = True
                sq = ArithmeticOp("*", a, a)
                sumsq_v = Variable(attribute=self._field("sum", sq, AttrType.DOUBLE))
                mean = ArithmeticOp("/", sum_v, cnt_v)
                var = ArithmeticOp(
                    "-", ArithmeticOp("/", sumsq_v, cnt_v), ArithmeticOp("*", mean, mean)
                )
                # clamp float-rounding negatives before the root
                from siddhi_tpu.query_api import Constant

                var = FunctionCall(None, "maximum", (var, Constant(0.0, AttrType.DOUBLE)))
                return FunctionCall(None, "sqrt", (var,))
            if name in ("min", "max", "minForever", "maxForever"):
                # Forever variants degrade to per-bucket min/max: inside the
                # cascade the merge (min-of-mins) already gives the running
                # extremum over any queried range.
                a = self._one_arg(expr)
                at = self.compiler.compile(a).type
                if at not in _NUMERIC_WIDE:
                    raise SiddhiAppCreationError(f"aggregation: {name}() needs a numeric argument")
                op = "min" if name in ("min", "minForever") else "max"
                return Variable(attribute=self._field(op, a, at))
            if name == "distinctCount":
                a = self._one_arg(expr)
                return Variable(attribute=self._field("set", a, AttrType.LONG))
            raise SiddhiAppCreationError(
                f"aggregation: aggregator '{name}' is not incrementally mergeable"
            )
        if isinstance(expr, ArithmeticOp):
            return ArithmeticOp(expr.op, self.rewrite(expr.left), self.rewrite(expr.right))
        if isinstance(expr, CompareOp):
            return CompareOp(expr.op, self.rewrite(expr.left), self.rewrite(expr.right))
        if isinstance(expr, AndOp):
            return AndOp(self.rewrite(expr.left), self.rewrite(expr.right))
        if isinstance(expr, OrOp):
            return OrOp(self.rewrite(expr.left), self.rewrite(expr.right))
        if isinstance(expr, NotOp):
            return NotOp(self.rewrite(expr.expr))
        if isinstance(expr, IsNull):
            return IsNull(self.rewrite(expr.expr))
        if isinstance(expr, InOp):
            return InOp(self.rewrite(expr.expr), expr.source_id)
        if isinstance(expr, FunctionCall):
            return FunctionCall(
                expr.namespace, expr.name, tuple(self.rewrite(a) for a in expr.args), expr.star
            )
        return expr


# ---------------------------------------------------------------------------
# Bucket store
# ---------------------------------------------------------------------------


class _Bucket:
    """Per (duration, bucket_start, group_key) base accumulator row."""

    __slots__ = ("values", "last_ts")

    def __init__(self):
        self.values: Dict[str, object] = {}
        self.last_ts = -1


def _merge_value(op: str, old, new, old_ts: int, new_ts: int):
    if old is None:
        return new
    if new is None:
        return old
    if op in ("sum", "count"):
        return old + new
    if op == "min":
        return min(old, new)
    if op == "max":
        return max(old, new)
    if op == "set":
        return old | new
    # 'last': later timestamp wins
    return new if new_ts >= old_ts else old


class _DurationStore:
    """All buckets of one duration: running (in-memory, may still receive
    events) and finished (flushed by the cascade — the analog of the
    reference's per-duration backing table)."""

    def __init__(self, duration: str):
        self.duration = duration
        self.running: Dict[Tuple[int, Tuple], _Bucket] = {}
        self.finished: Dict[Tuple[int, Tuple], _Bucket] = {}

    def merge_into(self, target: Dict, key: Tuple[int, Tuple], values: Dict, last_ts: int,
                   ops: Dict[str, str]):
        b = target.get(key)
        if b is None:
            b = target[key] = _Bucket()
        for fname, v in values.items():
            b.values[fname] = _merge_value(ops[fname], b.values.get(fname), v, b.last_ts, last_ts)
        if last_ts > b.last_ts:
            b.last_ts = last_ts


# ---------------------------------------------------------------------------
# Runtime
# ---------------------------------------------------------------------------


class AggregationRuntime:
    """Executes one ``define aggregation``.

    Subscribes to the input stream junction; per batch performs the bucketed
    reduction into the finest duration's running store; a watermark (max
    event time seen) drives the flush cascade.  ``find`` serves joins and
    on-demand queries.
    """

    def __init__(self, definition: AggregationDefinition, app_planner):
        self.definition = definition
        self.name = definition.id
        self.app_context = app_planner.app_context
        s = definition.input_stream
        in_def = app_planner.resolve_stream_definition(s)
        self.input_stream_id = s.stream_id
        self._init_purge(definition)
        declared = [d for d in DURATIONS if d in definition.durations]
        if not declared:
            raise SiddhiAppCreationError(f"aggregation '{self.name}': no durations")
        # Fill the min..max range along the NESTING chain (sec..day, month,
        # year).  Weeks do not nest inside months, so 'weeks' is a side
        # branch fed from days (or finer) — never part of the month/year
        # cascade.  (The reference keeps a linear executor chain and shares
        # this constraint via its duration validation.)
        chain = [d for d in DURATIONS if d != "weeks"]
        chain_declared = [d for d in declared if d != "weeks"]
        if chain_declared:
            lo = chain.index(chain_declared[0])
            hi = chain.index(chain_declared[-1])
            self.chain = chain[lo : hi + 1]
        else:
            self.chain = []
        self.has_weeks = "weeks" in declared
        self.durations = list(self.chain)
        if self.has_weeks:
            self.durations = sorted(
                self.durations + ["weeks"], key=DURATIONS.index
            )

        ref = s.alias or s.stream_id
        scope = Scope()
        for a in in_def.attributes:
            scope.add(ref, a.name, a.name, a.type)
        self.compiler = ExpressionCompiler(scope)

        # tpu mode: float base fields live device-resident (bucket bank
        # scatter-adds, SURVEY §7 step 5) or reduce on the device; the
        # host store stays the source of truth for snapshots, rollups
        # and on-demand queries — in tpu mode it is completed lazily, at
        # flush barriers (rollover/find/snapshot), not per batch
        self._device_segments = (
            app_planner.app_context.execution_mode == "tpu")
        self._device_fn = None
        # @app:execution('tpu', agg.device.min.batch='N'): minimum batch
        # size before the transient [U]-segment reduce rides the device
        # (the bank path is batch-size independent)
        self._agg_min_batch = getattr(
            app_planner.app_context, "tpu_agg_min_batch", 512)

        # input filters: `from S[cond] select ...` aggregates only
        # passing rows (reference: AggregationParser wires the stream's
        # filter chain ahead of the IncrementalExecutor;
        # AggregationFilterTestCase.java:43) — the query chain's own
        # FilterProcessor, so masking/type-check behavior stays shared
        from siddhi_tpu.core.query import FilterProcessor

        self.input_filters = []
        for h in getattr(s, "handlers", []):
            if type(h).__name__ != "Filter":
                raise SiddhiAppCreationError(
                    f"aggregation '{self.name}': only filters are "
                    "supported on the input stream")
            self.input_filters.append(
                FilterProcessor(self.compiler.compile(h.expression)))

        # aggregate by <attr> (defaults to event arrival timestamp)
        self.ts_compiled: Optional[CompiledExpression] = None
        if definition.aggregate_by is not None:
            c = self.compiler.compile(Variable(attribute=definition.aggregate_by))
            if c.type not in (AttrType.LONG, AttrType.INT):
                raise SiddhiAppCreationError(
                    f"aggregation '{self.name}': 'aggregate by {definition.aggregate_by}' "
                    "must be a long epoch-ms attribute"
                )
            self.ts_compiled = c

        sel = definition.selector
        self.group_by: List[CompiledExpression] = [
            self.compiler.compile(g) for g in (sel.group_by or [])
        ]
        self.group_names: List[str] = [
            g.attribute if isinstance(g, Variable) else f"_g{i}"
            for i, g in enumerate(sel.group_by or [])
        ]

        # decompose select items
        final_scope = Scope()
        final_scope.add_bare(AGG_START_TS, AttrType.LONG)
        for nm, g in zip(self.group_names, sel.group_by or []):
            gc = self.compiler.compile(g)
            final_scope.add_bare(nm, gc.type)
        rw = IncrementalRewrite(self.compiler, final_scope)
        self.out_items: List[Tuple[str, CompiledExpression]] = []
        out_attrs: List[Attribute] = []
        if not sel.selection:
            raise SiddhiAppCreationError(
                f"aggregation '{self.name}': select clause is required"
            )
        final_compiler = ExpressionCompiler(final_scope)
        group_key_exprs = {repr(g) for g in (sel.group_by or [])}
        for item in sel.selection:
            expr = item.expression
            nm = item.name
            if isinstance(expr, Variable) and repr(expr) in group_key_exprs:
                # group-by key: passes through the bucket key
                idx = [repr(g) for g in sel.group_by].index(repr(expr))
                gname = self.group_names[idx]
                compiled = final_compiler.compile(Variable(attribute=gname))
            else:
                rewritten = rw.rewrite(expr)
                if repr(rewritten) == repr(expr):
                    # no aggregator inside: per-bucket last value
                    src = self.compiler.compile(expr)
                    fname = rw._field("last", expr, src.type)
                    compiled = final_compiler.compile(Variable(attribute=fname))
                else:
                    compiled = final_compiler.compile(rewritten)
            self.out_items.append((nm, compiled))
            out_attrs.append(Attribute(nm, compiled.type))
        self.base_fields: List[BaseField] = list(rw.fields.values())
        self.field_ops: Dict[str, str] = {f.name: f.op for f in self.base_fields}

        # device-resident ingest (tpu mode): float sum/min/max base
        # fields of running finest buckets accumulate in device rows,
        # LONG sums (``sum(intcol)`` widens INT→LONG) in exact hi/lo
        # int32 pair rows, LONG extrema in exact lexicographic hi/lo
        # pairs, and all materialize to the host store only at flush
        # barriers (aggregation/device_bank.py); remaining last/set
        # fields keep the exact host path at native width
        self._bank = None
        if self._device_segments:
            bank_fields = [
                f for f in self.base_fields
                if (f.op in ("sum", "min", "max")
                    and f.type in (AttrType.FLOAT, AttrType.DOUBLE))
                or (f.op == "sum" and f.type == AttrType.LONG)
                or (f.op in ("min", "max")
                    and f.type in (AttrType.INT, AttrType.LONG))
            ]
            # avg(x) over a numeric argument rewrites to _SUM/_COUNT
            # and stdDev(x) to _SUM/_SUMSQ/_COUNT (the sumsq row is a
            # DOUBLE "sum"-op field and an int avg's _SUM is a LONG
            # sum, so both numerators are already banked above);
            # with the numerators banked, banking the shared count
            # denominator too lets avg- and stdDev-bearing ingest skip
            # the host reduction entirely.  Count rows are float32 on
            # the device — exact below 2**24, enforced by the overflow
            # barrier in _bank_ingest — and cast back to exact ints at
            # flush merge.  Bare counts (no avg/stdDev) ride the same
            # float32 add rows under the same barrier, so count-only
            # selects skip the host reduction too.
            bank_fields += [
                f for f in self.base_fields if f.op == "count"
            ]
            if bank_fields:
                from siddhi_tpu.aggregation.device_bank import (
                    DeviceBucketBank,
                )

                # @app:kernels('bank'): Pallas segmented-reduce scatter
                # when the capability probe + smoke lowering pass;
                # otherwise a counted fallback to the XLA scatter
                ctx = app_planner.app_context
                use_kernel = False
                if getattr(ctx, "kernels", False) and (
                        "bank" in getattr(ctx, "kernel_kinds", ())):
                    from siddhi_tpu.planner.kernels import (
                        try_enable_bank_kernel,
                    )

                    use_kernel = try_enable_bank_kernel(ctx, self.name)
                self._bank = DeviceBucketBank(
                    bank_fields, use_kernel=use_kernel)

        self.output_definition = StreamDefinition(
            id=self.name, attributes=[Attribute(AGG_START_TS, AttrType.LONG)] + out_attrs
        )
        # flush-cascade topology: each duration feeds the next chain duration;
        # weeks hang off the coarsest sub-week chain duration
        self._feeds: Dict[str, List[str]] = {d: [] for d in self.durations}
        for i, d in enumerate(self.chain[:-1]):
            self._feeds[d].append(self.chain[i + 1])
        if self.has_weeks and self.chain:
            sub_week = [d for d in self.chain if DURATIONS.index(d) < DURATIONS.index("weeks")]
            if not sub_week:
                raise SiddhiAppCreationError(
                    f"aggregation '{self.name}': 'week' needs a day-or-finer "
                    "duration to aggregate from when months/years are present"
                )
            self._feeds[sub_week[-1]].append("weeks")

        self.stores: Dict[str, _DurationStore] = {d: _DurationStore(d) for d in self.durations}
        self.watermark: int = -(1 << 62)

    # -- purging (reference: aggregation/IncrementalDataPurger.java) --------

    _DEFAULT_RETENTION = {
        "seconds": 120 * 1000,              # 120 sec
        "minutes": 24 * 3_600_000,          # 24 hours
        "hours": 30 * 86_400_000,           # 30 days
        "days": 365 * 86_400_000,           # 1 year
        "weeks": -1,                        # retain all (reference purger
        "months": -1,                       # has no WEEKS/MONTHS defaults)
        "years": -1,
    }
    _MIN_RETENTION = {
        "seconds": 120 * 1000,
        "minutes": 120 * 60_000,
        "hours": 25 * 3_600_000,
        "days": 32 * 86_400_000,
        "weeks": 5 * 7 * 86_400_000,
        "months": 13 * 30 * 86_400_000,
        "years": -1,
    }
    _KEY_TO_DURATION = {
        "sec": "seconds", "seconds": "seconds",
        "min": "minutes", "minutes": "minutes",
        "hour": "hours", "hours": "hours",
        "day": "days", "days": "days",
        "week": "weeks", "weeks": "weeks",
        "month": "months", "months": "months",
        "year": "years", "years": "years",
    }

    def _init_purge(self, definition):
        """@purge(enable, interval, @retentionPeriod(sec=..., min=..., ...))
        (reference: AggregationParser purge handling +
        IncrementalDataPurger.init:95-130 defaults/minimums)."""
        from siddhi_tpu.compiler.parser import parse_time_string
        from siddhi_tpu.query_api.annotation import find_annotation

        self._purge_enabled = True
        self._purge_interval_ms = 15 * 60_000
        self._retention = dict(self._DEFAULT_RETENTION)
        self._last_purge = 0
        ann = find_annotation(definition.annotations, "purge")
        if ann is None:
            return
        enable = ann.element("enable")
        if enable is not None:
            if enable.lower() not in ("true", "false"):
                raise SiddhiAppCreationError(
                    f"aggregation '{definition.id}': invalid @purge enable "
                    f"'{enable}' (true|false)")
            self._purge_enabled = enable.lower() == "true"
        interval = ann.element("interval")
        if interval is not None:
            self._purge_interval_ms = parse_time_string(interval)
        rp = ann.nested("retentionPeriod")
        if rp is not None:
            for key, value in rp.elements:
                if key is None:
                    continue
                d = self._KEY_TO_DURATION.get(key.lower())
                if d is None:
                    raise SiddhiAppCreationError(
                        f"aggregation '{definition.id}': unknown retention "
                        f"duration '{key}'")
                if value.strip().lower() == "all":
                    self._retention[d] = -1
                    continue
                ms = parse_time_string(value)
                minimum = self._MIN_RETENTION[d]
                if minimum > 0 and ms < minimum:
                    raise SiddhiAppCreationError(
                        f"aggregation '{definition.id}': retention for {d} "
                        f"must be >= {minimum} ms (got {ms})")
                self._retention[d] = ms

    def _purge(self, now: int):
        if not self._purge_enabled or now - self._last_purge < self._purge_interval_ms:
            return
        self._last_purge = now
        for d in self.durations:
            keep_ms = self._retention.get(d, -1)
            if keep_ms < 0:
                continue
            st = self.stores[d]
            cutoff = now - keep_ms
            for k in [k for k in st.finished if bucket_end(k[0], d) < cutoff]:
                del st.finished[k]

    # -- ingest -------------------------------------------------------------

    def on_event(self, batch: EventBatch, now: int):
        batch = batch.only(ev.CURRENT)
        for fp in self.input_filters:
            if len(batch) == 0:
                break
            batch = fp.process(batch, now)
        if len(batch) == 0:
            self._advance(now)
            return
        env = build_env(batch)
        ts = (
            np.asarray(self.ts_compiled(env), dtype=np.int64)
            if self.ts_compiled is not None
            else batch.timestamps
        )
        n = len(batch)
        finest = self.durations[0]
        buckets = bucket_starts(ts, finest)

        # group keys (gcols columns; tuples built only per unique
        # segment below — not per row)
        gcols = ([np.broadcast_to(np.asarray(g(env)), (n,))
                  for g in self.group_by] if self.group_by else [])

        def key_at(i: int) -> Tuple:
            return tuple(c[i] for c in gcols)

        # base-field per-event values
        fvals: Dict[str, np.ndarray] = {}
        for f in self.base_fields:
            if f.op == "count":
                fvals[f.name] = np.ones(n, dtype=np.int64)
            else:
                fvals[f.name] = np.broadcast_to(np.asarray(f.arg(env)), (n,))

        # segment by (bucket, key): one combined-code np.unique replaces
        # the former O(n * unique-segments) per-segment masking loop
        # (SURVEY §7 step 5 — bucketed scatter-adds; float fields ride a
        # jitted device scatter under @app:execution('tpu')).  Falls
        # back to the exact per-row probe on unorderable key values
        # (nulls in object columns) or radix overflow.
        try:
            key_ids = np.zeros(n, dtype=np.int64)
            radix = 1
            for c in gcols:
                u, inv = np.unique(c, return_inverse=True)
                radix *= len(u) + 1
                if radix > 2**31:
                    raise OverflowError("group-key radix")
                key_ids = key_ids * (len(u) + 1) + inv
            _bu, binv = np.unique(buckets, return_inverse=True)
            if (len(_bu) + 1) * radix > 2**62:
                raise OverflowError("bucket x key radix")
            codes = (binv.astype(np.int64) * (int(key_ids.max()) + 1)
                     + key_ids)
            _uc, uidx, ids = np.unique(codes, return_index=True,
                                       return_inverse=True)
        except (TypeError, OverflowError):
            combo: Dict = {}
            uidx_l: List[int] = []
            ids = np.empty(n, dtype=np.int64)
            for i in range(n):
                k = (int(buckets[i]), key_at(i))
                j = combo.get(k)
                if j is None:
                    j = combo[k] = len(uidx_l)
                    uidx_l.append(i)
                ids[i] = j
            uidx = np.asarray(uidx_l, dtype=np.int64)
        U = len(uidx)
        store = self.stores[finest]
        wm_bucket = int(bucket_starts(
            np.asarray([self.watermark]), finest)[0])
        seg_keys = [
            (int(buckets[int(uidx[u])]), key_at(int(uidx[u])))
            for u in range(U)
        ]
        running = np.asarray([k[0] >= wm_bucket for k in seg_keys],
                             dtype=bool)
        # device-resident ingest: float sum/min/max fields of running
        # buckets scatter into the bank in place and skip the host
        # reduction entirely — no device→host flush this batch
        bank_names = self._bank_ingest(seg_keys, running, ids, fvals)
        host_fields = [f for f in self.base_fields
                       if f.name not in bank_names]
        seg_vals, seg_last = self._reduce_segments(
            ids, U, fvals, ts, n, fields=host_fields)
        # out-of-order events take the host merge path even for bank
        # fields (the bank's dump row absorbed their device lanes)
        ooo_vals: Dict[str, List] = {}
        if bank_names and not running.all():
            ooo_vals = self._reduce_ooo(ids, U, fvals, bank_names, running)
        for u in range(U):
            k = seg_keys[u]
            values = {f.name: seg_vals[f.name][u] for f in host_fields}
            last_ts = int(seg_last[u])
            # out-of-order below the watermark: merge straight into the
            # finished store (the reference's OutOfOrderEventsDataAggregator)
            if not running[u]:
                for name in bank_names:
                    values[name] = ooo_vals[name][u]
                self._merge_out_of_order(k, values, last_ts)
            else:
                store.merge_into(store.running, k, values, last_ts,
                                 self.field_ops)
        self.watermark = max(self.watermark, int(ts.max()))
        self._advance(now)
        self._purge(now)

    def _bank_ingest(self, seg_keys, running, ids, fvals):
        """Scatter this batch's bank-eligible field values into the
        device bucket bank.  Returns the set of field names the bank
        absorbed (empty = host path for everything: no bank, or more
        unique running buckets than the bank holds even after a
        capacity flush)."""
        bank = self._bank
        if bank is None:
            return set()
        # float32 count rows stay exact only below 2**24 increments:
        # force a flush before this batch could push any row past that
        if bank.count_overflow_risk(len(ids)):
            self._flush_bank()
        # LONG-sum hi/lo int32 pair rows must never wrap: flush when
        # the conservative accumulated bound nears int32 range; a batch
        # whose values are alone too hot for int32 takes the exact host
        # path for every bank field (host merges and later bank flushes
        # combine associatively, so mixing the paths stays exact)
        if bank.long_overflow_risk(fvals, len(ids)):
            self._flush_bank()
            if bank.long_overflow_risk(fvals, len(ids)):
                return set()
        run_keys = [k for k, r in zip(seg_keys, running) if r]
        if not bank.assign(run_keys):
            # capacity barrier: materialize every row and retry once
            self._flush_bank()
            if not bank.assign(run_keys):
                return set()
        seg_rows = np.full(len(seg_keys), bank.dump_row, dtype=np.int32)
        for u, (k, r) in enumerate(zip(seg_keys, running)):
            if r:
                seg_rows[u] = bank.rows[k]
        bank.scatter(seg_rows[ids],
                     {name: fvals[name] for name in bank.names})
        return set(bank.names)

    def _reduce_ooo(self, ids, U, fvals, names, running):
        """Host reduction of bank fields over the OUT-OF-ORDER events
        only (the rare late path; in-order events rode the bank)."""
        mask = ~running[ids]
        out: Dict[str, List] = {}
        for name in names:
            op = self.field_ops[name]
            v = fvals[name]
            if op in ("sum", "count"):
                # count values are per-event ones (int64): the same
                # scatter-add yields the exact late-event count
                acc = np.zeros(U, dtype=v.dtype)
                np.add.at(acc, ids[mask], v[mask])
            elif op == "min":
                # integer dtypes cannot hold inf — use the exact dtype
                # extrema as identities (mirrors _reduce_segments)
                ident = (np.iinfo(v.dtype).max
                         if np.issubdtype(v.dtype, np.integer) else np.inf)
                acc = np.full(U, ident, dtype=v.dtype)
                np.minimum.at(acc, ids[mask], v[mask])
            else:
                ident = (np.iinfo(v.dtype).min
                         if np.issubdtype(v.dtype, np.integer)
                         else -np.inf)
                acc = np.full(U, ident, dtype=v.dtype)
                np.maximum.at(acc, ids[mask], v[mask])
            out[name] = [x.item() for x in acc]
        return out

    def _flush_bank(self):
        """Flush barrier: materialize the device bucket rows into the
        host running store (one coalesced fetch) — rollover, find,
        snapshot, and capacity pressure call this; never the per-batch
        ingest path."""
        if self._bank is None:
            return
        st = self.stores[self.durations[0]]
        for key, values in self._bank.flush().items():
            # count rows rode the bank as float32 (exact below 2**24 by
            # the ingest overflow barrier); the host store keeps exact
            # int semantics, so cast the denominator back here
            for name in values:
                if self.field_ops[name] == "count":
                    values[name] = int(values[name])
            # last_ts sentinel: bank ops (sum/count/min/max) are
            # ts-insensitive; the host bucket's last_ts was set at
            # ingest time
            st.merge_into(st.running, key, values, -(1 << 62),
                          self.field_ops)

    def _reduce_segments(self, ids: np.ndarray, U: int,
                         fvals: Dict[str, np.ndarray], ts: np.ndarray,
                         n: int, fields=None):
        """Per-segment field reductions: {name: [U] python-typed
        values}, seg_last_ts [U].  Numeric sum/count/min/max fields
        reduce with np scatter ufuncs (or one jitted device scatter in
        tpu mode); 'last'/'set'/object fields walk sorted segment
        slices.  ``fields`` restricts the reduction (the device bucket
        bank absorbs its fields upstream); default all base fields."""
        if fields is None:
            fields = self.base_fields
        seg_vals: Dict[str, List] = {}
        # min-init (not zero): pre-epoch/negative timestamps must win
        seg_last = np.full(U, np.iinfo(np.int64).min, dtype=np.int64)
        np.maximum.at(seg_last, ids, ts)

        scatter_fields = []
        slice_fields = []
        for f in fields:
            v = fvals[f.name]
            if (f.op in ("sum", "count", "min", "max")
                    and v.dtype.kind in "iuf"):
                scatter_fields.append(f)
            else:
                slice_fields.append(f)

        # float fields may ride the jitted device scatter in tpu mode
        # (float32 lanes = the device precision policy); int fields stay
        # on exact numpy scatter ufuncs at native width
        dev = [f for f in scatter_fields
               if self._device_segments and n >= self._agg_min_batch
               and fvals[f.name].dtype.kind == "f"]
        for f, col in zip(dev, self._device_reduce(ids, U, fvals, dev)):
            seg_vals[f.name] = [x.item() for x in col]
        for f in scatter_fields:
            if f.name in seg_vals:
                continue
            v = fvals[f.name]
            if f.op in ("sum", "count"):
                # integer sums widen to int64 (np.sum's promotion rule;
                # an int32 accumulator would silently wrap)
                acc_dt = np.int64 if v.dtype.kind in "iu" else v.dtype
                acc = np.zeros(U, dtype=acc_dt)
                np.add.at(acc, ids, v)
            elif f.op == "min":
                acc = np.full(U, np.inf if v.dtype.kind == "f"
                              else np.iinfo(v.dtype).max, dtype=v.dtype)
                np.minimum.at(acc, ids, v)
            else:
                acc = np.full(U, -np.inf if v.dtype.kind == "f"
                              else np.iinfo(v.dtype).min, dtype=v.dtype)
                np.maximum.at(acc, ids, v)
            seg_vals[f.name] = [x.item() for x in acc]

        if slice_fields:
            # sorted segment slices; within a segment the stable sort
            # keeps arrival order, so 'last' tie-breaks like the
            # cross-batch merge (later arrival wins at equal ts)
            order = np.argsort(ids, kind="stable")
            bounds = np.searchsorted(ids[order], np.arange(U + 1))
            ts_sorted = ts[order]
            for f in slice_fields:
                v = fvals[f.name][order]
                vals: List = []
                for u in range(U):
                    seg = v[bounds[u]:bounds[u + 1]]
                    if f.op == "set":
                        vals.append(set(seg.tolist()))
                    elif f.op in ("sum", "count"):
                        vals.append(sum(seg))
                    elif f.op == "min":
                        vals.append(min(seg))
                    elif f.op == "max":
                        vals.append(max(seg))
                    else:  # last: latest ts, later arrival wins ties
                        sts = ts_sorted[bounds[u]:bounds[u + 1]]
                        li = len(sts) - 1 - int(np.argmax(sts[::-1]))
                        x = seg[li]
                        vals.append(x.item() if hasattr(x, "item")
                                    and not isinstance(x, (str, bytes))
                                    else x)
                seg_vals[f.name] = vals
        return seg_vals, seg_last

    def _device_reduce(self, ids: np.ndarray, U: int,
                       fvals: Dict[str, np.ndarray], fields) -> List:
        """One jitted scatter over the float fields: [n] values +
        segment ids -> [U] per-op reductions on float32 device lanes
        (int fields keep native width on the numpy path — see
        _reduce_segments gating)."""
        if not fields:
            return []
        import jax
        import jax.numpy as jnp

        if self._device_fn is None:
            def reduce_fn(ids_d, vals, ops, U_static):
                outs = []
                for op, v in zip(ops, vals):
                    if op in ("sum", "count"):
                        outs.append(jnp.zeros(U_static, v.dtype)
                                    .at[ids_d].add(v))
                    elif op == "min":
                        outs.append(jnp.full(U_static, jnp.inf, v.dtype)
                                    .at[ids_d].min(v))
                    else:
                        outs.append(jnp.full(U_static, -jnp.inf, v.dtype)
                                    .at[ids_d].max(v))
                return outs

            self._device_fn = jax.jit(reduce_fn, static_argnums=(2, 3))
        # pow-2 padding on BOTH axes bounds jit shape variety (streaming
        # n and U vary per batch); padded rows scatter identities into
        # the padded dump segment
        n = len(ids)
        n_pad = max(1 << (n - 1).bit_length(), 512)
        U_pad = max(1 << U.bit_length(), 16)  # U real segments + dump
        ids_p = np.full(n_pad, U_pad - 1, dtype=np.int32)
        ids_p[:n] = ids
        vals = []
        for f in fields:
            col = np.zeros(n_pad, dtype=np.float32)
            col[:n] = fvals[f.name].astype(np.float32)
            if f.op == "min":
                col[n:] = np.inf
            elif f.op == "max":
                col[n:] = -np.inf
            vals.append(jnp.asarray(col))
        ops = tuple(f.op for f in fields)
        out = self._device_fn(jnp.asarray(ids_p), tuple(vals), ops, U_pad)
        return [np.asarray(o)[:U] for o in out]

    def _merge_out_of_order(self, key: Tuple[int, Tuple], values: Dict, last_ts: int):
        """Late event: fold into the finished bucket of every duration.
        Buckets already past a duration's retention cutoff are dropped,
        not resurrected as partial data."""
        for d in self.durations:
            keep_ms = self._retention.get(d, -1)
            if (self._purge_enabled and keep_ms >= 0
                    and bucket_end(int(bucket_starts(np.asarray([key[0]]), d)[0]), d)
                    < self.watermark - keep_ms):
                continue
            st = self.stores[d]
            dk = (int(bucket_starts(np.asarray([key[0]]), d)[0]), key[1])
            target = st.finished if dk in st.finished or d == self.durations[0] else st.running
            st.merge_into(target, dk, values, last_ts, self.field_ops)

    def _advance(self, now: int):
        """Flush every running bucket that the watermark has passed, cascading
        base values into the parent duration."""
        wm = self.watermark
        if self._bank is not None and self._bank.rows:
            # rollover barrier: a finest bucket is about to complete, so
            # its device rows must reach the host store first; one
            # coalesced fetch covers every pending bank row
            finest = self.durations[0]
            if any(bucket_end(k[0], finest) <= wm for k in self._bank.rows):
                self._flush_bank()
        for d in self.durations:
            st = self.stores[d]
            done = [k for k in st.running if bucket_end(k[0], d) <= wm]
            for k in done:
                b = st.running.pop(k)
                st.merge_into(st.finished, k, b.values, b.last_ts, self.field_ops)
                for parent in self._feeds[d]:
                    pst = self.stores[parent]
                    pk = (int(bucket_starts(np.asarray([k[0]]), parent)[0]), k[1])
                    pst.merge_into(pst.running, pk, b.values, b.last_ts, self.field_ops)

    # -- query --------------------------------------------------------------

    def find(
        self,
        per: str,
        within: Optional[Tuple[int, int]] = None,
    ) -> EventBatch:
        """All buckets of duration ``per`` intersecting [start, end), finished
        and running stitched, finer running buckets rolled up — returned as a
        batch over the aggregation's output schema."""
        per = _canon_duration(per)
        if per not in self.durations:
            raise SiddhiAppCreationError(
                f"aggregation '{self.name}': per '{per}' is not one of {self.durations}"
            )
        # pull-query barrier: running buckets' device rows must be
        # host-visible before the stitch below reads them
        self._flush_bank()
        # union of finished + running at `per`, plus roll-up of finer running
        merged: Dict[Tuple[int, Tuple], _Bucket] = {}
        ops = self.field_ops

        def fold(key, b: _Bucket):
            t = merged.get(key)
            if t is None:
                t = merged[key] = _Bucket()
            for fname, v in b.values.items():
                t.values[fname] = _merge_value(ops[fname], t.values.get(fname), v, t.last_ts, b.last_ts)
            if b.last_ts > t.last_ts:
                t.last_ts = b.last_ts

        st = self.stores[per]
        for key, b in st.finished.items():
            fold(key, b)
        for key, b in st.running.items():
            fold(key, b)
        # weeks never roll into months/years (non-nesting); chain durations
        # finer than `per` always do
        for d in self.chain:
            if DURATIONS.index(d) >= DURATIONS.index(per):
                continue
            for (bs, gk), b in self.stores[d].running.items():
                pk = (int(bucket_starts(np.asarray([bs]), per)[0]), gk)
                fold(pk, b)

        items = sorted(merged.items(), key=lambda kv: (kv[0][0], repr(kv[0][1])))
        if within is not None:
            lo, hi = within
            items = [(k, b) for k, b in items if lo <= k[0] < hi]

        n = len(items)
        env: Dict[str, object] = {}
        starts = np.asarray([k[0] for k, _ in items], dtype=np.int64)
        env[AGG_START_TS] = starts
        for gi, gname in enumerate(self.group_names):
            vals = [k[1][gi] for k, _ in items]
            env[gname] = np.asarray(vals, dtype=object if any(isinstance(v, str) for v in vals) else None)
        for f in self.base_fields:
            col = [b.values.get(f.name) for _, b in items]
            if f.op == "set":
                env[f.name] = np.asarray([len(s) if s is not None else 0 for s in col], dtype=np.int64)
            elif f.type in (AttrType.STRING, AttrType.OBJECT):
                env[f.name] = np.asarray(col, dtype=object)
            else:
                env[f.name] = np.asarray(col)
        from siddhi_tpu.planner.expr import N_KEY, TS_KEY

        env[N_KEY] = n
        env[TS_KEY] = starts
        cols: Dict[str, np.ndarray] = {AGG_START_TS: starts}
        for nm, compiled in self.out_items:
            cols[nm] = np.broadcast_to(np.asarray(compiled(env)), (n,)) if n else np.asarray([])
        return EventBatch(
            self.name,
            [a.name for a in self.output_definition.attributes],
            cols,
            timestamps=starts,
        )

    # -- snapshot -----------------------------------------------------------

    def snapshot(self) -> Dict:
        # persistence barrier: the host store must be complete — device
        # bucket rows would otherwise be lost with the process
        self._flush_bank()

        def dump(d: Dict[Tuple[int, Tuple], _Bucket]):
            return [(k, b.values, b.last_ts) for k, b in d.items()]

        return {
            "watermark": self.watermark,
            "stores": {
                d: {"running": dump(st.running), "finished": dump(st.finished)}
                for d, st in self.stores.items()
            },
        }

    def restore(self, state: Dict):
        # the restored host snapshot is the single source of truth;
        # pre-restore device rows are stale
        if self._bank is not None:
            self._bank.clear()
        self.watermark = state["watermark"]
        for d, st_state in state["stores"].items():
            st = self.stores[d]
            st.running.clear()
            st.finished.clear()
            for k, values, last_ts in st_state["running"]:
                b = _Bucket()
                b.values = dict(values)
                b.last_ts = last_ts
                st.running[tuple(k) if not isinstance(k, tuple) else k] = b
            for k, values, last_ts in st_state["finished"]:
                b = _Bucket()
                b.values = dict(values)
                b.last_ts = last_ts
                st.finished[tuple(k) if not isinstance(k, tuple) else k] = b


_DT_FIELDS = 6  # year month day hour minute second


def parse_datetime_ms(s: str) -> int:
    """``yyyy-MM-dd HH:mm:ss`` (optional ``+HH:MM`` offset) -> epoch ms, UTC
    default (the reference's IncrementalTimeConverterUtil)."""
    import datetime as _dt

    s = s.strip()
    tz = _dt.timezone.utc
    m = _re.search(r"\s([+-]\d{2}):(\d{2})$", s)
    if m:
        sign = 1 if m.group(1)[0] == "+" else -1
        tz = _dt.timezone(
            sign * _dt.timedelta(hours=abs(int(m.group(1))), minutes=int(m.group(2)))
        )
        s = s[: m.start()]
    dt = _dt.datetime.strptime(s, "%Y-%m-%d %H:%M:%S").replace(tzinfo=tz)
    return int(dt.timestamp() * 1000)


def _wildcard_bounds(pattern: str) -> Tuple[int, int]:
    """``"2017-06-** **:**:**"`` -> [month start, next month).  The first
    ``**`` fixes the granularity; everything after it must be wildcarded."""
    import datetime as _dt

    parts = _re.split(r"[-\s:]+", pattern.strip())
    if len(parts) != _DT_FIELDS:
        raise SiddhiAppCreationError(
            f"within pattern '{pattern}': expected yyyy-MM-dd HH:mm:ss with ** wildcards"
        )
    fixed: List[int] = []
    for p in parts:
        if p == "**":
            break
        fixed.append(int(p))
    if len(fixed) == _DT_FIELDS:  # no wildcard: a single second
        lo = parse_datetime_ms(
            f"{fixed[0]:04d}-{fixed[1]:02d}-{fixed[2]:02d} {fixed[3]:02d}:{fixed[4]:02d}:{fixed[5]:02d}"
        )
        return lo, lo + 1000
    mins = [1, 1, 1, 0, 0, 0]  # month/day floor at 1
    vals = fixed + mins[len(fixed) :]
    start = _dt.datetime(*vals, tzinfo=_dt.timezone.utc)
    unit = len(fixed) - 1  # index of last fixed field
    if unit < 0:
        raise SiddhiAppCreationError(f"within pattern '{pattern}': fully wildcarded")
    if unit == 0:
        end = start.replace(year=start.year + 1)
    elif unit == 1:
        end = (
            start.replace(year=start.year + 1, month=1)
            if start.month == 12
            else start.replace(month=start.month + 1)
        )
    else:
        deltas = {2: _dt.timedelta(days=1), 3: _dt.timedelta(hours=1),
                  4: _dt.timedelta(minutes=1), 5: _dt.timedelta(seconds=1)}
        end = start + deltas[unit]
    return int(start.timestamp() * 1000), int(end.timestamp() * 1000)


def within_bounds(v1, v2=None) -> Tuple[int, int]:
    """Resolve a ``within`` clause to an epoch-ms half-open range.

    One arg: a wildcard pattern string (or a plain instant, which bounds only
    the start).  Two args: [start, end) each a long or datetime string.
    """

    def to_ms(v) -> int:
        if isinstance(v, (int, np.integer)):
            return int(v)
        if isinstance(v, (float, np.floating)):
            return int(v)
        if isinstance(v, str):
            if "*" in v:
                raise SiddhiAppCreationError("wildcard pattern is single-arg only")
            return parse_datetime_ms(v)
        raise SiddhiAppCreationError(f"within: cannot interpret {v!r} as a time")

    if v2 is None:
        if isinstance(v1, str) and "*" in v1:
            return _wildcard_bounds(v1)
        return to_ms(v1), 1 << 62
    return to_ms(v1), to_ms(v2)


def _canon_duration(per: str) -> str:
    p = per.strip().lower()
    table = {
        "sec": "seconds", "second": "seconds", "seconds": "seconds",
        "min": "minutes", "minute": "minutes", "minutes": "minutes",
        "hour": "hours", "hours": "hours",
        "day": "days", "days": "days",
        "week": "weeks", "weeks": "weeks",
        "month": "months", "months": "months",
        "year": "years", "years": "years",
    }
    if p not in table:
        raise SiddhiAppCreationError(f"unknown aggregation duration '{per}'")
    return table[p]
