from siddhi_tpu.aggregation.runtime import AggregationRuntime

__all__ = ["AggregationRuntime"]
