from siddhi_tpu.table.table import CompiledTableCondition, InMemoryTable
from siddhi_tpu.table.callbacks import (
    DeleteTableCallback,
    InsertIntoTableCallback,
    UpdateOrInsertTableCallback,
    UpdateTableCallback,
    compile_set_clause,
)

__all__ = [
    "CompiledTableCondition",
    "InMemoryTable",
    "DeleteTableCallback",
    "InsertIntoTableCallback",
    "UpdateOrInsertTableCallback",
    "UpdateTableCallback",
    "compile_set_clause",
]
