from siddhi_tpu.table.table import (
    CompiledTableCondition,
    InMemoryTable,
    compile_table_condition,
)
from siddhi_tpu.table.record import (
    AbstractRecordTable,
    InMemoryRecordStore,
    RecordCompiledCondition,
    RecordTableHandler,
    RecordTableRuntime,
    TableCache,
)
from siddhi_tpu.table.callbacks import (
    DeleteTableCallback,
    InsertIntoTableCallback,
    UpdateOrInsertTableCallback,
    UpdateTableCallback,
    compile_set_clause,
)

__all__ = [
    "AbstractRecordTable",
    "CompiledTableCondition",
    "InMemoryRecordStore",
    "RecordCompiledCondition",
    "RecordTableHandler",
    "RecordTableRuntime",
    "TableCache",
    "compile_table_condition",
    "InMemoryTable",
    "DeleteTableCallback",
    "InsertIntoTableCallback",
    "UpdateOrInsertTableCallback",
    "UpdateTableCallback",
    "compile_set_clause",
]
