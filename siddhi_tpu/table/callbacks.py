"""Table output callbacks: insert / delete / update / update-or-insert.

Re-design of the reference ``query/output/callback/``
(InsertIntoTableCallback, DeleteTableCallback, UpdateTableCallback,
UpdateOrInsertTableCallback): the selector's output batch is the
matching-side event set; each row probes the table through the compiled
condition (pk/index/scan plan) and mutates matched slots.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from siddhi_tpu.core import event as ev
from siddhi_tpu.core.event import EventBatch
from siddhi_tpu.core.exceptions import SiddhiAppCreationError
from siddhi_tpu.core.query import OutputCallback
from siddhi_tpu.planner.expr import CompiledExpression, ExpressionCompiler, Scope
from siddhi_tpu.query_api import AttrType, SetAttribute, Variable
from siddhi_tpu.table.table import CompiledTableCondition, InMemoryTable, _merge_table_scope


def _select_types(batch: EventBatch, event_type: str) -> EventBatch:
    if event_type == "current":
        return batch.only(ev.CURRENT)
    if event_type == "expired":
        return batch.only(ev.EXPIRED)
    return batch.only(ev.CURRENT, ev.EXPIRED)


def _event_env(batch: EventBatch, i: int) -> Dict:
    env = {nm: batch.columns[nm][i] for nm in batch.attribute_names}
    from siddhi_tpu.planner.expr import N_KEY, TS_KEY

    env[TS_KEY] = batch.timestamps[i]
    env[N_KEY] = 1
    return env


def _require_covering_schema(table: InMemoryTable, output_names: Optional[List[str]], what: str):
    if output_names is None:
        return
    missing = [nm for nm in table.definition.attribute_names if nm not in output_names]
    if missing:
        raise SiddhiAppCreationError(
            f"{what} '{table.table_id}': output is missing table attribute(s) {missing}"
        )


class InsertIntoTableCallback(OutputCallback):
    """insert into <table> (reference: InsertIntoTableCallback.java)."""

    def __init__(self, table: InMemoryTable, event_type: str, output_names: Optional[List[str]] = None):
        self.table = table
        self.event_type = event_type
        _require_covering_schema(table, output_names, "insert into table")

    def send(self, batch: EventBatch, now: int):
        out = _select_types(batch, self.event_type)
        if len(out) == 0:
            return
        if out.attribute_names != self.table.definition.attribute_names:
            # project by name (validated to cover the table at plan time)
            cols = {nm: out.columns[nm] for nm in self.table.definition.attribute_names}
            out = EventBatch(
                self.table.table_id,
                self.table.definition.attribute_names,
                cols,
                out.timestamps,
                out.types,
            )
        self.table.insert(out)


class DeleteTableCallback(OutputCallback):
    """<query> delete <table> on <cond> (reference: DeleteTableCallback)."""

    def __init__(self, table: InMemoryTable, condition: CompiledTableCondition, event_type: str):
        self.table = table
        self.condition = condition
        self.event_type = event_type

    def send(self, batch: EventBatch, now: int):
        out = _select_types(batch, self.event_type)
        for i in range(len(out)):
            slots = self.condition.slots_matching(_event_env(out, i))
            if len(slots):
                self.table.delete_slots(slots)


class _SetOp:
    __slots__ = ("attr", "compiled")

    def __init__(self, attr: str, compiled: CompiledExpression):
        self.attr = attr
        self.compiled = compiled


def compile_set_clause(
    table: InMemoryTable,
    set_clause: Optional[List[SetAttribute]],
    event_scope: Scope,
    output_names: List[str],
    functions: Optional[Dict] = None,
    table_resolver=None,
) -> List[_SetOp]:
    """Compile `set T.a = expr, ...`; default (no clause) copies every
    output attribute whose name matches a table attribute (reference:
    UpdateTableCallback default set semantics)."""
    scope = _merge_table_scope(event_scope, table)
    compiler = ExpressionCompiler(scope, functions=functions, table_resolver=table_resolver)
    ops: List[_SetOp] = []
    if set_clause is None:
        table_names = set(table.definition.attribute_names)
        shared = [nm for nm in output_names if nm in table_names]
        if not shared:
            raise SiddhiAppCreationError(
                f"update {table.table_id}: no output attribute matches a table attribute"
            )
        for nm in shared:
            ops.append(_SetOp(nm, compiler.compile(Variable(attribute=nm))))
        return ops
    for sa in set_clause:
        v = sa.variable
        if v.stream_id not in (None, table.table_id) or (
            v.attribute not in table.definition.attribute_names
        ):
            raise SiddhiAppCreationError(
                f"set target '{v.stream_id}.{v.attribute}' is not an attribute "
                f"of table '{table.table_id}'"
            )
        ops.append(_SetOp(v.attribute, compiler.compile(sa.expression)))
    return ops


class UpdateTableCallback(OutputCallback):
    """<query> update <table> set ... on <cond>."""

    def __init__(
        self,
        table: InMemoryTable,
        condition: CompiledTableCondition,
        set_ops: List[_SetOp],
        event_type: str,
    ):
        self.table = table
        self.condition = condition
        self.set_ops = set_ops
        self.event_type = event_type

    def send(self, batch: EventBatch, now: int):
        out = _select_types(batch, self.event_type)
        for i in range(len(out)):
            env = _event_env(out, i)
            slots = self.condition.slots_matching(env)
            if len(slots):
                self._apply(slots, env)

    def _apply(self, slots: np.ndarray, env: Dict):
        env = dict(env)
        env.update(self.table.column_env(slots))
        values = {
            op.attr: np.broadcast_to(np.asarray(op.compiled.fn(env)), (len(slots),))
            for op in self.set_ops
        }
        self.table.update_slots(slots, values)


class UpdateOrInsertTableCallback(UpdateTableCallback):
    """<query> update or insert into <table> set ... on <cond>: rows with
    no match insert the output event as a new table row (reference:
    UpdateOrInsertTableCallback)."""

    def __init__(self, table, condition, set_ops, event_type, output_names=None):
        super().__init__(table, condition, set_ops, event_type)
        # a PARTIAL projection is allowed (reference
        # UpdateOrInsertTableTestCase.updateOrInsertTableTest5: `select
        # comp as symbol, vol as volume update or insert ...`): matched
        # rows update only the projected columns; the insert path fills
        # unprojected columns with null
        self._projected = (
            None if output_names is None
            else [nm for nm in table.definition.attribute_names
                  if nm in output_names])

    def send(self, batch: EventBatch, now: int):
        out = _select_types(batch, self.event_type)
        names = (self._projected if self._projected is not None
                 else self.table.definition.attribute_names)
        for i in range(len(out)):
            env = _event_env(out, i)
            slots = self.condition.slots_matching(env)
            if len(slots):
                self._apply(slots, env)
            else:
                row = {
                    nm: (out.columns[nm][i] if nm in names else None)
                    for nm in self.table.definition.attribute_names
                }
                with self.table._lock:
                    self.table._insert_row(row, int(out.timestamps[i]))


# -- lowered devtable callbacks (one scatter step per batch) ----------------
#
# These replace the per-row probe loops above when the planner's devtable
# mutation gate passes (single primary-key equality condition, event-only
# set expressions — see devtable/planner.py).  Each evaluates the key and
# set expressions VECTORIZED over the output batch and hands the whole
# batch to one DeviceTable entry point (one jitted scatter).  Runtime
# shapes the kernel cannot express — a primary-key rewrite, an insert
# landing after an update of the same slot — delegate that batch to the
# kept generic callback: counted and logged once, results never change.


def _batch_env(out: EventBatch) -> Dict:
    from siddhi_tpu.planner.expr import N_KEY, TS_KEY

    env = {nm: out.columns[nm] for nm in out.attribute_names}
    env[TS_KEY] = out.timestamps
    env[N_KEY] = len(out)
    return env


class _DevTableCallback(OutputCallback):
    def __init__(self, table, key_expr, event_type: str, generic=None):
        self.table = table
        self.key = key_expr
        self.event_type = event_type
        self.generic = generic
        self._warned = False

    def _keys(self, out: EventBatch, env: Dict) -> np.ndarray:
        return np.broadcast_to(self.key.fn(env), (len(out),))

    def _delegate(self, batch: EventBatch, now: int, reason: str):
        if not self._warned:
            self._warned = True
            import logging

            logging.getLogger("siddhi_tpu").warning(
                "devtable '%s': batch delegated to the host-path callback "
                "(%s); results are unchanged, this batch runs per-row",
                self.table.table_id, reason)
        sm = getattr(self.table, "_sm", None)
        if sm is not None:
            sm.record_devtable_fallback(
                f"table:{self.table.table_id}", reason)
        self.generic.send(batch, now)


class DevTableDeleteCallback(_DevTableCallback):
    """<query> delete <devtable> on T.pk == <event expr> — one kill
    scatter for the batch."""

    def send(self, batch: EventBatch, now: int):
        out = _select_types(batch, self.event_type)
        if len(out) == 0:
            return
        env = _batch_env(out)
        self.table.delete_keys(self._keys(out, env))


class DevTableUpdateCallback(_DevTableCallback):
    """<query> update <devtable> set ... on T.pk == <event expr> — one
    write scatter for the batch."""

    def __init__(self, table, key_expr, set_ops, event_type: str, generic):
        super().__init__(table, key_expr, event_type, generic)
        self.set_ops = set_ops

    def _values(self, out: EventBatch, env: Dict) -> Dict[str, np.ndarray]:
        n = len(out)
        return {attr: np.broadcast_to(c.fn(env), (n,))
                for attr, c in self.set_ops}

    def send(self, batch: EventBatch, now: int):
        out = _select_types(batch, self.event_type)
        if len(out) == 0:
            return
        env = _batch_env(out)
        keys = self._keys(out, env)
        values = self._values(out, env)
        pk = self.table.pk
        if pk in values:
            if np.array_equal(values[pk], keys):
                values.pop(pk)  # identity rewrite: a no-op on the map
            else:
                self._delegate(batch, now, "primary-key rewrite in set clause")
                return
        if values:
            self.table.update_keys(keys, values)


class DevTableUpsertCallback(DevTableUpdateCallback):
    """<query> update or insert into <devtable> set ... on T.pk == <event
    expr> — misses insert the projected row, hits apply the set clause;
    at most two scatters for the batch."""

    def send(self, batch: EventBatch, now: int):
        out = _select_types(batch, self.event_type)
        if len(out) == 0:
            return
        env = _batch_env(out)
        keys = self._keys(out, env)
        values = self._values(out, env)
        pk = self.table.pk
        if pk in values and not np.array_equal(values[pk], keys):
            # host semantics: a hit rewrites the row's key via update_slots
            self._delegate(batch, now, "primary-key rewrite in set clause")
            return
        values.pop(pk, None)
        ins = {nm: out.columns[nm]
               for nm in self.table.definition.attribute_names}
        if not self.table.upsert(keys, ins, values, out.timestamps):
            self._delegate(batch, now,
                           "insert after update of the same slot in one batch")
