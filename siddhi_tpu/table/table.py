"""In-memory tables: columnar storage + primary-key/secondary indexes +
compiled conditions.

Re-design of the reference ``core/table/`` (InMemoryTable.java:58,
holder/IndexEventHolder.java:60) and the compiled-condition planner
(``util/collection/`` + CollectionExpressionParser.java:79): rows live in
columnar numpy arrays with a liveness mask; a compiled condition picks
between a primary-key hash probe, a secondary-index probe, and a
vectorized full scan (the ExhaustiveCollectionExecutor analog — but one
numpy pass over the column instead of a per-row executor walk).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from siddhi_tpu.core import event as ev
from siddhi_tpu.core.event import EventBatch
from siddhi_tpu.core.exceptions import SiddhiAppCreationError, SiddhiAppRuntimeError
from siddhi_tpu.planner.expr import CompiledExpression, ExpressionCompiler, Scope
from siddhi_tpu.query_api import (
    AndOp,
    AttrType,
    CompareOp,
    Expression,
    TableDefinition,
    Variable,
)
from siddhi_tpu.query_api.annotation import find_annotation

TBL = "__tbl."  # env-key prefix for table columns inside compiled conditions


def _scalar(v):
    if isinstance(v, (np.generic, np.ndarray)):
        return v.item()  # 0-d / single-element only (scalar contexts)
    return v


class InMemoryTable:
    """Columnar in-memory table.

    Storage: one capacity-sized numpy array per attribute + a liveness
    mask.  Deletes clear the mask (slots are recycled via a free list);
    scans are vectorized over live rows.  ``@PrimaryKey`` maintains a
    hash map key-tuple -> slot; ``@Index`` maintains per-value slot sets.
    """

    def __init__(self, definition: TableDefinition, capacity: int = 64):
        self.definition = definition
        self.table_id = definition.id
        self._lock = threading.RLock()
        self._cap = capacity
        self._cols: Dict[str, np.ndarray] = {
            a.name: np.zeros(capacity, dtype=a.type.np_dtype) for a in definition.attributes
        }
        self._ts = np.zeros(capacity, dtype=np.int64)
        self._live = np.zeros(capacity, dtype=bool)
        self._hwm = 0  # high-water mark
        self._free: List[int] = []

        pk_ann = find_annotation(definition.annotations, "PrimaryKey")
        self.primary_keys: Optional[List[str]] = None
        if pk_ann is not None:
            self.primary_keys = [v for _, v in pk_ann.elements] or None
            for k in self.primary_keys or ():
                if k not in definition.attribute_names:
                    raise SiddhiAppCreationError(
                        f"table '{definition.id}': primary key '{k}' is not an attribute"
                    )
        self._pk_map: Dict = {}
        self.indexes: Dict[str, Dict] = {}
        for idx_ann in (a for a in definition.annotations if a.name.lower() == "index"):
            for _, attr in idx_ann.elements:
                if attr not in definition.attribute_names:
                    raise SiddhiAppCreationError(
                        f"table '{definition.id}': index '{attr}' is not an attribute"
                    )
                self.indexes[attr] = {}

    # -- basics -------------------------------------------------------------

    def __len__(self) -> int:
        return int(self._live.sum())

    @property
    def size(self) -> int:
        return len(self)

    def live_slots(self) -> np.ndarray:
        return np.flatnonzero(self._live)

    def _pk_of_slot(self, slot: int):
        vals = tuple(_scalar(self._cols[k][slot]) for k in self.primary_keys)
        return vals[0] if len(vals) == 1 else vals

    def _grow(self, need: int):
        new_cap = max(self._cap * 2, self._hwm + need)
        for k, col in self._cols.items():
            g = np.zeros(new_cap, dtype=col.dtype)
            g[: self._cap] = col
            self._cols[k] = g
        for name, arr in (("_ts", self._ts), ("_live", self._live)):
            g = np.zeros(new_cap, dtype=arr.dtype)
            g[: self._cap] = arr
            setattr(self, name, g)
        self._cap = new_cap

    def _alloc(self) -> int:
        if self._free:
            return self._free.pop()
        if self._hwm >= self._cap:
            self._grow(1)
        s = self._hwm
        self._hwm += 1
        return s

    # -- mutation -----------------------------------------------------------

    def insert(self, batch: EventBatch):
        """Add rows (reference: InMemoryTable.add).  With a primary key,
        a duplicate-key insert replaces the existing row (last-writer-wins,
        the deterministic analog of IndexEventHolder overwrite)."""
        with self._lock:
            for i in range(len(batch)):
                row = {nm: batch.columns[nm][i] for nm in self.definition.attribute_names}
                self._insert_row(row, int(batch.timestamps[i]))

    def _promote_to_object(self, nm: str):
        """Switch a typed column to object dtype so it can hold nulls
        (outer-join unmatched lanes insert None — the reference's boxed
        rows hold nulls natively; scans on object columns stay correct,
        just slower)."""
        col = self._cols[nm]
        if col.dtype != object:
            self._cols[nm] = col.astype(object)

    def _insert_row(self, row: Dict, ts: int) -> int:
        if self.primary_keys:
            vals = tuple(_scalar(row[k]) for k in self.primary_keys)
            key = vals[0] if len(vals) == 1 else vals
            existing = self._pk_map.get(key)
            if existing is not None:
                self._delete_slot(existing)
            slot = self._alloc()
            self._pk_map[key] = slot
        else:
            slot = self._alloc()
        for nm in self.definition.attribute_names:
            v = row[nm]
            if v is None and self._cols[nm].dtype != object:
                self._promote_to_object(nm)
            try:
                self._cols[nm][slot] = v
            except (TypeError, ValueError):
                self._promote_to_object(nm)
                self._cols[nm][slot] = v
        self._ts[slot] = ts
        self._live[slot] = True
        for attr, index in self.indexes.items():
            index.setdefault(_scalar(row[attr]), set()).add(slot)
        return slot

    def _delete_slot(self, slot: int):
        self._live[slot] = False
        self._free.append(slot)
        if self.primary_keys:
            self._pk_map.pop(self._pk_of_slot(slot), None)
        for attr, index in self.indexes.items():
            v = _scalar(self._cols[attr][slot])
            bucket = index.get(v)
            if bucket is not None:
                bucket.discard(slot)
                if not bucket:
                    del index[v]

    def delete_slots(self, slots: Sequence[int]):
        with self._lock:
            for s in slots:
                if self._live[s]:
                    self._delete_slot(int(s))

    def update_slots(self, slots: Sequence[int], values: Dict[str, Sequence]):
        """Set table attrs on given slots; values[attr][j] applies to
        slots[j].  Maintains pk/index structures."""
        with self._lock:
            touched_pk = self.primary_keys and any(k in values for k in self.primary_keys)
            for j, s in enumerate(slots):
                s = int(s)
                if not self._live[s]:
                    continue
                if touched_pk:
                    self._pk_map.pop(self._pk_of_slot(s), None)
                for attr in values:
                    if attr in self.indexes:
                        v_old = _scalar(self._cols[attr][s])
                        bucket = self.indexes[attr].get(v_old)
                        if bucket is not None:
                            bucket.discard(s)
                            if not bucket:
                                del self.indexes[attr][v_old]
                for attr, vals in values.items():
                    v = vals[j]
                    if v is None and self._cols[attr].dtype != object:
                        self._promote_to_object(attr)
                    try:
                        self._cols[attr][s] = v
                    except (TypeError, ValueError):
                        self._promote_to_object(attr)
                        self._cols[attr][s] = v
                    if attr in self.indexes:
                        self.indexes[attr].setdefault(_scalar(self._cols[attr][s]), set()).add(s)
                if touched_pk:
                    key = self._pk_of_slot(s)
                    # keep the key unique: an update landing on an existing
                    # key replaces that row (last-writer-wins, same as insert)
                    other = self._pk_map.get(key)
                    if other is not None and other != s:
                        self._delete_slot(other)
                    self._pk_map[key] = s

    # -- reads --------------------------------------------------------------

    def rows_batch(self, slots: Optional[np.ndarray] = None) -> EventBatch:
        """Live rows (optionally restricted to slots) as an EventBatch in
        insertion-slot order."""
        with self._lock:
            if slots is None:
                slots = self.live_slots()
            return EventBatch(
                self.table_id,
                self.definition.attribute_names,
                {nm: self._cols[nm][slots] for nm in self.definition.attribute_names},
                self._ts[slots],
            )

    def column_env(self, slots: np.ndarray) -> Dict[str, np.ndarray]:
        return {TBL + nm: self._cols[nm][slots] for nm in self.definition.attribute_names}

    def contains_fn(self, attr_hint: Optional[str] = None) -> Callable:
        """Membership test for `expr IN Table`: matches against the
        primary key when single-attribute, else the sole attribute."""
        if self.primary_keys and len(self.primary_keys) == 1:
            probe = self.primary_keys[0]
        elif len(self.definition.attributes) == 1:
            probe = self.definition.attributes[0].name
        elif attr_hint is not None:
            probe = attr_hint
        else:
            raise SiddhiAppCreationError(
                f"'IN {self.table_id}': table needs a single-attribute primary key"
            )

        def member(values) -> np.ndarray:
            with self._lock:
                if self.primary_keys == [probe]:
                    keys = self._pk_map
                    return np.frompyfunc(lambda v: _scalar(v) in keys, 1, 1)(
                        np.atleast_1d(np.asarray(values))
                    ).astype(bool)
                col = self._cols[probe][self.live_slots()]
                return np.isin(np.atleast_1d(np.asarray(values)), col)

        return member

    # -- snapshot contract --------------------------------------------------

    def snapshot(self) -> Dict:
        with self._lock:
            slots = self.live_slots()
            return {
                "cols": {nm: self._cols[nm][slots].copy() for nm in self._cols},
                "ts": self._ts[slots].copy(),
            }

    def restore(self, state: Dict):
        with self._lock:
            self._pk_map.clear()
            for index in self.indexes.values():
                index.clear()
            self._live[:] = False
            self._free = []
            self._hwm = 0
            n = len(state["ts"])
            if n > self._cap:
                self._grow(n)
            for i in range(n):
                row = {nm: state["cols"][nm][i] for nm in self._cols}
                self._insert_row(row, int(state["ts"][i]))


# ---------------------------------------------------------------------------
# Compiled conditions (CollectionExecutor analog)
# ---------------------------------------------------------------------------


class CompiledTableCondition:
    """A condition over (table row, matching-side event) compiled into a
    slot-set planner: per matching event, returns the live slots whose
    rows satisfy the condition.

    Plans, in order of preference (reference:
    CollectionExpressionParser.java:79 choosing Compare/AndMultiPrimaryKey/
    Exhaustive collection executors):
      1. primary-key probe — equality terms cover the full primary key;
      2. secondary-index probe — an equality term hits an indexed attr
         (remaining terms verified on the candidate set);
      3. vectorized full scan.
    """

    def __init__(
        self,
        table: InMemoryTable,
        condition: Optional[Expression],
        event_scope: Scope,
        extra_functions: Optional[Dict] = None,
        table_resolver=None,
    ):
        self.table = table
        scope = _merge_table_scope(event_scope, table)
        compiler = ExpressionCompiler(
            scope, functions=extra_functions, table_resolver=table_resolver
        )
        self._predicate: Optional[CompiledExpression] = None
        self._pk_exprs: Optional[List[CompiledExpression]] = None
        self._index_probe: Optional[Tuple[str, CompiledExpression]] = None
        if condition is None:
            return
        self._predicate = compiler.compile(condition)
        if self._predicate.type != AttrType.BOOL:
            raise SiddhiAppCreationError("'on' condition must be boolean")

        eq_terms, only_conj = _equality_terms(condition, table)
        if only_conj and table.primary_keys:
            by_attr = {attr: rhs for attr, rhs in eq_terms}
            if all(k in by_attr for k in table.primary_keys) and len(eq_terms) == len(
                table.primary_keys
            ):
                self._pk_exprs = [compiler.compile(by_attr[k]) for k in table.primary_keys]
        if self._pk_exprs is None and only_conj:
            for attr, rhs in eq_terms:
                if attr in table.indexes:
                    self._index_probe = (attr, compiler.compile(rhs))
                    break

    def slots_matching(self, event_env: Dict) -> np.ndarray:
        """Slots of table rows matching one event (env holds scalar
        values of the matching-side attributes)."""
        table = self.table
        if self._predicate is None:
            return table.live_slots()
        if self._pk_exprs is not None:
            vals = tuple(_scalar(np.asarray(e.fn(event_env)).reshape(())) for e in self._pk_exprs)
            key = vals[0] if len(vals) == 1 else vals
            slot = table._pk_map.get(key)
            return np.asarray([slot] if slot is not None else [], dtype=np.int64)
        if self._index_probe is not None:
            attr, e = self._index_probe
            v = _scalar(np.asarray(e.fn(event_env)).reshape(()))
            cand = np.asarray(sorted(table.indexes[attr].get(v, ())), dtype=np.int64)
        else:
            cand = table.live_slots()
        if len(cand) == 0:
            return cand
        env = dict(event_env)
        env.update(table.column_env(cand))
        m = np.broadcast_to(np.asarray(self._predicate.fn(env)), (len(cand),))
        return cand[m]


def _merge_table_scope(event_scope: Scope, table: InMemoryTable) -> Scope:
    """Matching-side attrs resolve bare or stream-qualified; table attrs
    resolve under the table name (and bare when not shadowed by the
    event side)."""
    scope = event_scope.clone()
    for a in table.definition.attributes:
        already_bare = scope._bare.get(a.name) is not None
        scope.add(table.table_id, a.name, TBL + a.name, a.type)
        if already_bare:
            # event side shadows the table for bare names (undo add's
            # ambiguity marking — on-conditions resolve bare attrs to the
            # matching-event side, reference compileCondition behavior)
            scope._bare[a.name] = event_scope._bare[a.name]
    return scope


def _equality_terms(cond: Expression, table: InMemoryTable):
    """Collect (table_attr, event_expr) equality terms from a pure
    conjunction; returns (terms, is_pure_conjunction_of_equalities)."""
    terms: List[Tuple[str, Expression]] = []

    def is_table_var(e: Expression) -> Optional[str]:
        if (
            isinstance(e, Variable)
            and e.stream_id in (table.table_id, None)
            and e.attribute in table.definition.attribute_names
        ):
            # bare names are table-side only when unambiguous is not
            # required here: qualified access is the supported fast path
            if e.stream_id == table.table_id:
                return e.attribute
        return None

    def refs_table(e: Expression) -> bool:
        if isinstance(e, Variable):
            return e.stream_id == table.table_id
        for f in ("left", "right", "expr"):
            sub = getattr(e, f, None)
            if isinstance(sub, Expression) and refs_table(sub):
                return True
        for a in getattr(e, "args", ()) or ():
            if isinstance(a, Expression) and refs_table(a):
                return True
        return False

    def walk(e: Expression) -> bool:
        if isinstance(e, AndOp):
            return walk(e.left) and walk(e.right)
        if isinstance(e, CompareOp) and e.op == "==":
            lv, rv = is_table_var(e.left), is_table_var(e.right)
            if lv is not None and not refs_table(e.right):
                terms.append((lv, e.right))
                return True
            if rv is not None and not refs_table(e.left):
                terms.append((rv, e.left))
                return True
        return False

    ok = walk(cond)
    return terms, ok


def compile_table_condition(table, condition, event_scope, extra_functions=None,
                            table_resolver=None):
    """Dispatch: slot-planner condition for in-memory tables, push-down
    IR + post-filter for record (store-backed) tables."""
    from siddhi_tpu.table.record import RecordCompiledCondition, RecordTableRuntime

    if isinstance(table, RecordTableRuntime):
        return RecordCompiledCondition(
            table, condition, event_scope, extra_functions, table_resolver
        )
    return CompiledTableCondition(
        table, condition, event_scope, extra_functions, table_resolver
    )
