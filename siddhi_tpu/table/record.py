"""Record tables: external-store-backed tables behind a Python SPI.

Re-design of the reference record-table layer
(``table/record/AbstractRecordTable.java`` — add/find/contains/delete/
update/updateOrAdd against an external store with compiled conditions,
``AbstractQueryableRecordTable.java`` — store-side condition push-down,
``table/CacheTable.java`` — FIFO/LRU/LFU caching in front of the store,
``table/record/RecordTableHandler.java`` — interception hook).

Instead of the reference's visitor-built store-native query strings, a
condition compiles to a small **portable IR** (And/Or/Not/Compare/IsNull
over table attributes, with event-side subexpressions turned into named
parameters evaluated per lookup).  Stores interpret as much of the IR as
they can; the runtime always re-verifies fetched rows with the full
vectorized predicate, so a store may ignore the IR entirely and scan.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from siddhi_tpu.core.event import EventBatch
from siddhi_tpu.core.exceptions import SiddhiAppCreationError
from siddhi_tpu.extension.registry import extension
from siddhi_tpu.planner.expr import CompiledExpression, ExpressionCompiler, Scope
from siddhi_tpu.query_api import AttrType
from siddhi_tpu.query_api import expression as X
from siddhi_tpu.table.table import TBL, _merge_table_scope, _scalar


# ---------------------------------------------------------------------------
# Portable condition IR handed to stores
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class StoreConst:
    value: object


@dataclass(frozen=True)
class StoreParam:
    """Named parameter filled per lookup from the matching-side event."""

    key: str


@dataclass(frozen=True)
class StoreCompare:
    attr: str
    op: str  # '<', '<=', '>', '>=', '==', '!='
    rhs: object  # StoreConst | StoreParam


@dataclass(frozen=True)
class StoreIsNull:
    attr: str


@dataclass(frozen=True)
class StoreAnd:
    parts: tuple


@dataclass(frozen=True)
class StoreOr:
    parts: tuple


@dataclass(frozen=True)
class StoreNot:
    part: object


@dataclass(frozen=True)
class StoreTrue:
    """Matches every record (store should full-scan)."""


_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "==": "==", "!=": "!="}


class _StoreConditionBuilder:
    """Expression AST -> (IR, param expressions).

    Push-down is conservative: a subtree is pushed only when it is a
    comparison between one bare table attribute and an event-side
    expression (or constants), composed with and/or/not.  Unpushable
    subtrees inside a conjunction are dropped from the IR (the runtime's
    post-filter keeps exactness); anywhere else the whole condition
    falls back to StoreTrue.
    """

    def __init__(self, table_id: str, table_attrs: List[str], event_compiler: ExpressionCompiler):
        self.table_id = table_id
        self.table_attrs = set(table_attrs)
        self.event_compiler = event_compiler
        self.params: Dict[str, CompiledExpression] = {}

    def build(self, e: X.Expression):
        ir = self._conj(e)
        return ir if ir is not None else StoreTrue()

    # conjunction level: drop unpushable conjuncts
    def _conj(self, e: X.Expression):
        if isinstance(e, X.AndOp):
            left, right = self._conj(e.left), self._conj(e.right)
            if left is None:
                return right
            if right is None:
                return left
            return StoreAnd((left, right))
        return self._strict(e)

    # below a NOT/OR everything must be pushable or nothing is
    def _strict(self, e: X.Expression):
        if isinstance(e, X.AndOp):
            left, right = self._strict(e.left), self._strict(e.right)
            if left is None or right is None:
                return None
            return StoreAnd((left, right))
        if isinstance(e, X.OrOp):
            left, right = self._strict(e.left), self._strict(e.right)
            if left is None or right is None:
                return None
            return StoreOr((left, right))
        if isinstance(e, X.NotOp):
            part = self._strict(e.expr)
            return StoreNot(part) if part is not None else None
        if isinstance(e, X.IsNull):
            attr = self._table_attr(e.expr)
            return StoreIsNull(attr) if attr is not None else None
        if isinstance(e, X.CompareOp):
            lattr, rattr = self._table_attr(e.left), self._table_attr(e.right)
            if lattr is not None and rattr is None and not self._refs_table(e.right):
                return StoreCompare(lattr, e.op, self._operand(e.right))
            if rattr is not None and lattr is None and not self._refs_table(e.left):
                return StoreCompare(rattr, _FLIP[e.op], self._operand(e.left))
            return None
        if isinstance(e, X.Constant) and e.value is True:
            return StoreTrue()
        return None

    def _table_attr(self, e: X.Expression) -> Optional[str]:
        if isinstance(e, X.Variable) and e.attribute in self.table_attrs:
            if e.stream_id == self.table_id or e.stream_id is None:
                return e.attribute
        return None

    def _refs_table(self, e: X.Expression) -> bool:
        if isinstance(e, X.Variable):
            return self._table_attr(e) is not None or e.stream_id == self.table_id
        for attr in ("left", "right", "expr"):
            child = getattr(e, attr, None)
            if isinstance(child, X.Expression) and self._refs_table(child):
                return True
        if isinstance(e, X.FunctionCall):
            return any(self._refs_table(a) for a in e.args)
        return False

    def _operand(self, e: X.Expression):
        if isinstance(e, X.Constant):
            return StoreConst(e.value)
        if isinstance(e, X.TimeConstant):
            return StoreConst(e.value)
        key = f"p{len(self.params)}"
        self.params[key] = self.event_compiler.compile(e)
        return StoreParam(key)


def evaluate_store_condition(ir, record: Dict, params: Dict) -> bool:
    """Reference interpreter for the IR over one record dict — used by
    InMemoryRecordStore and available to any store without a native
    query language."""
    if isinstance(ir, StoreTrue):
        return True
    if isinstance(ir, StoreAnd):
        return all(evaluate_store_condition(p, record, params) for p in ir.parts)
    if isinstance(ir, StoreOr):
        return any(evaluate_store_condition(p, record, params) for p in ir.parts)
    if isinstance(ir, StoreNot):
        return not evaluate_store_condition(ir.part, record, params)
    if isinstance(ir, StoreIsNull):
        return record.get(ir.attr) is None
    if isinstance(ir, StoreCompare):
        a = record.get(ir.attr)
        b = ir.rhs.value if isinstance(ir.rhs, StoreConst) else params[ir.rhs.key]
        op = ir.op
        if op == "==":
            return a == b
        if op == "!=":
            return a != b
        if a is None or b is None:
            return False
        if op == "<":
            return a < b
        if op == "<=":
            return a <= b
        if op == ">":
            return a > b
        return a >= b
    raise SiddhiAppCreationError(f"unknown store-condition node {type(ir).__name__}")


# ---------------------------------------------------------------------------
# Store SPI
# ---------------------------------------------------------------------------


class AbstractRecordTable:
    """External-store SPI (reference: AbstractRecordTable.java:87-409).

    Subclasses implement the record operations; records are lists in
    table-attribute order.  ``find`` receives the portable condition IR
    and a per-lookup parameter dict; a store may interpret it fully,
    partially, or return a superset — the runtime re-verifies rows.
    """

    def init(self, definition, options: Dict[str, str], config_reader=None):
        self.definition = definition
        self.options = options
        self.config_reader = config_reader

    def connect(self):
        pass

    def disconnect(self):
        pass

    # -- record operations -------------------------------------------------

    def add(self, records: List[list]):
        raise NotImplementedError

    def find(self, condition, params: Dict) -> Iterable[list]:
        raise NotImplementedError

    def contains(self, condition, params: Dict) -> bool:
        for _ in self.find(condition, params):
            return True
        return False

    def delete(self, condition, params_list: List[Dict]):
        raise NotImplementedError

    def update(self, condition, params_list: List[Dict], set_maps: List[Dict]):
        raise NotImplementedError

    def update_or_add(self, condition, params_list: List[Dict],
                      set_maps: List[Dict], records: List[list]):
        """Default: update matches; add a record for params with none."""
        for params, set_map, record in zip(params_list, set_maps, records):
            if self.contains(condition, params):
                self.update(condition, [params], [set_map])
            else:
                self.add([record])


class RecordTableHandler:
    """Interception hook around store operations (reference:
    RecordTableHandler.java).  Subclass and override; default passes
    through."""

    def on_add(self, records, call: Callable):
        return call(records)

    def on_find(self, condition, params, call: Callable):
        return call(condition, params)

    def on_delete(self, condition, params_list, call: Callable):
        return call(condition, params_list)

    def on_update(self, condition, params_list, set_maps, call: Callable):
        return call(condition, params_list, set_maps)


@extension("store", "memory")
class InMemoryRecordStore(AbstractRecordTable):
    """List-backed record store: the reference implementation of the SPI
    and the test double for store-backed tables (the analog of the
    reference's test ``testStoreContainingInMemoryTable``)."""

    _shared: Dict[str, List[list]] = {}
    _shared_locks: Dict[str, threading.RLock] = {}
    _shared_lock = threading.Lock()

    def init(self, definition, options, config_reader=None):
        super().init(definition, options, config_reader)
        self._names = list(definition.attribute_names)
        if options.get("shared", "false").lower() == "true":
            # rows outlive the runtime, keyed by table name — mirrors the
            # reference test stores' static backing map, letting restart
            # tests see a store that persisted across app instances.
            # The guarding lock must be shared too: per-instance locks
            # over shared rows would let two runtimes race on mutation.
            with self._shared_lock:
                self._rows = self._shared.setdefault(definition.id, [])
                self._lock = self._shared_locks.setdefault(
                    definition.id, threading.RLock())
        else:
            self._rows = []
            self._lock = threading.RLock()

    def _as_dict(self, row: list) -> Dict:
        return dict(zip(self._names, row))

    def add(self, records):
        with self._lock:
            self._rows.extend(list(r) for r in records)

    def find(self, condition, params):
        with self._lock:
            return [list(r) for r in self._rows
                    if evaluate_store_condition(condition, self._as_dict(r), params)]

    def delete(self, condition, params_list):
        with self._lock:
            for params in params_list:
                self._rows[:] = [
                    r for r in self._rows
                    if not evaluate_store_condition(condition, self._as_dict(r), params)
                ]

    def update(self, condition, params_list, set_maps):
        with self._lock:
            for params, set_map in zip(params_list, set_maps):
                for r in self._rows:
                    if evaluate_store_condition(condition, self._as_dict(r), params):
                        for attr, v in set_map.items():
                            r[self._names.index(attr)] = v


# ---------------------------------------------------------------------------
# Cache layer
# ---------------------------------------------------------------------------


class TableCache:
    """Primary-key row cache with FIFO / LRU / LFU eviction and
    optional time-based retention (reference: CacheTable.java +
    CacheTableFIFO/LRU/LFU with retention.period from @cache; unlike
    the reference's CacheExpirer thread, expired entries are dropped
    lazily on access and swept on insert)."""

    def __init__(self, max_size: int, policy: str = "FIFO",
                 retention_ms: Optional[int] = None, now_fn=None):
        policy = policy.upper()
        if policy not in ("FIFO", "LRU", "LFU"):
            raise SiddhiAppCreationError(f"unknown cache policy '{policy}'")
        if max_size < 1:
            raise SiddhiAppCreationError(
                f"@cache size must be >= 1, got {max_size}")
        self.max_size = max_size
        self.policy = policy
        self.retention_ms = retention_ms
        self._now = now_fn or (lambda: int(time.time() * 1000))
        self._d: "OrderedDict" = OrderedDict()
        self._freq: Dict = {}
        # key -> insert ms, kept oldest-first (stamps are clamped
        # monotone) so the retention sweep walks only the expired prefix
        self._added: "OrderedDict" = OrderedDict()
        self._last_stamp = 0
        self.hits = 0
        self.misses = 0

    def _expired(self, key) -> bool:
        return (self.retention_ms is not None
                and self._now() - self._added.get(key, 0)
                >= self.retention_ms)

    def get(self, key):
        if key in self._d and self._expired(key):
            self.invalidate(key)
        if key not in self._d:
            self.misses += 1
            return None
        self.hits += 1
        if self.policy == "LRU":
            self._d.move_to_end(key)
        elif self.policy == "LFU":
            self._freq[key] = self._freq.get(key, 0) + 1
        return self._d[key]

    def put(self, key, row):
        if self.retention_ms is not None:
            # clamp against backwards clock steps so stamps stay
            # monotone and the oldest-first prefix sweep stays sound
            now = max(self._now(), self._last_stamp)
            self._last_stamp = now
            while self._added:
                k, t = next(iter(self._added.items()))
                if now - t < self.retention_ms:
                    break
                self.invalidate(k)
            self._added[key] = now
            self._added.move_to_end(key)  # refresh keeps oldest-first
        if key in self._d:
            self._d[key] = row
            if self.policy == "LRU":
                self._d.move_to_end(key)
            return
        while len(self._d) >= self.max_size:
            self._evict_one()
        self._d[key] = row
        if self.policy == "LFU":
            self._freq[key] = 1

    def _evict_one(self):
        if self.policy == "LFU":
            victim = min(self._d, key=lambda k: self._freq.get(k, 0))
            self._d.pop(victim)
            self._freq.pop(victim, None)
        else:  # FIFO inserts at the back; LRU moves hits to the back
            victim, _ = self._d.popitem(last=False)
        self._added.pop(victim, None)

    def invalidate(self, key):
        self._d.pop(key, None)
        self._freq.pop(key, None)
        self._added.pop(key, None)

    def clear(self):
        self._d.clear()
        self._freq.clear()
        self._added.clear()

    def __len__(self):
        return len(self._d)


# ---------------------------------------------------------------------------
# Engine-facing runtime
# ---------------------------------------------------------------------------


class RecordTableRuntime:
    """Presents the InMemoryTable surface (insert / compiled-condition
    find / slot delete / slot update / contains) on top of a store SPI.

    "Slots" are positions in the most recent fetch; every mutating slot
    operation is translated back into a store condition (primary-key
    equality when a key is defined, full-row equality otherwise), which
    is how the reference maps chunk operations onto record stores.
    """

    def __init__(self, definition, store: AbstractRecordTable,
                 cache: Optional[TableCache] = None,
                 handler: Optional[RecordTableHandler] = None):
        from siddhi_tpu.query_api.annotation import find_annotation

        self.definition = definition
        self.table_id = definition.id
        self.store = store
        self.cache = cache
        self.handler = handler or RecordTableHandler()
        self._lock = threading.RLock()

        pk_ann = find_annotation(definition.annotations, "PrimaryKey")
        self.primary_keys: Optional[List[str]] = None
        if pk_ann is not None:
            self.primary_keys = [v for _, v in pk_ann.elements] or None
        self.indexes: Dict[str, Dict] = {}  # stores own their indexing

        names = definition.attribute_names
        self._names = list(names)
        # fetch staging area: last materialized find
        self._fetch_rows: List[list] = []

        # pre-built IR: match one row by primary key / by full row
        if self.primary_keys:
            self._row_ir = StoreAnd(tuple(
                StoreCompare(k, "==", StoreParam(k)) for k in self.primary_keys
            )) if len(self.primary_keys) > 1 else StoreCompare(
                self.primary_keys[0], "==", StoreParam(self.primary_keys[0]))
            self._row_params = list(self.primary_keys)
        else:
            self._row_ir = StoreAnd(tuple(
                StoreCompare(nm, "==", StoreParam(nm)) for nm in self._names
            ))
            self._row_params = list(self._names)

    # -- basics -------------------------------------------------------------

    def __len__(self) -> int:
        return len(self._find(StoreTrue(), {}))

    @property
    def size(self) -> int:
        return len(self)

    def _pk_key(self, row: list):
        vals = tuple(row[self._names.index(k)] for k in self.primary_keys)
        return vals[0] if len(vals) == 1 else vals

    def _row_param_map(self, row: list) -> Dict:
        return {k: row[self._names.index(k)] for k in self._row_params}

    def _find(self, ir, params: Dict) -> List[list]:
        return list(self.handler.on_find(ir, params, self.store.find))

    # -- engine surface ------------------------------------------------------

    def insert(self, batch: EventBatch):
        rows = [[_scalar(batch.columns[nm][i]) for nm in self._names]
                for i in range(len(batch))]
        if not rows:
            return
        with self._lock:
            self.handler.on_add(rows, self.store.add)
            if self.cache is not None and self.primary_keys:
                for r in rows:
                    self.cache.put(self._pk_key(r), r)

    def _insert_row(self, row: Dict, ts: int):
        """Single-row insert used by update-or-insert's miss branch
        (signature matches InMemoryTable._insert_row; callers hold
        self._lock).  Pk-duplicate rows replace via the store."""
        rec = [_scalar(row[nm]) for nm in self._names]
        if self.primary_keys:
            params = self._row_param_map(rec)
            if self.store.contains(self._row_ir, params):
                set_map = dict(zip(self._names, rec))
                self.handler.on_update(
                    self._row_ir, [params], [set_map], self.store.update)
                if self.cache is not None:
                    self.cache.put(self._pk_key(rec), rec)
                return
        self.handler.on_add([rec], self.store.add)
        if self.cache is not None and self.primary_keys:
            self.cache.put(self._pk_key(rec), rec)

    def live_slots(self) -> np.ndarray:
        with self._lock:
            self._fetch_rows = self._find(StoreTrue(), {})
            return np.arange(len(self._fetch_rows), dtype=np.int64)

    def fetch_matching(self, ir, params: Dict, pk_probe_key=None) -> np.ndarray:
        """Run a store find (through the cache for primary-key probes),
        stage the rows, and return their slot ids."""
        with self._lock:
            if pk_probe_key is not None and self.cache is not None:
                row = self.cache.get(pk_probe_key)
                if row is not None:
                    self._fetch_rows = [row]
                    return np.arange(1, dtype=np.int64)
            rows = self._find(ir, params)
            if pk_probe_key is not None and self.cache is not None and len(rows) == 1:
                self.cache.put(pk_probe_key, rows[0])
            self._fetch_rows = rows
            return np.arange(len(rows), dtype=np.int64)

    def rows_batch(self, slots: Optional[np.ndarray] = None) -> EventBatch:
        with self._lock:
            if slots is None:
                self.live_slots()
                slots = np.arange(len(self._fetch_rows), dtype=np.int64)
            rows = [self._fetch_rows[int(s)] for s in slots]
            types = [a.type for a in self.definition.attributes]
            cols = {
                nm: np.asarray([r[i] for r in rows],
                               dtype=types[i].np_dtype)
                for i, nm in enumerate(self._names)
            }
            return EventBatch(self.table_id, self._names, cols,
                              np.zeros(len(rows), dtype=np.int64))

    def column_env(self, slots: np.ndarray) -> Dict[str, np.ndarray]:
        b = self.rows_batch(slots)
        return {TBL + nm: b.columns[nm] for nm in self._names}

    def delete_slots(self, slots):
        with self._lock:
            rows = [self._fetch_rows[int(s)] for s in slots]
            if not rows:
                return
            params_list = [self._row_param_map(r) for r in rows]
            self.handler.on_delete(self._row_ir, params_list, self.store.delete)
            if self.cache is not None and self.primary_keys:
                for r in rows:
                    self.cache.invalidate(self._pk_key(r))

    def update_slots(self, slots, values: Dict[str, list]):
        with self._lock:
            rows = [self._fetch_rows[int(s)] for s in slots]
            if not rows:
                return
            params_list = [self._row_param_map(r) for r in rows]
            set_maps = [
                {attr: _scalar(np.asarray(vals)[j]) for attr, vals in values.items()}
                for j in range(len(rows))
            ]
            self.handler.on_update(self._row_ir, params_list, set_maps, self.store.update)
            if self.cache is not None and self.primary_keys:
                # A set clause that rewrites a primary-key attribute moves
                # the row to a NEW key: invalidating only the pre-update key
                # would leave any cached entry under the destination key
                # stale (update-or-insert probes would keep serving it).
                pk_rewrite = any(k in values for k in self.primary_keys)
                for j, r in enumerate(rows):
                    self.cache.invalidate(self._pk_key(r))
                    if pk_rewrite:
                        merged = [set_maps[j].get(nm, r[i])
                                  for i, nm in enumerate(self._names)]
                        self.cache.invalidate(self._pk_key(merged))

    def contains_fn(self, attr_hint: Optional[str] = None) -> Callable:
        if self.primary_keys and len(self.primary_keys) == 1:
            probe = self.primary_keys[0]
        elif len(self._names) == 1:
            probe = self._names[0]
        elif attr_hint is not None:
            probe = attr_hint
        else:
            raise SiddhiAppCreationError(
                f"'IN {self.table_id}': table needs a single-attribute primary key"
            )
        ir = StoreCompare(probe, "==", StoreParam("v"))

        def member(values) -> np.ndarray:
            vals = np.atleast_1d(np.asarray(values))
            return np.asarray(
                [self.store.contains(ir, {"v": _scalar(v)}) for v in vals], dtype=bool
            )

        return member

    # -- lifecycle / snapshot ------------------------------------------------

    def start(self):
        self.store.connect()

    def shutdown(self):
        self.store.disconnect()

    def snapshot(self) -> Dict:
        # external stores own their data; nothing to checkpoint in-engine
        return {}

    def restore(self, state: Dict):
        pass


class RecordCompiledCondition:
    """Compiled condition against a record table: store push-down IR +
    exact vectorized post-filter (reference:
    AbstractQueryableRecordTable.compileCondition)."""

    def __init__(self, table: RecordTableRuntime, condition: Optional[X.Expression],
                 event_scope: Scope, extra_functions=None, table_resolver=None):
        self.table = table
        scope = _merge_table_scope(event_scope, table)
        compiler = ExpressionCompiler(scope, functions=extra_functions,
                                      table_resolver=table_resolver)
        event_compiler = ExpressionCompiler(event_scope, functions=extra_functions,
                                            table_resolver=table_resolver)
        self._predicate: Optional[CompiledExpression] = None
        self._ir = StoreTrue()
        self._param_exprs: Dict[str, CompiledExpression] = {}
        self._pk_param_of_attr: Dict[str, str] = {}
        if condition is None:
            return
        self._predicate = compiler.compile(condition)
        if self._predicate.type != AttrType.BOOL:
            raise SiddhiAppCreationError("'on' condition must be boolean")
        builder = _StoreConditionBuilder(
            table.table_id, table.definition.attribute_names, event_compiler
        )
        self._ir = builder.build(condition)
        self._param_exprs = builder.params
        # detect full-primary-key equality probe for the cache path
        if table.primary_keys:
            eq = self._pk_equalities(self._ir)
            if eq is not None and all(k in eq for k in table.primary_keys):
                self._pk_param_of_attr = {k: eq[k] for k in table.primary_keys}

    def _pk_equalities(self, ir) -> Optional[Dict[str, object]]:
        """attr -> StoreParam/StoreConst for top-level '==' conjuncts."""
        out: Dict[str, object] = {}

        def walk(node) -> bool:
            if isinstance(node, StoreAnd):
                return all(walk(p) for p in node.parts)
            if isinstance(node, StoreCompare) and node.op == "==":
                out[node.attr] = node.rhs
                return True
            return isinstance(node, StoreTrue)

        return out if walk(self._ir) else None

    def slots_matching(self, event_env: Dict) -> np.ndarray:
        table = self.table
        if self._predicate is None:
            return table.live_slots()
        params = {
            k: _scalar(np.asarray(e.fn(event_env)).reshape(()))
            for k, e in self._param_exprs.items()
        }
        pk_key = None
        if self._pk_param_of_attr:
            vals = []
            for k in table.primary_keys:
                rhs = self._pk_param_of_attr[k]
                vals.append(rhs.value if isinstance(rhs, StoreConst) else params[rhs.key])
            pk_key = vals[0] if len(vals) == 1 else tuple(vals)
        cand = table.fetch_matching(self._ir, params, pk_probe_key=pk_key)
        if len(cand) == 0:
            return cand
        env = dict(event_env)
        env.update(table.column_env(cand))
        m = np.broadcast_to(np.asarray(self._predicate.fn(env)), (len(cand),))
        return cand[m]
