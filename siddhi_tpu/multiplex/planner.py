"""Multiplex placement: seat eligible queries in shared device engines.

``MultiplexPlanner`` rides the two ``@app:execution('tpu')`` gates in
``planner/query_planner.py``: before the dedicated dense / device-query
paths run, an ``@app:multiplex`` app first tries to seat the query in a
manager-wide shared engine keyed by structural fingerprint
(``fingerprint.py``).  Success wires a per-tenant adapter runtime
(``tumbling_group.py`` / ``dense_group.py``) behind the exact same
QueryRuntime surface the dedicated paths build, so selectors, output
callbacks, statistics and snapshots are indistinguishable downstream.

Every ineligibility is COUNTED, not silent: the reason lands on
``StatisticsManager.record_multiplex_fallback`` (REST:
``multiplexFallbackReason``) and the planner falls through to the
dedicated engine, so behavior degrades to PR-parity rather than
failing the app.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from siddhi_tpu.core.exceptions import (
    DefinitionNotExistError,
    SiddhiAppCreationError,
)
from siddhi_tpu.core.query import QueryRuntime
from siddhi_tpu.multiplex.fingerprint import query_fingerprint, reads_clock
from siddhi_tpu.multiplex.registry import registry_for
from siddhi_tpu.query_api import (
    Attribute,
    Query,
    SingleInputStream,
    StreamDefinition,
    WindowHandler,
)

log = logging.getLogger("siddhi_tpu")

_TUMBLING_WINDOWS = ("lengthBatch", "timeBatch")


class MultiplexPlanner:
    """Attempts multiplex placement for one query; ``None`` = fall back."""

    def __init__(self, qp):
        self.qp = qp  # the owning QueryPlanner
        self.app = qp.app
        self.ctx = qp.app.app_context

    # -- shared helpers ------------------------------------------------------

    def _fallback(self, name: str, reason: str) -> None:
        sm = self.ctx.statistics_manager
        if sm is not None:
            sm.record_multiplex_fallback(name, reason)
        # WARN, not info: @app:multiplex was requested and this query is
        # not getting it — same visibility contract as every other
        # planner fallback
        log.warning("query '%s': multiplex ineligible (%s); dedicated "
                    "engine used", name, reason)
        return None

    def _common_reject(self, query: Query, name: str) -> Optional[str]:
        """Eligibility conditions shared by both engine families."""
        if self.ctx.tpu_devices:
            if self.ctx.multiplex:
                # pinned @app:multiplex losing to the pinned mesh is a
                # plan CONFLICT (precedence: shard > multiplex), counted
                # separately from ordinary shape ineligibility
                sm = self.ctx.statistics_manager
                if sm is not None:
                    sm.record_planner_conflict(
                        name, "@app:multiplex pinned but the app declares "
                        "a device mesh (precedence: shard > multiplex)")
            return "mesh-sharded state does not multiplex"
        if query.output_rate is not None:
            return "output rate limits need a dedicated engine"
        out = query.output_stream
        if out is not None and getattr(out, "event_type", "current") != "current":
            return "multiplexed engines emit CURRENT events only"
        clock_fn = reads_clock(query)
        if clock_fn is not None:
            # these compile against the engine's relative-time anchor,
            # which a shared group re-bases across tenants
            return f"{clock_fn}() reads the engine clock anchor"
        return None

    # -- tumbling windowed aggregates ---------------------------------------

    def try_single(self, query: Query, name: str,
                   s: SingleInputStream) -> Optional[QueryRuntime]:
        """Seat a tumbling windowed-aggregate query in a shared
        :class:`~siddhi_tpu.multiplex.tumbling_group.TumblingMultiplexGroup`;
        ``None`` (with a counted reason) falls back to the dedicated
        ``_plan_device_single`` / host path."""
        from siddhi_tpu.multiplex.tumbling_group import TumblingMultiplexGroup
        from siddhi_tpu.ops.device_query import DeviceQueryEngine

        reason = self._common_reject(query, name)
        if reason is not None:
            return self._fallback(name, reason)
        if not (s.is_inner or s.is_fault):
            if s.stream_id in self.app.named_windows:
                return self._fallback(
                    name, "named-window inputs need CURRENT+EXPIRED "
                    "semantics")
            if s.stream_id in self.app.tables or s.stream_id in getattr(
                    self.app, "aggregations", {}):
                return self._fallback(
                    name, "table/aggregation inputs need the host planner")
        window = next((h for h in s.handlers
                       if isinstance(h, WindowHandler)), None)
        if window is None or window.name not in _TUMBLING_WINDOWS or (
                window.namespace or "") != "":
            return self._fallback(
                name, "only tumbling lengthBatch/timeBatch windows "
                "multiplex")

        definition = self.app.resolve_stream_definition(s)
        slots = int(self.ctx.multiplex_slots)
        fp = query_fingerprint(
            query, [definition],
            {"family": "tumbling",
             "n_groups": self.ctx.tpu_partitions,
             "slots": slots})

        def factory():
            engine = DeviceQueryEngine(
                query, definition,
                n_groups=self.ctx.tpu_partitions,
                partition_mode=False,
                defer_order_by=True,
            )
            if engine.kind != "tumbling":
                raise SiddhiAppCreationError(
                    "engine lowered to a non-tumbling form")
            return TumblingMultiplexGroup(engine, slots)

        registry = registry_for(self.ctx.siddhi_context)
        try:
            group, slot = registry.acquire(fp, factory)
        except SiddhiAppCreationError as e:
            return self._fallback(name, str(e))
        try:
            return self._wire_single(query, name, s, group, slot, registry)
        except BaseException:
            registry.release(group, slot)
            raise

    def _wire_single(self, query: Query, name: str, s: SingleInputStream,
                     group, slot: int, registry) -> QueryRuntime:
        from siddhi_tpu.core.device_single import _DeviceQueryReceiver
        from siddhi_tpu.multiplex.tumbling_group import MultiplexTenantRuntime

        engine = group.engine
        out_target = getattr(query.output_stream, "target", None) or f"__ret_{name}"
        out_attrs = [
            Attribute(nm, t)
            for nm, t in zip(engine.output_names, engine.out_types)
        ]
        selector = self.qp._passthrough_selector(
            query.selector, engine.output_names, out_target)
        out_def = StreamDefinition(id=out_target, attributes=out_attrs)
        output = self.qp._plan_output(query, out_def)
        rate_limiter = self.qp._plan_rate_limiter(query)
        qr = QueryRuntime(
            name, [[]], selector, rate_limiter, output, self.ctx)
        runtime = MultiplexTenantRuntime(
            group, slot, f"#device_{name}",
            emit=lambda b: qr.process(b, 0),
            clock=self.ctx.timestamp_generator.current_time,
            faults=self.ctx.fault_injector,
            registry=registry)
        qr.device_runtime = runtime
        junction = self.app.junction_for_input(s)
        junction.subscribe(_DeviceQueryReceiver(runtime))
        # registered LAST (same contract as the dedicated paths): nothing
        # below may raise, so fallbacks never leak a live scheduler task
        self.app.scheduler.register_task(runtime)
        qr.lowered_to = "multiplex"
        self._record_placement(name, group)
        return qr

    # -- dense patterns ------------------------------------------------------

    def try_state(self, query: Query, name: str, st) -> Optional[QueryRuntime]:
        """Seat an unpartitioned non-aggregating pattern query in a shared
        :class:`~siddhi_tpu.multiplex.dense_group.DenseMultiplexGroup`
        (one partition row per tenant); ``None`` falls back to the
        dedicated ``_plan_dense_state`` / host path."""
        from siddhi_tpu.core.dense_pattern import (
            build_dense_engine,
            output_attr_types,
        )
        from siddhi_tpu.multiplex.dense_group import DenseMultiplexGroup

        reason = self._common_reject(query, name)
        if reason is not None:
            return self._fallback(name, reason)
        sel = query.selector
        if sel.group_by or sel.having is not None or \
                self.qp._has_aggregators(sel):
            return self._fallback(
                name, "aggregating pattern selectors keep per-query host "
                "state")
        defs: List[StreamDefinition] = []
        for sid in sorted(set(st.stream_ids())):
            d = self.app.definitions.get(sid)
            if d is None:
                return self._fallback(
                    name, f"input stream '{sid}' has no groupable "
                    "definition")
            defs.append(d)

        slots = int(self.ctx.multiplex_slots)
        fp = query_fingerprint(
            query, defs,
            {"family": "dense",
             "instances": self.ctx.tpu_instances,
             "slots": slots})

        def factory():
            # one partition row per tenant seat: the dedicated path runs
            # unpartitioned patterns with n_partitions=1, so row t is the
            # bit-identical single-row automaton of tenant t
            engine = build_dense_engine(
                query, st, self.app.resolve_stream_definition,
                n_partitions=slots,
                n_instances=self.ctx.tpu_instances)
            if getattr(engine, "has_deadlines", False):
                raise SiddhiAppCreationError(
                    "absent-pattern deadlines need per-query timers")
            return DenseMultiplexGroup(
                engine, [t.np_dtype for t in output_attr_types(engine)],
                slots)

        registry = registry_for(self.ctx.siddhi_context)
        try:
            group, slot = registry.acquire(fp, factory)
        except SiddhiAppCreationError as e:
            return self._fallback(name, str(e))
        try:
            return self._wire_state(query, name, group, slot, registry)
        except BaseException:
            registry.release(group, slot)
            raise

    def _wire_state(self, query: Query, name: str, group, slot: int,
                    registry) -> QueryRuntime:
        from siddhi_tpu.core.dense_pattern import (
            _DenseStreamReceiver,
            output_attr_types,
        )
        from siddhi_tpu.multiplex.dense_group import DenseMultiplexTenantRuntime

        engine = group.engine
        out_target = getattr(query.output_stream, "target", None) or f"__ret_{name}"
        out_names = engine.output_names
        out_attrs = [
            Attribute(nm, t)
            for nm, t in zip(out_names, output_attr_types(engine))
        ]
        selector = self.qp._passthrough_selector(
            query.selector, out_names, out_target)
        out_def = StreamDefinition(id=out_target, attributes=out_attrs)
        output = self.qp._plan_output(query, out_def)
        rate_limiter = self.qp._plan_rate_limiter(query)
        qr = QueryRuntime(
            name, [[]], selector, rate_limiter, output, self.ctx)
        runtime = DenseMultiplexTenantRuntime(
            group, slot, f"#matches_{name}",
            emit=lambda b: qr.process(b, 0),
            clock=self.ctx.timestamp_generator.current_time,
            faults=self.ctx.fault_injector,
            registry=registry)
        qr.pattern_processor = runtime
        for sk in engine.stream_keys:
            junction = self.app.junctions.get(sk)
            if junction is None:
                raise DefinitionNotExistError(
                    f"stream '{sk}' is not defined")
            junction.subscribe(_DenseStreamReceiver(runtime, sk))
        self.app.scheduler.register_task(runtime)
        qr.lowered_to = "multiplex"
        self._record_placement(name, group)
        return qr

    def _record_placement(self, name: str, group) -> None:
        sm = self.ctx.statistics_manager
        if sm is not None and hasattr(sm, "record_multiplex_placement"):
            sm.record_multiplex_placement(
                name, getattr(group, "fingerprint", ""),
                group.occupied_count())
        log.info(
            "query '%s': multiplexed into shared engine %s (%d/%d seats)",
            name, getattr(group, "fingerprint", "?")[:12],
            group.occupied_count(), group.slots)
