"""Multi-tenant engine multiplexing: pack many apps' compatible queries
into shared device engines.

Production traffic is thousands of small SiddhiApps, not one giant
query.  Dedicated lowering gives every query its own jitted engine, so
a mesh serving 1k tenants pays 1k dispatches, 1k compile-cache entries
and 1k tiny batches per step.  This package stacks a TENANT axis onto
the existing device state layouts so one jitted step serves every
compatible tenant at once — the CAMA idea (arXiv 2112.00267: many
automata packed into shared state arrays) applied to both device-query
accumulator rows and dense-NFA partition rows:

- ``fingerprint``: canonical structural hash of a query (pattern
  skeleton / window kind + size, aggregator set, dtype lanes, filter
  constants, relevant ``@app:execution`` knobs) — two queries multiplex
  iff their fingerprints are equal, which guarantees the FIRST tenant's
  compiled engine is exactly the engine every member would have
  compiled.
- ``registry``: manager-level ``MultiplexRegistry`` (one per
  ``SiddhiManager``, held on ``SiddhiContext`` so it survives app
  crashes) mapping fingerprint -> open groups with free tenant slots.
- ``tumbling_group``: ``TumblingMultiplexGroup`` packs N tenants'
  tumbling-window accumulator rows into one ``[T*G, ...]`` row bank of
  a shared :class:`~siddhi_tpu.ops.device_query.DeviceQueryEngine`;
  one batched accumulate step per staging cycle.
- ``dense_group``: ``DenseMultiplexGroup`` gives each tenant one
  partition row of a shared
  :class:`~siddhi_tpu.ops.dense_nfa.DensePatternEngine`; T tenants'
  events collapse from T single-event collision rounds into rounds of
  T partition-disjoint events.
- ``planner``: ``MultiplexPlanner``, hooked from
  ``planner/query_planner.py`` inside the ``@app:execution('tpu')``
  gates — tries a group seat first and falls back to the dedicated
  engines with a counted ``multiplexFallbackReason``.

Activation is opt-in per app: ``@app:multiplex()`` (optionally
``slots='N'``, the per-group tenant capacity, default 8).  Per-tenant
fault isolation, Emit/IngestStats and snapshot/restore ride the
adapters (`MultiplexTenantRuntime` / `DenseMultiplexTenantRuntime`),
which present the same runtime surface as the dedicated
``DeviceQueryRuntime`` / ``DensePatternRuntime`` so barriers, stats
wiring and crash recovery work unchanged.
"""

from siddhi_tpu.multiplex.fingerprint import query_fingerprint, reads_clock
from siddhi_tpu.multiplex.registry import MultiplexRegistry
from siddhi_tpu.multiplex.planner import MultiplexPlanner

__all__ = [
    "MultiplexPlanner",
    "MultiplexRegistry",
    "query_fingerprint",
    "reads_clock",
]
