"""Shared dense-NFA device engine for N compatible pattern tenants.

The dense engine is ALREADY batched over a partition axis — the
multiplex group simply makes that axis the tenant axis: one
:class:`~siddhi_tpu.ops.dense_nfa.DensePatternEngine` is built with
``n_partitions = slots`` and tenant ``t`` owns partition row ``t``
(every event of tenant ``t`` routes there; the scratch row at index
``slots`` keeps absorbing pad lanes).  Eligible queries are
unpartitioned, so each dedicated engine would have run its whole
stream through one state row anyway — the packed layout is the same
automaton replicated per tenant, and per-row arithmetic is identical,
so match sets are bit-identical.

The win: T dedicated engines dispatch T jitted steps per batch cycle,
and an unpartitioned dedicated engine degenerates to one COLLISION
ROUND PER EVENT (every event shares partition row 0).  The group
concatenates the staged sub-batches tenant-major — partitions are
disjoint across tenants — so each collision round now carries up to T
events, collapsing ``sum(n_i)`` rounds into ``max(n_i)`` rounds of one
shared step.

Timestamps are anchored to ONE group ``base_ts`` (min over the first
dispatch − 1).  `within` checks compare per-partition relative
differences, so the shared anchor is invisible per tenant; a late
tenant whose events predate the anchor triggers a group-wide host
down-shift via ``engine.shift_row_ts`` (rare), and the int32-horizon
re-anchor rides the engine's own ``maybe_re_anchor`` over the combined
batch.

Matches come back through the count-gated emit queue: zero-match
dispatch cycles transfer nothing; a non-empty match set is fetched
once (coalesced) and demultiplexed back to per-tenant callback queues
by splitting the ev-index-major match rows at the tenant-major batch
offsets.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from siddhi_tpu.core import event as ev
from siddhi_tpu.core.emit_queue import EmitQueue, EmitStats, PendingEmit
from siddhi_tpu.core.event import EventBatch
from siddhi_tpu.core.exceptions import SiddhiAppRuntimeError, TransferFaultError
from siddhi_tpu.core.ingest_stage import IngestStats
from siddhi_tpu.multiplex.common import retry_guard
from siddhi_tpu.util import faults as _faults

log = logging.getLogger(__name__)


class _DenseSeat:
    __slots__ = ("slot", "adapter", "staged", "pending_out", "last_good")

    def __init__(self, slot: int):
        self.slot = slot
        self.adapter = None
        self.staged = None  # (stream_key, cols, ts, now)
        self.pending_out = deque()  # (out_cols, out_ts, now)
        self.last_good = None  # {key: host rows [1, ...]}


class DenseMultiplexGroup:
    """One dense engine, ``slots`` tenants on the partition axis."""

    fingerprint = ""

    def __init__(self, engine, out_dtypes: List[np.dtype], slots: int):
        self.engine = engine
        self.slots = int(slots)
        self._out_dtypes = out_dtypes
        self.lock = threading.RLock()
        self.seats: List[Optional[_DenseSeat]] = [None] * self.slots
        self._free = list(range(self.slots - 1, -1, -1))
        self.ingest_stats = IngestStats()
        self.emit_stats = EmitStats()
        engine.ingest_stats = self.ingest_stats
        engine.faults = None  # per-tenant injection lives in the adapters
        self.emit_queue = EmitQueue(depth=1, stats=self.emit_stats,
                                    faults=None, on_fault=None)
        self.state = engine.init_state()
        self._init_host = engine.init_state_host()
        self.dispatches = 0
        self.combined_steps = 0
        self._ovf_warned = 0

    # -- seat lifecycle ----------------------------------------------------

    def try_alloc_seat(self) -> Optional[int]:
        with self.lock:
            if not self._free:
                return None
            slot = self._free.pop()
            self.seats[slot] = _DenseSeat(slot)
            return slot

    def bind(self, slot: int, adapter) -> None:
        with self.lock:
            self.seats[slot].adapter = adapter

    def free_seat(self, slot: int) -> None:
        with self.lock:
            seat = self.seats[slot]
            if seat is None:
                return
            # parity with DensePatternRuntime.close(): short-lived
            # tenants still surface dropped-instance warnings
            self._check_overflow()
            self.seats[slot] = None
            self._free.append(slot)
            jnp = self.engine.jnp
            self.state = {
                k: self.state[k].at[slot:slot + 1].set(
                    jnp.asarray(self._init_host[k][slot:slot + 1]))
                for k in self.state
            }

    def occupied_count(self) -> int:
        with self.lock:
            return sum(1 for s in self.seats if s is not None)

    # -- staging + dispatch -------------------------------------------------

    def stage(self, adapter, stream_key: str, cols, ts: np.ndarray,
              now) -> None:
        with self.lock:
            seat = self.seats[adapter.slot]
            if seat.staged is not None:
                # a second sub-batch (same or other source stream) must
                # observe the first's transitions: dispatch in between
                self._dispatch_locked()
            seat.staged = (stream_key, cols, ts, now)
            adapter.ingest_stats.staged_batches += 1
            adapter.ingest_stats.note_depth(1)
            if all(s is None or s.staged is not None for s in self.seats):
                self._dispatch_locked()

    def dispatch_staged(self) -> None:
        with self.lock:
            self._dispatch_locked()

    def _dispatch_locked(self) -> None:
        staged = [s for s in self.seats if s is not None and s.staged is not None]
        if not staged:
            return
        eng = self.engine
        by_stream: Dict[str, list] = {}
        for seat in staged:
            stream_key, cols, ts, now = seat.staged
            seat.staged = None
            by_stream.setdefault(stream_key, []).append((seat, cols, ts, now))
        self._anchor_base(by_stream)
        self.dispatches += 1
        for stream_key, items in by_stream.items():
            self._dispatch_stream(stream_key, items)
        for seat in staged:
            if seat.adapter is not None:
                seat.adapter.ingest_stats.device_puts += 1
            self._poison_guard(seat)
        # matches must be host-visible before tenants deliver: drain the
        # count-gated queue (zero-match cycles transferred nothing)
        self.emit_queue.drain()
        if self.dispatches % 256 == 0:
            self._check_overflow()

    def _anchor_base(self, by_stream) -> None:
        eng = self.engine
        ts_min = min(int(ts.min())
                     for items in by_stream.values()
                     for _s, _c, ts, _n in items)
        if eng.base_ts is None:
            eng.base_ts = ts_min - 1
        elif ts_min - eng.base_ts <= 0:
            # late tenant with events older than the group anchor:
            # shift the shared base down so relative ts stay positive
            # (host round trip; rare — admission-time skew only)
            delta = (ts_min - eng.base_ts) - 1
            host = {k: np.asarray(v) for k, v in self.state.items()}
            host = eng.shift_row_ts(host, delta)
            jnp = eng.jnp
            self.state = {k: jnp.asarray(v) for k, v in host.items()}
            eng.base_ts += delta

    def _dispatch_stream(self, stream_key: str, items) -> None:
        """ONE engine dispatch for every tenant staged on this source
        stream: tenant-major concat with each tenant's events routed to
        its own partition row."""
        eng = self.engine
        cat_cols = {
            k: np.concatenate([np.asarray(cols[k]) for _s, cols, _t, _n in items])
            for k in items[0][1]
        }
        cat_ts = np.concatenate([ts for _s, _c, ts, _n in items])
        part = np.concatenate([
            np.full(len(ts), seat.slot, dtype=np.int32)
            for seat, _c, ts, _n in items
        ])
        offsets = np.cumsum([0] + [len(ts) for _s, _c, ts, _n in items])
        self.state, pending = eng.process_deferred(
            self.state, stream_key, part, cat_cols, cat_ts)
        self.combined_steps += 1
        if pending is None or pending.resolve() == 0:
            self.emit_queue.skip()
            return
        seats = [seat for seat, _c, _t, _n in items]
        nows = [now for _s, _c, _t, now in items]
        self.emit_queue.push(PendingEmit(
            pending.device_arrays(),
            lambda host, p=pending, o=offsets, s=seats, t=cat_ts, n=nows:
                self._demux(p, host, o, s, t, n)))

    def _demux(self, pending, host_arrays, offsets, seats, cat_ts, nows):
        """Split the ev-index-sorted match rows back per tenant (the
        combined batch is tenant-major, so one searchsorted per seat)."""
        ev_idx, out = pending.materialize(host_arrays)
        if len(ev_idx) == 0:
            return
        eng = self.engine
        names = eng.output_names
        bounds = np.searchsorted(ev_idx, offsets)
        for si, seat in enumerate(seats):
            lo, hi = bounds[si], bounds[si + 1]
            if lo == hi:
                continue
            out_cols = {
                name: out[lo:hi, oi].astype(self._out_dtypes[oi])
                for oi, name in enumerate(names)
            }
            seat.pending_out.append(
                (out_cols, cat_ts[ev_idx[lo:hi]], nows[si]))

    # -- per-tenant fault isolation ----------------------------------------

    def _poison_guard(self, seat: _DenseSeat) -> None:
        adapter = seat.adapter
        fi = adapter.faults if adapter is not None else None
        if fi is None or not fi.watches("state.poison"):
            return
        t = seat.slot
        rows = {k: self.state[k][t:t + 1] for k in self.state}
        if fi.poisoned("state.poison"):
            rows = _faults.poison_state(rows)
            self.state = {
                k: self.state[k].at[t:t + 1].set(rows[k])
                for k in self.state
            }
        if not _faults.state_has_poison(rows):
            seat.last_good = _faults.host_copy(rows)
            return
        fi.stats.poison_quarantines += 1
        log.warning(
            "multiplex: poisoned state in dense tenant slot %d "
            "quarantined; restoring last known good rows", t)
        good = (seat.last_good if seat.last_good is not None
                else {k: v[t:t + 1] for k, v in self._init_host.items()})
        jnp = self.engine.jnp
        self.state = {
            k: self.state[k].at[t:t + 1].set(jnp.asarray(good[k]))
            for k in self.state
        }

    def _check_overflow(self) -> None:
        total = int(self.engine.jnp.sum(self.state["overflow"]))
        if total > self._ovf_warned:
            log.warning(
                "dense multiplex group: %d pending instance(s) dropped — "
                "instance lanes full; matches may be missing.  Raise "
                "@app:execution('tpu', instances='N') (current %d).",
                total, self.engine.I)
            self._ovf_warned = total

    # -- snapshot / restore -------------------------------------------------

    def snapshot_tenant(self, adapter) -> Dict:
        with self.lock:
            self._dispatch_locked()
            t = adapter.slot
            return {
                "dense_state": {k: np.asarray(v[t:t + 1])
                                for k, v in self.state.items()},
                "base_ts": self.engine.base_ts,
            }

    def restore_tenant(self, adapter, snap: Dict) -> None:
        eng = self.engine
        with self.lock:
            self._dispatch_locked()
            t = adapter.slot
            seat = self.seats[t]
            seat.pending_out.clear()
            seat.last_good = None
            rows = {k: np.asarray(v) for k, v in snap["dense_state"].items()}
            for k, ref in self._init_host.items():
                got = rows.get(k)
                want = (1,) + ref.shape[1:]
                if got is None or got.shape != want:
                    raise SiddhiAppRuntimeError(
                        f"cannot restore: tenant snapshot key '{k}' has "
                        f"shape {None if got is None else got.shape}, this "
                        f"group needs {want} (snapshot taken under a "
                        "different @app:execution/@app:multiplex setting)")
            b_snap = snap.get("base_ts")
            if eng.base_ts is None:
                eng.base_ts = b_snap
            elif b_snap is not None and b_snap != eng.base_ts:
                # the snapshot's relative anchors were taken against its
                # own base; re-express them against the group base
                rows = eng.shift_row_ts(rows, eng.base_ts - b_snap)
            jnp = eng.jnp
            self.state = {
                k: self.state[k].at[t:t + 1].set(jnp.asarray(rows[k]))
                for k in self.state
            }


class DenseMultiplexTenantRuntime:
    """One tenant's runtime over a shared :class:`DenseMultiplexGroup`.

    Presents the ``DensePatternRuntime`` surface the planner, scheduler
    barriers, app_runtime stats discovery and crash recovery expect
    (process_stream_batch / drain / fire / stats / snapshot / restore /
    close + emit/ingest stats)."""

    def __init__(self, group: DenseMultiplexGroup, slot: int,
                 out_stream_id: str, emit,
                 clock=None, faults=None, registry=None):
        self.group = group
        self.slot = slot
        self.engine = group.engine
        self.out_stream_id = out_stream_id
        self.emit_cb = emit
        self.clock = clock
        self.faults = faults
        self.registry = registry
        self.emit_stats = EmitStats()
        self.ingest_stats = IngestStats()
        self.step_invocations = 0
        self._closed = False
        group.bind(slot, self)

    # -- ingest -------------------------------------------------------------

    def process_stream_batch(self, stream_key: str, batch: EventBatch,
                             part=None, keys=None) -> None:
        cur = batch.only(ev.CURRENT)
        n = len(cur)
        if n == 0:
            return
        eng = self.engine
        cols = {}
        for a in eng.numeric_stream_attrs(stream_key):
            col = cur.columns.get(a)
            if col is not None:
                cols[a] = np.asarray(col)
        ts = np.asarray(cur.timestamps, dtype=np.int64)
        # per-tenant chaos hooks: the dedicated engine checks step.dense
        # once per batch and retries transient ingest.put transfers
        if self.faults is not None:
            self.faults.check("step.dense")
        retry_guard(self.faults, "ingest.put")
        now = self.clock() if self.clock is not None else None
        self.group.stage(self, stream_key, cols, ts, now)
        self.step_invocations += 1
        self._deliver_pending()

    # -- delivery -----------------------------------------------------------

    def _deliver_pending(self) -> None:
        while True:
            with self.group.lock:
                seat = self.group.seats[self.slot]
                if seat is None or not seat.pending_out:
                    return
                out_cols, out_ts, now = seat.pending_out.popleft()
            try:
                retry_guard(self.faults, "emit.drain")
            except TransferFaultError as e:
                self.faults.stats.drains_failed += 1
                self._on_fault(e)
                log.error("multiplex: emit drain failed for %s after "
                          "retries; dropping batch: %s",
                          self.out_stream_id, e)
                continue
            mb = EventBatch(
                self.out_stream_id, self.engine.output_names, out_cols,
                out_ts, np.full(len(out_ts), ev.CURRENT, dtype=np.int8))
            if now is not None:
                mb.aux["emit_now"] = now
            self.emit_stats.emit_transfers += 1
            self.emit_cb(mb)

    def _on_fault(self, e: BaseException) -> None:
        if self.faults is not None:
            self.faults.notify(e)

    # -- barriers / scheduler ----------------------------------------------

    def drain(self) -> None:
        self.group.dispatch_staged()
        self._deliver_pending()

    def next_wakeup(self) -> Optional[int]:
        with self.group.lock:
            seat = self.group.seats[self.slot]
            if seat is not None and (seat.staged is not None
                                     or seat.pending_out):
                return 0
        return None

    def fire(self, now: int) -> None:
        # group dispatch only for this tenant's own staged cycle; a fire
        # woken by pending_out just delivers (see the tumbling adapter)
        with self.group.lock:
            seat = self.group.seats[self.slot]
            mine_staged = seat is not None and seat.staged is not None
        if mine_staged:
            self.group.dispatch_staged()
        self._deliver_pending()

    def on_time(self, now: int) -> None:
        pass

    def on_start(self, now: int) -> None:
        pass

    def stats(self) -> Dict:
        active = np.asarray(self.group.state["active"])
        return {
            "engine": "dense-multiplex",
            "partitions_in_use": 1,
            "partition_capacity": 1,
            "instance_lanes": self.engine.I,
            "active_instances": int(active[self.slot].sum()),
            "dropped_instances": int(
                np.asarray(self.group.state["overflow"])[self.slot]),
            "step_invocations": self.step_invocations,
        }

    # -- persistence --------------------------------------------------------

    def snapshot(self) -> Dict:
        self.drain()
        return self.group.snapshot_tenant(self)

    def restore(self, state: Dict) -> None:
        self.drain()
        self.group.restore_tenant(self, state)

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self.drain()
        finally:
            if self.registry is not None:
                self.registry.release(self.group, self.slot)
            else:
                self.group.free_seat(self.slot)
