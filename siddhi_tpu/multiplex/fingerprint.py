"""Structural query fingerprints for multiplex grouping.

Two queries may share one device engine iff compiling either of them
would produce exactly the same jitted steps: same pattern skeleton /
window kind + size, same filter tree (constants included — they are
baked into the compiled expression), same aggregator set and output
lanes, same input stream attribute layout (names, types → dtype
lanes), and the same engine-shaping knobs (partitions / instances /
slot count).  The fingerprint is a sha256 over a canonical recursive
encoding of those parts; equality of fingerprints is the grouping key.

What is deliberately EXCLUDED so distinct apps can still group:
query name, app name, ``@info``/other annotations, and the output
stream TARGET (each tenant keeps its own output stream + callbacks;
only the output ``event_type`` shapes the engine).

The query_api tree is all plain dataclasses (``query_api/execution.py``,
``query_api/expression.py``) with no volatile derived fields stored, so
a ``dataclasses.fields()`` walk is canonical by construction.
"""

from __future__ import annotations

import dataclasses
import enum
import hashlib
import json
from typing import Iterable, Optional

from siddhi_tpu.query_api.execution import Query
from siddhi_tpu.query_api.expression import FunctionCall

# Builtins whose compiled value depends on the engine's private time
# anchor or the host clock at evaluation time (planner/expr.py lowers
# eventTimestamp() to the RELATIVE device timestamp lane, which is
# measured against the engine's base_ts — a shared group anchor would
# change the values a tenant observes vs its dedicated engine).
_CLOCK_FNS = frozenset({"eventTimestamp", "currentTimeMillis"})


def _canon(node):
    """Canonical JSON-encodable form of a query_api subtree."""
    if node is None or isinstance(node, (bool, int, float, str)):
        return node
    if isinstance(node, enum.Enum):
        return [type(node).__name__, node.name]
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        return [
            type(node).__name__,
            [
                [f.name, _canon(getattr(node, f.name))]
                for f in dataclasses.fields(node)
                if f.name != "annotations"
            ],
        ]
    if isinstance(node, (list, tuple)):
        return [_canon(x) for x in node]
    if isinstance(node, dict):
        return [[_canon(k), _canon(v)] for k, v in sorted(node.items(), key=repr)]
    # Unknown leaf (should not happen for query_api trees): fall back to
    # a type-tagged repr so it at least hashes deterministically.
    return [type(node).__name__, repr(node)]


def query_fingerprint(query: Query, definitions, knobs: dict) -> str:
    """sha256 hex fingerprint of ``query``'s engine-relevant shape.

    ``definitions`` is an iterable of the resolved input
    ``StreamDefinition`` objects (attribute names + types fix the dtype
    lanes); ``knobs`` carries the engine-shaping app knobs
    (partitions / instances / multiplex slots).
    """
    payload = {
        "input": _canon(query.input_stream),
        "selector": _canon(query.selector),
        "out_event_type": getattr(query.output_stream, "event_type", "current"),
        "defs": [_canon(d) for d in definitions],
        "knobs": sorted((str(k), str(v)) for k, v in knobs.items()),
    }
    blob = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


def reads_clock(node) -> Optional[str]:
    """Name of the first clock/anchor-reading builtin in the tree, or None.

    Expressions calling these cannot multiplex: their compiled value is
    relative to the engine's private ``base_ts`` anchor (or the host
    clock), which a shared group engine does not preserve per tenant.
    """
    if isinstance(node, FunctionCall) and node.name in _CLOCK_FNS:
        return node.name
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        it: Iterable = (
            getattr(node, f.name)
            for f in dataclasses.fields(node)
            if f.name != "annotations"
        )
    elif isinstance(node, (list, tuple)):
        it = node
    else:
        return None
    for child in it:
        hit = reads_clock(child)
        if hit is not None:
            return hit
    return None
