"""Manager-level registry of open multiplex groups.

One ``MultiplexRegistry`` lives per ``SiddhiManager`` (lazily created on
``SiddhiContext.multiplex_registry`` by the planner), because grouping
is CROSS-APP: distinct SiddhiApps created under one manager contribute
tenants to the same shared engines.  Holding it on the manager context
— like ``input_journals`` — also keeps groups alive across a single
app's crash/restore cycle, so the surviving tenants keep flowing.

Groups are keyed by structural fingerprint (``fingerprint.py``).  A
fingerprint maps to a LIST of groups: when every seat of the open
groups is taken, a fresh overflow group is spun up rather than
refusing the tenant.  Seats free on tenant shutdown; a group whose
last seat frees is dropped so its device state can be collected.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple


class MultiplexRegistry:
    """fingerprint -> open groups with free tenant slots."""

    def __init__(self):
        self._lock = threading.Lock()
        self._groups: Dict[str, List[object]] = {}
        # lifetime counters, surfaced by bench / tests
        self.groups_created = 0
        self.seats_placed = 0

    def acquire(self, fingerprint: str, factory: Callable[[], object]) -> Tuple[object, int]:
        """Seat a tenant in an open group for ``fingerprint``.

        Tries each existing group's ``try_alloc_seat()``; when all are
        full (or none exist) builds a fresh group via ``factory`` and
        seats the tenant there.  Returns ``(group, slot)``.
        """
        with self._lock:
            bucket = self._groups.setdefault(fingerprint, [])
            for group in bucket:
                slot = group.try_alloc_seat()
                if slot is not None:
                    self.seats_placed += 1
                    return group, slot
            group = factory()
            group.fingerprint = fingerprint
            slot = group.try_alloc_seat()
            if slot is None:  # a factory-built group always has a seat
                raise RuntimeError("multiplex: fresh group has no free seat")
            bucket.append(group)
            self.groups_created += 1
            self.seats_placed += 1
            return group, slot

    def release(self, group, slot: int) -> None:
        """Free ``slot`` of ``group``; drop the group once empty."""
        with self._lock:
            group.free_seat(slot)
            if group.occupied_count() == 0:
                bucket = self._groups.get(getattr(group, "fingerprint", ""), [])
                if group in bucket:
                    bucket.remove(group)

    def open_groups(self) -> List[object]:
        with self._lock:
            return [g for bucket in self._groups.values() for g in bucket]


def registry_for(siddhi_context) -> MultiplexRegistry:
    """The manager context's registry, created on first use."""
    reg: Optional[MultiplexRegistry] = getattr(siddhi_context, "multiplex_registry", None)
    if reg is None:
        reg = MultiplexRegistry()
        siddhi_context.multiplex_registry = reg
    return reg
