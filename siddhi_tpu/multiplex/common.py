"""Shared helpers for multiplex tenant adapters.

Fault semantics mirror the dedicated engines': each tenant keeps its
OWN ``FaultInjector`` (the group engine runs with ``faults=None``), so
a tenant's injected ingest/emit faults retry and exhaust exactly like
its dedicated runtime would — without ever stalling the other seats.
"""

from __future__ import annotations

import logging
import time

from siddhi_tpu.core.exceptions import TransferFaultError

log = logging.getLogger(__name__)


def retry_guard(fi, site: str) -> None:
    """Per-tenant transient-fault gate with the staged_put retry ladder.

    Checks ``site`` on the tenant's injector, retrying transient
    transfer faults with the same bounded backoff as
    ``core/ingest_stage.staged_put`` (attempts / scale from the
    injector's knobs).  Exhaustion re-raises, which the caller
    propagates out of that tenant's receive/drain path only.
    """
    if fi is None:
        return
    attempts = fi.transfer_retry_attempts
    attempt = 0
    backoff = None
    while True:
        try:
            fi.check(site)
            if attempt:
                fi.stats.drains_recovered += 1
            return
        except TransferFaultError:
            if attempt >= attempts:
                raise
            attempt += 1
            fi.stats.transfer_retries += 1
            if backoff is None:
                from siddhi_tpu.transport.retry import BackoffRetryCounter

                backoff = BackoffRetryCounter(scale=fi.transfer_retry_scale)
            wait_s = backoff.get_time_interval_ms() / 1000.0
            backoff.increment()
            log.warning(
                "multiplex: transient fault at %s (attempt %d/%d), "
                "retrying in %.3fs", site, attempt, attempts, wait_s)
            if wait_s > 0:
                time.sleep(wait_s)
