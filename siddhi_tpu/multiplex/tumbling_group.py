"""Shared tumbling-window device engine for N compatible tenants.

One :class:`~siddhi_tpu.ops.device_query.DeviceQueryEngine` — compiled
from the FIRST tenant's query, which the fingerprint guarantees is
byte-identical to what every member would have compiled — serves up to
``slots`` tenants.  The packed device state stacks the tenant axis onto
the group axis: every ``[G, ...]`` accumulator array becomes
``[T*G, ...]``, with tenant ``t`` owning rows ``[t*G, (t+1)*G)``.  The
engine's jitted accumulate step is shape-polymorphic in the group axis
(``G = state["grp_keys"].shape[0]``), so the SAME compiled step runs
over the packed bank — group ids are simply offset by ``t*G`` and the
overflow/dump row moves to ``T*G``.

Host-side pane bookkeeping (group interning tables, pane anchor/fill,
last emitted keys) is PER TENANT: each seat owns a full copy, and a
``_borrow`` context swaps it onto the engine's attributes under the
group lock so the engine's own host machinery (``_intern_groups``,
``_pane_sweep``, ``_flush_cols``, ``_concat_chunks``, ``flush_due``
mirror, ``host_snapshot``/``host_restore``) runs verbatim against the
calling tenant's view.  Only ``base_ts`` — the int32 relative-time
anchor — is shared group-wide; pane anchors are stored relative to it,
and all emitted timestamps are absolute (``base + rel``), so sharing
the anchor is invisible in tenant output.

The hot path: each tenant stages at most one sub-batch; when every
occupied seat has staged (or a barrier / re-stage forces it) the group
concatenates the sub-batches tenant-major, offsets group ids, adds a
tenant-id lane, and dispatches ONE jitted accumulate over the shared
``staged_put`` ingest path — T tenants, one device step.  Per-tenant
pane fills come back as a ``[T]`` count vector from the same step.
Sub-batches that would close a pane (or overflow a lengthBatch pane)
take the engine's exact ``_pane_sweep`` slow path against the packed
state instead, so flush ordering inside the batch matches the
dedicated engine bit for bit.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional

import numpy as np

from siddhi_tpu.core import event as ev
from siddhi_tpu.core.emit_queue import EmitStats
from siddhi_tpu.core.event import EventBatch
from siddhi_tpu.core.exceptions import SiddhiAppRuntimeError, TransferFaultError
from siddhi_tpu.core.ingest_stage import IngestStats
from siddhi_tpu.multiplex.common import retry_guard
from siddhi_tpu.util import faults as _faults

log = logging.getLogger(__name__)


class _TenantSeat:
    """Per-tenant host state: interning tables, pane bookkeeping, the
    staged sub-batch, pending host-side outputs, and the last known
    clean device rows (poison quarantine restore point)."""

    __slots__ = (
        "slot", "adapter", "gids", "gvals", "gfree", "glast",
        "pane_end", "pane_fill", "prev_pane_fill", "last_group_keys",
        "staged", "pending_out", "last_good",
    )

    def __init__(self, slot: int):
        self.slot = slot
        self.adapter = None
        self.gids: Dict = {}
        self.gvals: List = []
        self.gfree: List[int] = []
        self.glast: Dict[int, int] = {}
        self.pane_end: Optional[int] = None
        self.pane_fill = 0
        self.prev_pane_fill = 0
        self.last_group_keys: Optional[List] = None
        self.staged = None  # (cols, ts, now) or None
        self.pending_out = deque()  # (out_cols, out_ts, keys, now)
        self.last_good = None  # {key: host rows [G, ...]}


class TumblingMultiplexGroup:
    """Packed [T*G] tumbling accumulator bank shared by up to ``slots``
    structurally identical queries."""

    fingerprint = ""

    def __init__(self, engine, slots: int):
        self.engine = engine
        self.slots = int(slots)
        self.G = int(engine.n_groups)
        self.lock = threading.RLock()
        self.seats: List[Optional[_TenantSeat]] = [None] * self.slots
        self._free = list(range(self.slots - 1, -1, -1))
        # group-wide ingest stats: staged_put counts every combined put
        self.ingest_stats = IngestStats()
        engine.ingest_stats = self.ingest_stats
        engine.faults = None  # fault injection is per tenant, not group
        self._init_host = engine.init_state_host()  # [G, ...] reference
        jnp = engine.jnp
        self.state = {
            k: jnp.asarray(np.tile(v, (self.slots,) + (1,) * (v.ndim - 1)))
            for k, v in self._init_host.items()
        }
        self.base_ts: Optional[int] = None
        # dispatch counters (bench + differential tests)
        self.dispatches = 0       # device dispatch cycles
        self.combined_steps = 0   # one-step-for-all-fast-seats dispatches
        self.slow_steps = 0       # per-tenant pane-sweep dispatches
        self.flush_skips = 0      # empty-pane flushes skipped device-side
        self._mux_acc = self._build_mux_acc()

    # -- seat lifecycle ----------------------------------------------------

    def try_alloc_seat(self) -> Optional[int]:
        with self.lock:
            if not self._free:
                return None
            slot = self._free.pop()
            self.seats[slot] = _TenantSeat(slot)
            return slot

    def bind(self, slot: int, adapter) -> None:
        with self.lock:
            self.seats[slot].adapter = adapter

    def free_seat(self, slot: int) -> None:
        with self.lock:
            seat = self.seats[slot]
            if seat is None:
                return
            self.seats[slot] = None
            self._free.append(slot)
            # reset the freed rows so a future occupant starts clean
            off = slot * self.G
            jnp = self.engine.jnp
            self.state = {
                k: self.state[k].at[off:off + self.G].set(
                    jnp.asarray(self._init_host[k]))
                for k in self.state
            }

    def occupied_count(self) -> int:
        with self.lock:
            return sum(1 for s in self.seats if s is not None)

    # -- host-bookkeeping borrow -------------------------------------------

    @contextmanager
    def _borrow(self, seat: _TenantSeat):
        """Swap ``seat``'s host bookkeeping onto the engine so the
        engine's own pane/intern/flush machinery runs against this
        tenant's view.  Caller must hold the group lock."""
        eng = self.engine
        eng._group_ids = seat.gids
        eng._group_vals = seat.gvals
        eng._group_free = seat.gfree
        eng._group_last = seat.glast
        eng._pane_end = seat.pane_end
        eng._pane_fill = seat.pane_fill
        eng._prev_pane_fill = seat.prev_pane_fill
        eng.last_group_keys = seat.last_group_keys
        eng.base_ts = self.base_ts
        try:
            yield eng
        finally:
            # capture rebinds too (host_restore replaces the dicts)
            seat.gids = eng._group_ids
            seat.gvals = eng._group_vals
            seat.gfree = eng._group_free
            seat.glast = eng._group_last
            seat.pane_end = eng._pane_end
            seat.pane_fill = eng._pane_fill
            seat.prev_pane_fill = eng._prev_pane_fill
            seat.last_group_keys = eng.last_group_keys

    # -- jitted combined accumulate ----------------------------------------

    def _build_mux_acc(self):
        eng = self.engine
        jnp = eng.jnp
        raw = eng.make_acc_step(jit=False)
        slots = self.slots

        def _mux(state, c, t, g, gkv, valid, tid):
            st2, _ = raw(state, c, t, g, gkv, valid)
            # recompute the filter mask per row (XLA CSEs this against
            # the accumulate) and bucket passing counts by tenant lane;
            # pad rows carry tid == slots and fall into the dump bucket.
            fmask = eng._filter_mask(eng._base_env(c, t, t.shape[0]), valid)
            counts = jnp.zeros((slots + 1,), jnp.int32).at[tid].add(
                fmask.astype(jnp.int32))
            return st2, counts[:slots]

        return eng.jax.jit(_mux, donate_argnums=(0,))

    # -- staging + dispatch -------------------------------------------------

    def stage(self, adapter, cols, ts: np.ndarray, now) -> None:
        """Stage one tenant sub-batch; dispatch when the cycle is full
        (every occupied seat staged) or this tenant re-stages."""
        with self.lock:
            seat = self.seats[adapter.slot]
            if seat.staged is not None:
                self._dispatch_locked()
            seat.staged = (cols, ts, now)
            adapter.ingest_stats.staged_batches += 1
            adapter.ingest_stats.note_depth(1)
            if all(s is None or s.staged is not None for s in self.seats):
                self._dispatch_locked()

    def dispatch_staged(self) -> None:
        """Barrier: dispatch whatever is staged (drain/fire/snapshot)."""
        with self.lock:
            self._dispatch_locked()

    def _dispatch_locked(self) -> None:
        staged = [s for s in self.seats if s is not None and s.staged is not None]
        if not staged:
            return
        eng = self.engine
        batches = []
        for seat in staged:
            cols, ts, now = seat.staged
            seat.staged = None
            batches.append((seat, cols, ts, now))
        self._anchor_base(batches)
        self.dispatches += 1

        fast, slow = [], []
        for seat, cols, ts, now in batches:
            n = len(ts)
            rel = (ts - self.base_ts).astype(np.int32)
            with self._borrow(seat):
                grp = eng._intern_groups(cols, ts, n)
            entry = self._classify(seat, cols, rel, grp, n)
            (fast if entry[0] else slow).append((seat, cols, ts, rel, grp, n, now, entry))

        if fast:
            self._dispatch_fast(fast)
        for item in slow:
            self._dispatch_slow(item)
        for seat, _c, _t, _r, _g, _n, _now, _e in fast + slow:
            if seat.adapter is not None:
                seat.adapter.ingest_stats.device_puts += 1
            self._poison_guard(seat)

    def _anchor_base(self, batches) -> None:
        """Establish / shift the shared relative-time anchor.

        Pane anchors are stored relative to ``base_ts`` and every
        emitted timestamp is absolute, so shifting the base (down for a
        late tenant with older events, up at the int32 horizon exactly
        like the dedicated ``_re_anchor``) moves every seat's
        ``pane_end`` by the opposite delta and changes nothing a tenant
        can observe."""
        eng = self.engine
        ts_min = min(int(ts.min()) for _s, _c, ts, _n in batches)
        ts_max = max(int(ts.max()) for _s, _c, ts, _n in batches)
        if self.base_ts is None:
            self.base_ts = ts_min - 1
            return
        delta = 0
        if ts_min - self.base_ts <= 0:
            delta = (ts_min - self.base_ts) - 1  # negative: shift down
        elif ts_max - self.base_ts >= eng._REL_LIMIT:
            horizon = int(eng.window_param) if eng.window_name == "timeBatch" else 0
            delta = (ts_min - self.base_ts) - 1 - horizon
            if delta <= 0 or (ts_max - self.base_ts) - delta >= 2**31:
                raise SiddhiAppRuntimeError(
                    "device query: timestamp span of one batch plus the "
                    "window horizon exceeds the int32 relative-time range")
        if delta:
            self.base_ts += delta
            for s in self.seats:
                if s is not None and s.pane_end is not None:
                    s.pane_end -= delta

    def _classify(self, seat: _TenantSeat, cols, rel, grp, n):
        """Fast-path eligibility: the sub-batch must not close a pane.

        Returns ``(fast, npass_host)``.  The timeBatch pane anchor is
        committed here exactly as ``_pane_sweep`` would (first passing
        batch pins ``pane_end = rel[0] + T``)."""
        eng = self.engine
        if eng.window_name == "timeBatch":
            if seat.pane_end is None:
                seat.pane_end = int(rel[0]) + int(eng.window_param)
                seat.pane_fill = 0
                seat.prev_pane_fill = 0
            return (int(rel.max()) < seat.pane_end, None)
        # lengthBatch: pane closes when passing events reach L
        with self._borrow(seat):
            fmask = eng._host_filter_mask(cols, rel, n)
        npass = int(np.count_nonzero(fmask))
        remaining = int(eng.window_param) - seat.pane_fill
        return (npass < remaining, npass)

    def _dispatch_fast(self, fast) -> None:
        """ONE jitted accumulate for every pane-interior sub-batch:
        tenant-major concat, group ids offset by slot*G, tenant-id lane
        for the per-seat passing counts."""
        eng = self.engine
        jnp = eng.jnp
        K = max(len(eng._numeric_group_keys), 1)
        cat_cols = {
            k: np.concatenate([np.asarray(cols[k])[:n] for _s, cols, _t, _r, _g, n, _now, _e in fast])
            for k in fast[0][1]
        }
        cat_rel = np.concatenate([rel[:n] for _s, _c, _t, rel, _g, n, _now, _e in fast])
        cat_grp = np.concatenate([
            (grp[:n] + seat.slot * self.G).astype(np.int32)
            for seat, _c, _t, _r, grp, n, _now, _e in fast
        ])
        gkv_parts, tid_parts = [], []
        for seat, _cols, _ts, _rel, grp, n, _now, _entry in fast:
            with self._borrow(seat):
                gkv_parts.append(eng._gk_vals(grp[:n], n))
            tid_parts.append(np.full(n, seat.slot, dtype=np.int32))
        ntot = len(cat_rel)
        c, t, g, _wg, valid, B = eng._pad(cat_cols, cat_rel, cat_grp, ntot)
        gkv = np.zeros((B, K), dtype=np.float32)
        gkv[:ntot] = np.concatenate(gkv_parts)
        tid = np.full(B, self.slots, dtype=np.int32)
        tid[:ntot] = np.concatenate(tid_parts)
        self.state, counts = self._mux_acc(
            self.state, c, t, g, jnp.asarray(gkv), valid, jnp.asarray(tid))
        self.combined_steps += 1
        counts_h = np.asarray(eng.jax.device_get(counts))
        for seat, _cols, _ts, _rel, _grp, _n, _now, entry in fast:
            # timeBatch mirrors the dedicated device-count derivation;
            # lengthBatch mirrors its host fmask count
            npass = entry[1]
            seat.pane_fill += int(counts_h[seat.slot]) if npass is None else npass

    def _dispatch_slow(self, item) -> None:
        """Pane-closing sub-batch: run the engine's exact
        ``_pane_sweep`` against this tenant's packed rows."""
        seat, cols, ts, rel, grp, n, now, _entry = item
        eng = self.engine
        self.slow_steps += 1
        chunks = []

        def acc_segment(state, cols_, rel_, grp_, idx):
            return self._acc_rows(seat, state, cols_, rel_, grp_, idx)

        def flush_pane(st, when):
            st, fcols, nf, keys = self._flush_slice(st, seat)
            chunks.append((fcols, when, nf, keys))
            return st

        with self._borrow(seat):
            self.state = eng._pane_sweep(
                self.state, cols, rel, grp, n, acc_segment, flush_pane)
            out_cols, out_ts = eng._concat_chunks(chunks)
        if len(out_ts):
            seat.pending_out.append(
                (out_cols, out_ts, seat.last_group_keys, now))

    def _acc_rows(self, seat: _TenantSeat, state, cols, rel, grp, idx):
        """``_acc_segment`` against the packed bank: device group ids
        offset by slot*G, group-key values from the tenant's LOCAL ids
        (the borrow is active — ``_gk_vals`` reads the seat tables)."""
        eng = self.engine
        acc = eng.make_acc_step()
        n = len(idx)
        c, t, g, _wg, valid, B = eng._pad(
            {k: np.asarray(v)[idx] for k, v in cols.items()},
            rel[idx], (grp[idx] + seat.slot * self.G).astype(np.int32), n)
        gkv = np.zeros((B, max(len(eng._numeric_group_keys), 1)),
                       dtype=np.float32)
        gkv[:n] = eng._gk_vals(grp[idx], n)
        state, n_pass = acc(state, c, t, g, eng.jnp.asarray(gkv), valid)
        return state, int(eng.jax.device_get(n_pass))

    # -- flush --------------------------------------------------------------

    def _flush_slice(self, state, seat: _TenantSeat):
        """Flush the tenant's [G] row slice.  A pane with zero passing
        events left every accumulator at its reset value (misses
        scatter identity values and dump into the dropped row), so the
        device dispatch is skipped entirely — same state, no output.
        timeBatch only: its fill count is final at flush time, while
        lengthBatch increments AFTER the closing flush (and only ever
        closes full panes anyway)."""
        eng = self.engine
        if eng.window_name == "timeBatch" and eng._pane_fill == 0:
            self.flush_skips += 1
            return (state, eng._empty_cols(), 0,
                    [] if eng.group_exprs else None)
        off = seat.slot * self.G
        sl = {k: state[k][off:off + self.G] for k in state}
        sl, fcols, nf, keys = eng._flush_cols(sl)
        state = {k: state[k].at[off:off + self.G].set(sl[k]) for k in state}
        return state, fcols, nf, keys

    def flush_due_for(self, adapter, now: int) -> None:
        """Timer flush for one tenant: mirror of ``engine.flush_due``
        over the tenant's row slice (caller dispatched staged first)."""
        eng = self.engine
        with self.lock:
            seat = self.seats[adapter.slot]
            chunks = []
            with self._borrow(seat):
                while True:
                    w = eng.pane_wakeup()
                    if w is None or w > now:
                        break
                    self.state, fcols, nf, keys = self._flush_slice(
                        self.state, seat)
                    chunks.append((fcols, w, nf, keys))
                    eng._advance_pane()
                out_cols, out_ts = eng._concat_chunks(chunks)
            if len(out_ts):
                seat.pending_out.append(
                    (out_cols, out_ts, seat.last_group_keys, now))

    def pane_wakeup_for(self, adapter) -> Optional[int]:
        with self.lock:
            seat = self.seats[adapter.slot]
            if seat is None:
                return None
            with self._borrow(seat):
                return self.engine.pane_wakeup()

    # -- per-tenant fault isolation ----------------------------------------

    def _poison_guard(self, seat: _TenantSeat) -> None:
        """Quarantine a poisoned tenant's rows without touching the
        other seats — the packed-bank analog of
        ``DeviceQueryRuntime._poison_guard``."""
        adapter = seat.adapter
        fi = adapter.faults if adapter is not None else None
        if fi is None or not fi.watches("state.poison"):
            return
        eng = self.engine
        off = seat.slot * self.G
        rows = {k: self.state[k][off:off + self.G] for k in self.state}
        if fi.poisoned("state.poison"):
            rows = _faults.poison_state(rows)
            self.state = {
                k: self.state[k].at[off:off + self.G].set(rows[k])
                for k in self.state
            }
        if not _faults.state_has_poison(rows):
            seat.last_good = _faults.host_copy(rows)
            return
        fi.stats.poison_quarantines += 1
        log.warning(
            "multiplex: poisoned state in tenant slot %d quarantined; "
            "restoring last known good rows", seat.slot)
        good = seat.last_good if seat.last_good is not None else self._init_host
        jnp = eng.jnp
        self.state = {
            k: self.state[k].at[off:off + self.G].set(jnp.asarray(good[k]))
            for k in self.state
        }

    # -- snapshot / restore -------------------------------------------------

    def snapshot_tenant(self, adapter) -> Dict:
        """Dedicated-shape snapshot of one tenant (device rows [G,...] +
        host bookkeeping), interchangeable with a dedicated runtime's."""
        with self.lock:
            self._dispatch_locked()
            seat = self.seats[adapter.slot]
            off = adapter.slot * self.G
            dev = {k: np.asarray(self.state[k][off:off + self.G])
                   for k in self.state}
            with self._borrow(seat):
                host = self.engine.host_snapshot()
            return {"device_state": dev, "host": host}

    def restore_tenant(self, adapter, snap: Dict) -> None:
        eng = self.engine
        with self.lock:
            self._dispatch_locked()
            seat = self.seats[adapter.slot]
            seat.pending_out.clear()
            seat.last_good = None
            dev = snap["device_state"]
            for k, ref in self._init_host.items():
                got = dev.get(k)
                if got is None or tuple(np.shape(got)) != ref.shape:
                    raise SiddhiAppRuntimeError(
                        f"restored device state key '{k}' has shape "
                        f"{None if got is None else tuple(np.shape(got))}, "
                        f"engine expects {ref.shape}")
            off = adapter.slot * self.G
            jnp = eng.jnp
            self.state = {
                k: self.state[k].at[off:off + self.G].set(jnp.asarray(dev[k]))
                for k in self.state
            }
            with self._borrow(seat):
                eng.host_restore(snap["host"])
                # the snapshot's pane anchor is relative to ITS base;
                # re-express it against the group's shared base
                b_snap = eng.base_ts
                if self.base_ts is None:
                    self.base_ts = b_snap
                elif b_snap is not None and eng._pane_end is not None:
                    eng._pane_end += b_snap - self.base_ts


class MultiplexTenantRuntime:
    """One tenant's runtime over a shared :class:`TumblingMultiplexGroup`.

    Presents the same surface as ``core/device_single.DeviceQueryRuntime``
    (process_stream_batch / drain / fire / next_wakeup / snapshot /
    restore / emit+ingest stats), so planner wiring, scheduler barriers,
    statistics discovery and crash recovery treat it identically."""

    def __init__(self, group: TumblingMultiplexGroup, slot: int,
                 out_stream_id: str, emit,
                 clock=None, faults=None, registry=None):
        self.group = group
        self.slot = slot
        self.engine = group.engine
        self.out_stream_id = out_stream_id
        self.emit_cb = emit
        self.clock = clock
        self.faults = faults
        self.registry = registry
        self.emit_stats = EmitStats()
        self.ingest_stats = IngestStats()
        self.step_invocations = 0
        self._closed = False
        group.bind(slot, self)

    # -- ingest -------------------------------------------------------------

    def process_stream_batch(self, batch: EventBatch, keys=None) -> None:
        cur = batch.only(ev.CURRENT)
        n = len(cur)
        if n == 0:
            return
        eng = self.engine
        cols = {a: np.asarray(cur.columns[a]) for a in eng.all_attrs
                if a in cur.columns}
        ts = np.asarray(cur.timestamps, dtype=np.int64)
        # per-tenant transient ingest faults retry/exhaust here, before
        # any group state is touched — a failing tenant never wedges
        # the shared engine
        retry_guard(self.faults, "ingest.put")
        now = self.clock() if self.clock is not None else None
        self.group.stage(self, cols, ts, now)
        self.step_invocations += 1
        self._deliver_pending()

    # -- delivery -----------------------------------------------------------

    def _deliver_pending(self) -> None:
        """Emit this tenant's demultiplexed outputs OUTSIDE the group
        lock (lock order is app -> group, never group -> app)."""
        while True:
            with self.group.lock:
                seat = self.group.seats[self.slot]
                if seat is None or not seat.pending_out:
                    return
                out_cols, out_ts, gkeys, now = seat.pending_out.popleft()
            try:
                retry_guard(self.faults, "emit.drain")
            except TransferFaultError as e:
                self.faults.stats.drains_failed += 1
                self._on_fault(e)
                log.error("multiplex: emit drain failed for %s after "
                          "retries; dropping batch: %s",
                          self.out_stream_id, e)
                continue
            self._emit(out_cols, out_ts, gkeys, now)

    def _emit(self, out_cols, out_ts, keys, now) -> None:
        if len(out_ts) == 0:
            return
        eng = self.engine
        mb = EventBatch(
            self.out_stream_id, eng.output_names, out_cols, out_ts,
            np.full(len(out_ts), ev.CURRENT, dtype=np.int8))
        if keys is not None:
            if len(keys) != len(mb):
                raise SiddhiAppRuntimeError(
                    f"device query emitted {len(mb)} rows but "
                    f"{len(keys)} group keys")
            mb.aux["group_keys"] = list(keys)
        if now is not None:
            mb.aux["emit_now"] = now
        self.emit_stats.emit_transfers += 1
        self.emit_cb(mb)

    def _on_fault(self, e: BaseException) -> None:
        if self.faults is not None:
            self.faults.notify(e)

    # -- barriers / scheduler ----------------------------------------------

    def drain(self) -> None:
        self.group.dispatch_staged()
        self._deliver_pending()

    def next_wakeup(self) -> Optional[int]:
        with self.group.lock:
            seat = self.group.seats[self.slot]
            if seat is None:
                return None
            if seat.staged is not None or seat.pending_out:
                return 0
        return self.group.pane_wakeup_for(self)

    def fire(self, now: int) -> None:
        # dispatch the group only when THIS tenant's seat is staged (its
        # previous cycle — a re-send or a processing-time tick must not
        # leave it parked).  A fire woken purely by pending_out would
        # otherwise flush OTHER tenants' half-staged cycles through the
        # slow path and defeat the packing (each app runs its own
        # scheduler, so these fires interleave mid-cycle).
        with self.group.lock:
            seat = self.group.seats[self.slot]
            mine_staged = seat is not None and seat.staged is not None
        if mine_staged:
            self.group.dispatch_staged()
        self.group.flush_due_for(self, now)
        self._deliver_pending()

    def on_start(self, now: int) -> None:
        pass

    def on_time(self, now: int) -> None:
        pass

    # -- persistence --------------------------------------------------------

    def snapshot(self) -> Dict:
        self.drain()
        return self.group.snapshot_tenant(self)

    def restore(self, state: Dict) -> None:
        self.drain()
        self.group.restore_tenant(self, state)

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self.drain()
        finally:
            if self.registry is not None:
                self.registry.release(self.group, self.slot)
            else:
                self.group.free_seat(self.slot)
