"""Pattern/sequence NFA engine — host semantics.

Re-design of the reference chain-of-processors NFA
(query/input/stream/state/: StreamPreStateProcessor.java:46,
StreamPostStateProcessor.java:64, LogicalPreStateProcessor.java:33,
CountPreStateProcessor.java:34, AbsentStreamPreStateProcessor.java:35;
planner StateInputStreamParser.java:73).

The state-element tree lowers to a linear chain of nodes (stream /
logical / absent, with count ranges).  Partial matches are Instance
objects; semantics were pinned against the reference TestNG corpus
(SequenceTestCase, EveryPatternTestCase, CountPatternTestCase):

- pattern mode: non-matching events are ignored; instances persist.
- sequence mode: an event an instance cannot use kills it (strict
  continuity); the start node is kept armed; only one pending per state.
- `every` groups re-arm a fresh instance at the group start (keeping
  captures of nodes before the group) when the group's last node first
  completes; overlapping instances for single-state groups.
- count nodes <min:max> capture greedily; once count >= min the instance
  is also pending on the following node(s) (epsilon closure over
  zero-min nodes); advancing clones the instance, the original keeps
  capturing while below max.
- non-every patterns/sequences stop after the first emitted match
  (all instances killed).
- `within t` drops partial matches older than t.
- absent nodes (`not X for t`) complete via scheduler deadline; a
  matching X before the deadline kills the instance.

This engine is the correctness reference; the dense vectorized TPU path
(ops/dense_nfa.py) handles the partitioned high-throughput subset and is
validated against this one.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from siddhi_tpu.core import event as ev
from siddhi_tpu.core.event import EventBatch
from siddhi_tpu.core.exceptions import SiddhiAppCreationError
from siddhi_tpu.planner.expr import (
    CompiledExpression,
    ExpressionCompiler,
    Scope,
    N_KEY,
    TS_KEY,
)
from siddhi_tpu.query_api import (
    AbsentStreamStateElement,
    AttrType,
    CountStateElement,
    EveryStateElement,
    Filter,
    LogicalStateElement,
    NextStateElement,
    StateElement,
    StateInputStream,
    StreamStateElement,
    Variable,
)
from siddhi_tpu.query_api.definition import StreamDefinition

ANY = CountStateElement.ANY  # -1 == unbounded


# ---------------------------------------------------------------------------
# Lowered NFA structure
# ---------------------------------------------------------------------------


@dataclass
class Spec:
    """One event-capturing sub-state."""

    ref: str
    stream_key: str  # junction key
    stream_def: StreamDefinition = None
    filter_compiled: Optional[CompiledExpression] = None
    # env entries the filter needs: key -> (ref, idx|None, attr) for captured
    filter_capture_keys: Dict[str, Tuple[str, Optional[int], str]] = field(default_factory=dict)
    # presence-check keys: key -> (ref, idx)
    filter_presence_keys: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    is_absent: bool = False
    waiting_ms: Optional[int] = None
    # un-compiled filter expression (re-compiled by the dense engine
    # against register slots)
    raw_filter: object = None


@dataclass
class Node:
    pos: int
    kind: str  # 'stream' | 'logical' | 'absent'
    specs: List[Spec] = field(default_factory=list)
    logical_op: Optional[str] = None  # 'and' | 'or'
    min_count: int = 1
    max_count: int = 1  # ANY == unbounded
    # `every` re-arm: when this node first completes, arm a fresh instance
    # at node `rearm_to` keeping captures of nodes < rearm_to
    rearm_to: Optional[int] = None


class Instance:
    __slots__ = (
        "pos", "captured", "count", "matched_sides", "violated",
        "first_ts", "enter_ts", "deadline", "emitted_at_node", "alive",
    )

    def __init__(self, pos: int, enter_ts: int):
        self.pos = pos
        self.captured: Dict[str, List[dict]] = {}
        self.count = 0  # captures at current node
        self.matched_sides: Set[int] = set()  # logical progress
        self.violated = False
        self.first_ts: Optional[int] = None
        self.enter_ts = enter_ts
        self.deadline: Optional[int] = None  # absent node deadline
        self.emitted_at_node: Set[int] = set()
        self.alive = True

    def clone(self) -> "Instance":
        c = Instance(self.pos, self.enter_ts)
        c.captured = {k: list(v) for k, v in self.captured.items()}
        c.count = self.count
        c.matched_sides = set(self.matched_sides)
        c.violated = self.violated
        c.first_ts = self.first_ts
        c.deadline = self.deadline
        c.emitted_at_node = set(self.emitted_at_node)
        return c

    def is_virgin(self) -> bool:
        return self.pos == 0 and not self.captured and self.count == 0


def _extract(captured: Dict[str, List[dict]], ref: str, idx: Optional[int], attr: str, attr_type: AttrType):
    rows = captured.get(ref)
    if not rows:
        row = None
    else:
        i = 0 if idx is None else (len(rows) + idx if idx < 0 else idx)
        row = rows[i] if 0 <= i < len(rows) else None
    if row is None:
        # null representation: NaN for numerics, None for objects
        if attr_type in (AttrType.FLOAT, AttrType.DOUBLE, AttrType.INT, AttrType.LONG):
            return math.nan
        return None
    return row.get(attr)


# ---------------------------------------------------------------------------
# Filter scope: resolves pattern variables, recording needed env keys
# ---------------------------------------------------------------------------


class PatternScope(Scope):
    """Scope over pattern event refs.  ``cand_ref`` names the spec whose
    candidate event is being filtered (bare attributes resolve to it);
    None for the selector scope (bare attrs resolve when unambiguous)."""

    def __init__(
        self,
        ref_defs: Dict[str, StreamDefinition],
        stream_to_ref: Dict[str, Optional[str]],
        cand_def: Optional[StreamDefinition] = None,
        cand_ref: Optional[str] = None,
    ):
        super().__init__()
        self.ref_defs = ref_defs
        self.stream_to_ref = stream_to_ref
        self.cand_def = cand_def
        self.cand_ref = cand_ref
        # recorded needs: key -> (ref, idx|None, attr, AttrType)
        self.used_captures: Dict[str, Tuple[str, Optional[int], str, AttrType]] = {}

    def _ref_for(self, stream_id: str) -> Optional[str]:
        if stream_id in self.ref_defs:
            return stream_id
        if stream_id in self.stream_to_ref:
            r = self.stream_to_ref[stream_id]
            if r is None:
                raise SiddhiAppCreationError(
                    f"stream '{stream_id}' matches several pattern states; use event references"
                )
            return r
        return None

    def resolve(self, var: Variable):
        if var.stream_id is None:
            # synthetic bare names first (aggregation outputs, select aliases)
            hit = self._bare.get(var.attribute)
            if hit is not None:
                return hit
            if self.cand_def is not None and var.attribute in self.cand_def.attribute_names:
                t = self.cand_def.attribute_type(var.attribute)
                return "__cand." + var.attribute, t
            # unambiguous across refs?
            hits = [
                (r, d.attribute_type(var.attribute))
                for r, d in self.ref_defs.items()
                if var.attribute in d.attribute_names
            ]
            if len(hits) == 1:
                r, t = hits[0]
                key = f"{r}.{var.attribute}"
                self.used_captures[key] = (r, None, var.attribute, t)
                return key, t
            raise SiddhiAppCreationError(
                f"cannot resolve attribute '{var.attribute}' in pattern scope"
                + (" (ambiguous)" if len(hits) > 1 else "")
            )
        if (
            self.cand_ref is not None
            and var.stream_id == self.cand_ref
            and var.stream_index is None
            and self.cand_def is not None
            and var.attribute in self.cand_def.attribute_names
        ):
            # a state's own ref inside its own filter is the INCOMING
            # event (reference: ExpressionParser resolves the current
            # state's ref to the candidate, e.g.
            # `e2=S[e1.symbol==e2.symbol]` — CountPatternTestCase.testQuery13)
            return "__cand." + var.attribute, self.cand_def.attribute_type(var.attribute)
        ref = self._ref_for(var.stream_id)
        if ref is None:
            raise SiddhiAppCreationError(
                f"unknown event reference '{var.stream_id}' in pattern"
            )
        d = self.ref_defs[ref]
        t = d.attribute_type(var.attribute)
        if var.stream_index is None:
            key = f"{ref}.{var.attribute}"
            self.used_captures[key] = (ref, None, var.attribute, t)
        else:
            key = f"{ref}[{var.stream_index}].{var.attribute}"
            self.used_captures[key] = (ref, var.stream_index, var.attribute, t)
        return key, t


# ---------------------------------------------------------------------------
# Lowering: StateElement tree -> node chain
# ---------------------------------------------------------------------------


def flatten_chain(element: StateElement) -> List[StateElement]:
    """Right-nested NextStateElement chain -> ordered element list."""
    out: List[StateElement] = []

    def walk(e: StateElement):
        if isinstance(e, NextStateElement):
            walk(e.element)
            walk(e.next)
        else:
            out.append(e)

    walk(element)
    return out


class NFABuilder:
    """Lowers a StateInputStream to the node chain + compiled filters."""

    def __init__(self, state_input: StateInputStream, resolve_def: Callable[[object], StreamDefinition]):
        self.state_input = state_input
        self.resolve_def = resolve_def
        self.ref_defs: Dict[str, StreamDefinition] = {}
        self.stream_to_ref: Dict[str, Optional[str]] = {}
        self.ref_counts: Dict[str, Tuple[int, int]] = {}  # ref -> (min,max)
        self.nodes: List[Node] = []
        self._anon = 0

    def build(self) -> List[Node]:
        elements = flatten_chain(self.state_input.state)
        # handle `every` at any chain position: group members tracked
        plan: List[Tuple[StateElement, Optional[int]]] = []  # (elem, group_start_pos)
        pos = 0
        for el in elements:
            if isinstance(el, EveryStateElement):
                inner = flatten_chain(el.element)
                start = pos
                for sub in inner:
                    plan.append((sub, None))
                    pos += 1
                # mark last node of the group for re-arming
                plan[-1] = (plan[-1][0], start)
            else:
                plan.append((el, None))
                pos += 1

        # pass 1: register refs so filters can reference later-declared
        # streams of earlier states only (reference behaves the same)
        for el, _ in plan:
            self._register_refs(el)

        for i, (el, rearm) in enumerate(plan):
            node = self._lower_element(el, i)
            node.rearm_to = rearm
            self.nodes.append(node)
        return self.nodes

    # -- ref registration ----------------------------------------------------

    def _reg(self, sse: StreamStateElement) -> str:
        ref = sse.event_ref
        if ref is None:
            ref = f"__s{self._anon}"
            self._anon += 1
            sse.event_ref = ref
        d = self.resolve_def(sse.stream)
        self.ref_defs[ref] = d
        sid = sse.stream.stream_id
        if sid in self.stream_to_ref and self.stream_to_ref[sid] != ref:
            self.stream_to_ref[sid] = None  # ambiguous
        elif sid not in self.stream_to_ref:
            self.stream_to_ref[sid] = ref
        return ref

    def _register_refs(self, el: StateElement):
        if isinstance(el, CountStateElement):
            self._reg(el.stream_state)
        elif isinstance(el, LogicalStateElement):
            for side in (el.element1, el.element2):
                if isinstance(side, (StreamStateElement,)):
                    self._reg(side)
                elif isinstance(side, CountStateElement):
                    self._reg(side.stream_state)
        elif isinstance(el, StreamStateElement):  # incl. Absent
            self._reg(el)
        else:
            raise SiddhiAppCreationError(f"unsupported state element {type(el).__name__}")

    # -- lowering ------------------------------------------------------------

    def _make_spec(self, sse: StreamStateElement) -> Spec:
        d = self.resolve_def(sse.stream)
        prefix = "#" if sse.stream.is_inner else ("!" if sse.stream.is_fault else "")
        spec = Spec(
            ref=sse.event_ref,
            stream_key=prefix + sse.stream.stream_id,
            stream_def=d,
            is_absent=isinstance(sse, AbsentStreamStateElement),
            waiting_ms=getattr(sse, "waiting_time_ms", None),
        )
        # compile pre-filters ANDed together
        filters = [h.expression for h in sse.stream.handlers if isinstance(h, Filter)]
        if len(sse.stream.handlers) != len(filters):
            raise SiddhiAppCreationError("only [filter] handlers are supported in pattern states")
        if filters:
            from siddhi_tpu.query_api import AndOp, IsNullStream

            expr = filters[0]
            for f in filters[1:]:
                expr = AndOp(expr, f)
            scope = PatternScope(self.ref_defs, self.stream_to_ref, cand_def=d,
                                 cand_ref=sse.event_ref)
            compiler = ExpressionCompiler(scope)
            spec.raw_filter = expr
            spec.filter_compiled = compiler.compile(expr)
            spec.filter_capture_keys = {
                k: (r, i, a) for k, (r, i, a, _t) in scope.used_captures.items()
            }
            self._capture_types = getattr(self, "_capture_types", {})
            for k, (r, i, a, t) in scope.used_captures.items():
                self._capture_types[k] = t
            # presence keys for IsNullStream nodes
            spec.filter_presence_keys = _collect_presence(expr, self.ref_defs, self.stream_to_ref)
        return spec

    def _lower_element(self, el: StateElement, pos: int) -> Node:
        if isinstance(el, CountStateElement):
            spec = self._make_spec(el.stream_state)
            return Node(
                pos=pos, kind="stream", specs=[spec],
                min_count=el.min_count,
                max_count=el.max_count,
            )
        if isinstance(el, LogicalStateElement):
            sides = []
            for side in (el.element1, el.element2):
                if isinstance(side, CountStateElement):
                    raise SiddhiAppCreationError("count states inside logical and/or are not supported")
                sides.append(self._make_spec(side))
            if el.operator == "or" and any(s.is_absent for s in sides):
                if any(s.is_absent and s.waiting_ms is None for s in sides):
                    # `not B or C` without a 'for' window can never
                    # complete via the absent branch; the reference only
                    # supports the timed race (`not B for t or C`)
                    raise SiddhiAppCreationError(
                        "'or' with an absent state needs a 'for' duration")
                if all(s.is_absent for s in sides):
                    # two racing absences share one deadline register and
                    # one violation kill — not representable
                    raise SiddhiAppCreationError(
                        "'or' of two absent states is not supported")
            return Node(pos=pos, kind="logical", specs=sides, logical_op=el.operator)
        if isinstance(el, AbsentStreamStateElement):
            spec = self._make_spec(el)
            return Node(pos=pos, kind="absent", specs=[spec])
        if isinstance(el, StreamStateElement):
            spec = self._make_spec(el)
            return Node(pos=pos, kind="stream", specs=[spec])
        raise SiddhiAppCreationError(f"unsupported state element {type(el).__name__}")

    def capture_type(self, key: str) -> AttrType:
        return getattr(self, "_capture_types", {}).get(key, AttrType.OBJECT)


def _collect_presence(expr, ref_defs, stream_to_ref) -> Dict[str, Tuple[str, int]]:
    from siddhi_tpu.query_api import (
        AndOp, ArithmeticOp, CompareOp, FunctionCall, InOp, IsNull,
        IsNullStream, NotOp, OrOp,
    )

    out: Dict[str, Tuple[str, int]] = {}

    def walk(e):
        if isinstance(e, IsNullStream):
            ref = e.stream_id if e.stream_id in ref_defs else stream_to_ref.get(e.stream_id)
            if ref is None:
                raise SiddhiAppCreationError(f"unknown event reference '{e.stream_id}'")
            idx = e.stream_index if e.stream_index is not None else 0
            out[f"__present.{e.stream_id}[{idx}]"] = (ref, idx)
        elif isinstance(e, (AndOp, OrOp)):
            walk(e.left)
            walk(e.right)
        elif isinstance(e, NotOp):
            walk(e.expr)
        elif isinstance(e, IsNull):
            walk(e.expr)
        elif isinstance(e, (ArithmeticOp, CompareOp)):
            walk(e.left)
            walk(e.right)
        elif isinstance(e, FunctionCall):
            for a in e.args:
                walk(a)
        elif isinstance(e, InOp):
            walk(e.expr)

    walk(expr)
    return out


# ---------------------------------------------------------------------------
# Runtime engine
# ---------------------------------------------------------------------------


class PatternProcessor:
    """Executes the lowered NFA over incoming events.

    Instances MOVE off a node once it can accept no more events
    (count == max); an in-progress count node (min <= count < max) is
    dually pending: it can capture more events AND spawn an advancing
    clone when an event matches a successor (the reference's shared
    linked-list forwarding, CountPreStateProcessor).

    ``emit(match_batch)`` receives a columnar batch whose columns are the
    capture keys requested by the planner (e.g. ``e1.price``).
    """

    def __init__(
        self,
        nodes: List[Node],
        mode: str,  # 'pattern' | 'sequence'
        within_ms: Optional[int],
        ref_defs: Dict[str, StreamDefinition],
        # output spec: key -> (ref, idx|None, attr, AttrType)
        output_keys: Dict[str, Tuple[str, Optional[int], str, AttrType]],
        presence_keys: Dict[str, Tuple[str, int]],
        emit: Callable[[EventBatch], None],
        out_stream_id: str = "#pattern_matches",
    ):
        self.nodes = nodes
        self.mode = mode
        self.within_ms = within_ms
        self.ref_defs = ref_defs
        self.output_keys = output_keys
        self.presence_keys = presence_keys
        self.emit_cb = emit
        self.out_stream_id = out_stream_id
        self.instances: List[Instance] = []
        self.matched_once = False
        self.has_every = any(n.rearm_to is not None for n in self.nodes)
        self._now = 0
        self._pending_matches: List[Tuple[Instance, int]] = []
        self._arm_fresh(0, 0)

    # -- state plumbing (snapshot contract) ---------------------------------

    def snapshot(self) -> Dict:
        return {"instances": self.instances, "matched_once": self.matched_once}

    def restore(self, state: Dict):
        self.instances = state["instances"]
        self.matched_once = state["matched_once"]

    # -- arming -------------------------------------------------------------

    def _arm_fresh(self, pos: int, now: int, src: Optional[Instance] = None):
        """Arm an instance at `pos` (virgin or every-rearm), keeping the
        captures of nodes before `pos` from `src`."""
        inst = Instance(pos, now)
        if src is not None and pos > 0:
            keep_refs = set()
            for n in self.nodes[:pos]:
                for s in n.specs:
                    keep_refs.add(s.ref)
            inst.captured = {r: list(v) for r, v in src.captured.items() if r in keep_refs}
            if inst.captured:
                inst.first_ts = src.first_ts
        # single pending per state for sequences; dedupe identical virgins
        if self.mode == "sequence" and any(
            i.alive and i.pos == pos for i in self.instances
        ):
            return
        if pos == 0 and not inst.captured and any(
            i.alive and i.is_virgin() for i in self.instances
        ):
            return
        self._enter_node(inst, pos, now)
        self.instances.append(inst)

    def _pend_match(self, inst: Instance, ts: int):
        if not any(i is inst for i, _ in self._pending_matches):
            self._pending_matches.append((inst, ts))

    def _enter_node(self, inst: Instance, pos: int, now: int):
        """Instance arrives at node `pos` with no captures there yet."""
        inst.pos = pos
        inst.count = 0
        inst.matched_sides = set()
        inst.enter_ts = now
        inst.deadline = None
        if pos >= len(self.nodes):
            return
        node = self.nodes[pos]
        for s in node.specs:
            if s.is_absent and s.waiting_ms is not None:
                inst.deadline = now + s.waiting_ms
        # min==0 stream nodes are satisfied on entry: handle every-rearm and
        # end-of-chain emission cascades
        if node.kind == "stream" and node.min_count == 0:
            if node.rearm_to is not None and node.rearm_to != pos:
                self._arm_fresh(node.rearm_to, now, src=inst)
            if self._end_reachable(pos + 1) and pos not in inst.emitted_at_node:
                inst.emitted_at_node.add(pos)
                self._pend_match(inst, now)

    # -- chain reachability -------------------------------------------------

    def _end_reachable(self, pos: int) -> bool:
        p = pos
        while p < len(self.nodes):
            n = self.nodes[p]
            if n.kind == "stream" and n.min_count == 0:
                p += 1
                continue
            return False
        return True

    def _successors(self, pos: int) -> List[int]:
        """Nodes testable after a satisfied node at `pos`: next node plus
        any reachable through zero-min stream nodes (absent stops the
        scan: it completes only by timer)."""
        out: List[int] = []
        p = pos + 1
        while p < len(self.nodes):
            n = self.nodes[p]
            if n.kind == "absent":
                break
            out.append(p)
            if n.kind == "stream" and n.min_count == 0:
                p += 1
                continue
            break
        return out

    # -- filters ------------------------------------------------------------

    def _filter_pass(self, spec: Spec, inst: Instance, row: dict, ts: int) -> bool:
        if spec.filter_compiled is None:
            return True
        env = {}
        for a in spec.stream_def.attribute_names:
            env["__cand." + a] = row.get(a)
        for key, (ref, idx, attr) in spec.filter_capture_keys.items():
            d = self.ref_defs[ref]
            t = d.attribute_type(attr) if attr in d.attribute_names else AttrType.OBJECT
            env[key] = _extract(inst.captured, ref, idx, attr, t)
        for key, (ref, idx) in spec.filter_presence_keys.items():
            rows = inst.captured.get(ref, [])
            i = len(rows) + idx if idx < 0 else idx
            env[key] = np.bool_(0 <= i < len(rows))
        env[TS_KEY] = ts
        env[N_KEY] = 1
        try:
            return bool(spec.filter_compiled.fn(env))
        except TypeError:
            return False  # null in comparison — no match

    # -- event processing ---------------------------------------------------

    def process_stream_batch(self, stream_key: str, batch: EventBatch):
        names = batch.attribute_names
        for i in range(len(batch)):
            if batch.types[i] != ev.CURRENT:
                continue
            row = {a: _unbox(batch.columns[a][i]) for a in names}
            self._process_event(stream_key, row, int(batch.timestamps[i]))

    def _process_event(self, stream_key: str, row: dict, ts: int):
        if self.matched_once and not self.has_every:
            return
        self._now = ts
        self._expire(ts)
        staged: List[Instance] = []

        for inst in list(self.instances):
            if not inst.alive:
                continue
            was_virgin = inst.is_virgin()
            used = False
            captured = False
            if inst.pos < len(self.nodes):
                node = self.nodes[inst.pos]
                # 1) dual-pending advances (tested against pre-capture state)
                if node.kind == "stream" and inst.count >= node.min_count and (
                    node.max_count == ANY or inst.count < node.max_count
                ):
                    advanced = False
                    for sp in self._successors(inst.pos):
                        advanced |= self._try_enter(
                            inst, self.nodes[sp], stream_key, row, ts, staged, via_clone=True
                        )
                    if advanced:
                        # the forwarded instance is SHARED with the
                        # successor — once the successor captures, the
                        # count state drops its copy and the arm emits at
                        # most once, in BOTH modes, even when the event
                        # could also have extended the count (reference
                        # CountPreStateProcessor.removeIfNextState-
                        # Processed runs before capture; pinned by
                        # ComplexPatternTestCase.testQuery3's three
                        # non-repeating matches and the peak corpus
                        # SequenceTestCase.testQuery20/23 restarts)
                        inst.alive = False
                    used |= advanced
                # 2) capture at current node
                if inst.alive:
                    captured = self._try_capture(inst, node, stream_key, row, ts)
                    used |= captured
                # 3) absent violation
                for s in node.specs:
                    if (
                        s.is_absent
                        and s.stream_key == stream_key
                        and self._filter_pass(s, inst, row, ts)
                    ):
                        if (
                            node.kind == "logical"
                            and node.logical_op == "or"
                            and any(not sp.is_absent for sp in node.specs)
                        ):
                            # `not B for t or C`: B only disables the
                            # absent branch — C may still win the race
                            # (LogicalAbsentPatternTestCase.
                            # testQueryAbsent15/16)
                            inst.violated = True
                        else:
                            inst.alive = False
                        used = True
            # strict continuity for sequences: only a CAPTURE keeps an
            # instance alive — an arm whose clone advanced via the
            # dual-pending path but which could not use the event itself
            # dies (reference: resetState clears all pendings each event;
            # only addState'd instances survive — the peak-detection
            # corpus SequenceTestCase.testQuery20 pins the restart).
            # Arms WAITING at an absent node are immune: the waiting
            # state consumes no events, and only a filter-matching
            # absent-stream event (the violation above) or the timer may
            # resolve it (AbsentSequenceTestCase.testQueryAbsent4/13)
            at_absent = (
                inst.pos < len(self.nodes)
                and self.nodes[inst.pos].kind == "absent"
            )
            if (self.mode == "sequence" and not captured and not was_virgin
                    and inst.alive and not at_absent):
                inst.alive = False

        self.instances = [i for i in self.instances if i.alive]
        self.instances.extend(i for i in staged if i.alive)
        self._flush_matches()  # consume emitted instances first
        if self.mode == "sequence":
            # single pending per state (reference keeps one,
            # StreamPreStateProcessor.addState for SEQUENCE)
            seen_pos = set()
            for i in self.instances:
                if i.pos in seen_pos:
                    i.alive = False
                else:
                    seen_pos.add(i.pos)
            self.instances = [i for i in self.instances if i.alive]
        if self.mode == "sequence" and self.has_every:
            # only `every` sequences re-arm the start per event; a
            # non-every sequence arms once and dies with its arm
            # (reference: init() re-arms only when
            # nextEveryStatePreProcessor != null —
            # SequenceTestCase.testQuery31 expects zero matches)
            if not any(i.alive and i.pos == 0 for i in self.instances):
                self._arm_fresh(0, ts)

    def _try_capture(self, inst: Instance, node: Node, stream_key: str, row: dict, ts: int) -> bool:
        if node.kind == "stream":
            spec = node.specs[0]
            if spec.is_absent or spec.stream_key != stream_key:
                return False
            if node.max_count != ANY and inst.count >= node.max_count:
                return False
            if not self._filter_pass(spec, inst, row, ts):
                return False
            was_satisfied = inst.count >= node.min_count
            inst.captured.setdefault(spec.ref, []).append(dict(row, __ts=ts))
            inst.count += 1
            if inst.first_ts is None:
                inst.first_ts = ts
            if inst.count >= node.min_count and not was_satisfied:
                if node.rearm_to is not None:
                    self._arm_fresh(node.rearm_to, ts, src=inst)
                if self._end_reachable(node.pos + 1) and node.pos not in inst.emitted_at_node:
                    inst.emitted_at_node.add(node.pos)
                    self._pend_match(inst, ts)
                # an open count forwards ONCE into a following absent
                # node at min-satisfaction (reference
                # processMinCountReached / SEQUENCE addState), with
                # SHARED capture lists so later captures are visible
                # when the deadline fires
                # (AbsentSequenceTestCase.testQueryAbsent36)
                open_count = (
                    node.max_count == ANY or node.max_count > node.min_count
                )
                if (
                    open_count
                    and node.pos + 1 < len(self.nodes)
                    and self.nodes[node.pos + 1].kind == "absent"
                ):
                    fwd = Instance(node.pos + 1, ts)
                    fwd.captured = inst.captured  # shared, not copied
                    fwd.first_ts = inst.first_ts
                    self._enter_node(fwd, node.pos + 1, ts)
                    self.instances.append(fwd)
            if node.max_count != ANY and inst.count >= node.max_count:
                # node full: move on (enter may cascade emits for min-0 tails)
                self._enter_node(inst, node.pos + 1, ts)
            return True
        if node.kind == "logical":
            got = False
            for si, spec in enumerate(node.specs):
                if spec.is_absent or si in inst.matched_sides:
                    continue
                if spec.stream_key == stream_key and self._filter_pass(spec, inst, row, ts):
                    inst.captured.setdefault(spec.ref, []).append(dict(row, __ts=ts))
                    inst.matched_sides.add(si)
                    if inst.first_ts is None:
                        inst.first_ts = ts
                    got = True
                    # 'and': ONE event can satisfy BOTH sides (reference
                    # partner processors each see it —
                    # LogicalPatternTestCase.testQuery5); 'or' consumes
                    # the first matching side only (testQuery3)
                    if node.logical_op == "or":
                        break
            if got and self._logical_complete(node, inst):
                self._complete_logical(inst, node, ts)
            return got
        return False

    def _try_enter(
        self, src: Instance, node: Node, stream_key: str, row: dict, ts: int,
        staged: List[Instance], via_clone: bool,
    ) -> bool:
        """An event enters successor `node` from dually-pending `src`."""
        if node.kind == "stream":
            spec = node.specs[0]
            if spec.is_absent or spec.stream_key != stream_key:
                return False
            if not self._filter_pass(spec, src, row, ts):
                return False
            inst = src.clone()
            self._enter_node_quiet(inst, node.pos, ts)
            inst.captured.setdefault(spec.ref, []).append(dict(row, __ts=ts))
            inst.count = 1
            if inst.first_ts is None:
                inst.first_ts = ts
            staged.append(inst)
            if inst.count >= node.min_count:
                if node.rearm_to is not None:
                    self._arm_fresh(node.rearm_to, ts, src=inst)
                if self._end_reachable(node.pos + 1):
                    inst.emitted_at_node.add(node.pos)
                    self._pend_match(inst, ts)
                if node.max_count != ANY and inst.count >= node.max_count:
                    self._enter_node(inst, node.pos + 1, ts)
            return True
        if node.kind == "logical":
            hits = []
            for si, spec in enumerate(node.specs):
                if spec.is_absent:
                    continue
                if spec.stream_key == stream_key and self._filter_pass(spec, src, row, ts):
                    hits.append(si)
                    if node.logical_op == "or":
                        break
            if not hits:
                return False
            inst = src.clone()
            self._enter_node_quiet(inst, node.pos, ts)
            for si in hits:
                inst.captured.setdefault(node.specs[si].ref, []).append(dict(row, __ts=ts))
            inst.matched_sides = set(hits)
            if inst.first_ts is None:
                inst.first_ts = ts
            staged.append(inst)
            if self._logical_complete(node, inst):
                self._complete_logical(inst, node, ts)
            return True
        return False

    def _enter_node_quiet(self, inst: Instance, pos: int, now: int):
        """enter without min-0 emission cascade (the entering event's own
        capture decides emission)."""
        inst.pos = pos
        inst.count = 0
        inst.matched_sides = set()
        inst.enter_ts = now
        inst.deadline = None
        if pos < len(self.nodes):
            for s in self.nodes[pos].specs:
                if s.is_absent and s.waiting_ms is not None:
                    inst.deadline = now + s.waiting_ms

    def _logical_complete(self, node: Node, inst: Instance) -> bool:
        present = [i for i, s in enumerate(node.specs) if not s.is_absent]
        if node.logical_op == "or":
            return any(i in inst.matched_sides for i in present)
        if not all(i in inst.matched_sides for i in present):
            return False
        # and-not with `for t`: absence must hold the full window
        if inst.deadline is not None:
            return self._now >= inst.deadline
        return True

    def _complete_logical(self, inst: Instance, node: Node, ts: int):
        if node.rearm_to is not None:
            self._arm_fresh(node.rearm_to, ts, src=inst)
        if self._end_reachable(node.pos + 1):
            inst.emitted_at_node.add(node.pos)
            self._pend_match(inst, ts)
        else:
            self._enter_node(inst, node.pos + 1, ts)

    # -- expiry / timers ----------------------------------------------------

    def _expire(self, now: int):
        if self.within_ms is None:
            return
        expired_src: Optional[Instance] = None
        for inst in self.instances:
            if inst.first_ts is not None and now - inst.first_ts > self.within_ms:
                inst.alive = False
                expired_src = inst
        self.instances = [i for i in self.instances if i.alive]
        if (
            expired_src is not None
            and self.mode == "pattern"
            and self.has_every
        ):
            # an every-pattern whose pending arm ran out of its within
            # window re-arms a fresh start (reference: expireEvents →
            # withinEveryPreStateProcessor.addEveryState, one re-arm per
            # tick; keeps captures before the every-group start).
            # _arm_fresh dedupes against an existing virgin, so patterns
            # that already keep a standing virgin are unaffected
            # (WithinPatternTestCase.testQuery1 vs testQuery4).
            restart = min(
                n.rearm_to for n in self.nodes if n.rearm_to is not None
            )
            self._arm_fresh(restart, now, src=expired_src)

    def on_time(self, now: int):
        """Scheduler tick: absent-node deadlines fire."""
        if self.matched_once and not self.has_every:
            return
        self._now = now
        self._expire(now)
        for inst in list(self.instances):
            if not inst.alive or inst.deadline is None or now < inst.deadline:
                continue
            if inst.pos >= len(self.nodes):
                continue
            node = self.nodes[inst.pos]
            fire_ts = inst.deadline
            inst.deadline = None
            if node.kind == "absent":
                if node.rearm_to is not None:
                    self._arm_fresh(node.rearm_to, fire_ts, src=inst)
                if self._end_reachable(node.pos + 1):
                    inst.emitted_at_node.add(node.pos)
                    self._pend_match(inst, fire_ts)
                else:
                    self._enter_node(inst, node.pos + 1, fire_ts)
            elif node.kind == "logical":
                if self._logical_complete(node, inst):
                    self._complete_logical(inst, node, fire_ts)
                elif (
                    node.logical_op == "or"
                    and not inst.violated
                    and any(s.is_absent for s in node.specs)
                ):
                    # `not B for t or C`: the absence window passed
                    # unviolated before any present side matched — the
                    # absent branch wins with null present captures
                    # (LogicalAbsentPatternTestCase.testQueryAbsent13)
                    self._complete_logical(inst, node, fire_ts)
        self._flush_matches()

    def next_wakeup(self) -> Optional[int]:
        deadlines = [i.deadline for i in self.instances if i.alive and i.deadline is not None]
        return min(deadlines) if deadlines else None

    def stats(self) -> Dict:
        """Ops introspection — same shape as the dense runtime's so the
        REST/on-demand surface is engine-agnostic."""
        return {
            "engine": "host",
            "active_instances": sum(1 for i in self.instances if i.alive),
            "matched_once": self.matched_once,
        }

    def fire(self, now: int):
        self.on_time(now)

    def on_start(self, now: int):
        """App start: (re)base deadlines of initially-armed instances —
        leading absent nodes count their window from start time."""
        for inst in self.instances:
            if inst.deadline is not None:
                node = self.nodes[inst.pos]
                wait = None
                for sp in node.specs:
                    if sp.is_absent and sp.waiting_ms is not None:
                        wait = sp.waiting_ms
                if wait is not None:
                    inst.enter_ts = now
                    inst.deadline = now + wait

    # -- emission -----------------------------------------------------------

    def _flush_matches(self):
        matches, self._pending_matches = self._pending_matches, []
        if not matches:
            return
        rows = []
        for inst, ts in matches:
            row = {"__ts": ts}
            for key, (ref, idx, attr, t) in self.output_keys.items():
                row[key] = _extract(inst.captured, ref, idx, attr, t)
            for key, (ref, idx) in self.presence_keys.items():
                caps = inst.captured.get(ref, [])
                i = len(caps) + idx if idx < 0 else idx
                row[key] = np.bool_(0 <= i < len(caps))
            rows.append(row)
            # matched instance is consumed unless it is an in-progress count
            # node still capturing (dual pending, shared-list analog)
            inst_node = self.nodes[inst.pos] if inst.pos < len(self.nodes) else None
            dual = (
                inst_node is not None
                and inst_node.kind == "stream"
                and inst_node.pos in inst.emitted_at_node
                and (inst_node.max_count == ANY or inst.count < inst_node.max_count)
                and inst.count > 0
            )
            if not dual:
                inst.alive = False
        if not self.has_every:
            self.matched_once = True
            for i in self.instances:
                i.alive = False
        self.instances = [i for i in self.instances if i.alive]
        # columnar match batch
        keys = list(self.output_keys) + list(self.presence_keys)
        cols: Dict[str, np.ndarray] = {}
        for key in keys:
            vals = [r.get(key) for r in rows]
            if key in self.output_keys:
                cols[key] = _column(vals, self.output_keys[key][3])
            else:
                cols[key] = np.asarray(vals, dtype=bool)
        batch = EventBatch(
            self.out_stream_id,
            keys,
            cols,
            np.asarray([r["__ts"] for r in rows], dtype=np.int64),
        )
        self.emit_cb(batch)


def _column(vals: List, t: AttrType) -> np.ndarray:
    has_null = any(v is None or (isinstance(v, float) and math.isnan(v)) for v in vals)
    if has_null or t in (AttrType.STRING, AttrType.OBJECT):
        # unmatched slots surface as nulls (reference emits null), so the
        # column falls back to object dtype
        out = np.empty(len(vals), dtype=object)
        for i, v in enumerate(vals):
            out[i] = None if (isinstance(v, float) and math.isnan(v)) else v
        return out
    return np.asarray(vals, dtype=t.np_dtype)


def _unbox(v):
    return v.item() if isinstance(v, np.generic) else v
