"""Compute operators: windows, aggregators, NFA kernels.

The "native layer" of the TPU build — where the reference has per-event
Java operators (query/processor/stream/window/*, query/selector/attribute/
aggregator/*, query/input/stream/state/*), this package has vectorized
columnar operators whose hot paths are jax-jittable.
"""
