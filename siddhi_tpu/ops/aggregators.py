"""Attribute aggregator executors (reference:
query/selector/attribute/aggregator/*AttributeAggregatorExecutor.java).

Per-group incremental aggregators with vectorized run processing: a "run"
is a maximal slice of same-type events for one group; ``add_run`` returns
the running aggregate value AFTER each row (Siddhi emits one output event
per input event carrying the aggregate-so-far), ``remove_run`` handles
EXPIRED events (window evictions), ``reset`` handles RESET markers from
batch windows.
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional

import numpy as np

from siddhi_tpu.core.exceptions import SiddhiAppCreationError
from siddhi_tpu.query_api import AttrType


class AggExecutor:
    """One instance per (query select-item); state is per group key."""

    return_type: AttrType = AttrType.DOUBLE

    def new_state(self) -> dict:
        raise NotImplementedError

    def add_run(self, state: dict, values: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def remove_run(self, state: dict, values: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def reset(self, state: dict):
        new = self.new_state()
        state.clear()
        state.update(new)


class SumAgg(AggExecutor):
    """sum() — returns LONG for int/long inputs, DOUBLE for float/double
    (reference: SumAttributeAggregatorExecutor)."""

    def __init__(self, arg_type: AttrType):
        if arg_type in (AttrType.INT, AttrType.LONG):
            self.return_type = AttrType.LONG
            self._dtype = np.int64
        else:
            self.return_type = AttrType.DOUBLE
            self._dtype = np.float64

    def new_state(self):
        return {"sum": self._dtype(0), "n": 0}

    def add_run(self, state, values):
        out = state["sum"] + np.cumsum(values.astype(self._dtype))
        state["sum"] = out[-1] if len(out) else state["sum"]
        state["n"] += len(values)
        return out

    def remove_run(self, state, values):
        out = state["sum"] - np.cumsum(values.astype(self._dtype))
        state["sum"] = out[-1] if len(out) else state["sum"]
        state["n"] -= len(values)
        return out


class CountAgg(AggExecutor):
    return_type = AttrType.LONG

    def new_state(self):
        return {"n": np.int64(0)}

    def add_run(self, state, values):
        n = len(values)
        out = state["n"] + np.arange(1, n + 1, dtype=np.int64)
        state["n"] = state["n"] + n
        return out

    def remove_run(self, state, values):
        n = len(values)
        out = state["n"] - np.arange(1, n + 1, dtype=np.int64)
        state["n"] = state["n"] - n
        return out


class AvgAgg(AggExecutor):
    return_type = AttrType.DOUBLE

    def new_state(self):
        return {"sum": np.float64(0), "n": np.int64(0)}

    def _emit(self, sums, counts):
        with np.errstate(invalid="ignore", divide="ignore"):
            return np.where(counts > 0, sums / counts, np.nan)

    def add_run(self, state, values):
        sums = state["sum"] + np.cumsum(values.astype(np.float64))
        counts = state["n"] + np.arange(1, len(values) + 1, dtype=np.int64)
        if len(values):
            state["sum"], state["n"] = sums[-1], counts[-1]
        return self._emit(sums, counts)

    def remove_run(self, state, values):
        sums = state["sum"] - np.cumsum(values.astype(np.float64))
        counts = state["n"] - np.arange(1, len(values) + 1, dtype=np.int64)
        if len(values):
            state["sum"], state["n"] = sums[-1], counts[-1]
        return self._emit(sums, counts)


class StdDevAgg(AggExecutor):
    """Population stddev (reference: StdDevAttributeAggregatorExecutor)."""

    return_type = AttrType.DOUBLE

    def new_state(self):
        return {"s1": np.float64(0), "s2": np.float64(0), "n": np.int64(0)}

    def _emit(self, s1, s2, n):
        with np.errstate(invalid="ignore", divide="ignore"):
            mean = s1 / n
            var = s2 / n - mean * mean
            return np.where(n > 0, np.sqrt(np.maximum(var, 0.0)), np.nan)

    def add_run(self, state, values):
        v = values.astype(np.float64)
        s1 = state["s1"] + np.cumsum(v)
        s2 = state["s2"] + np.cumsum(v * v)
        n = state["n"] + np.arange(1, len(v) + 1, dtype=np.int64)
        if len(v):
            state["s1"], state["s2"], state["n"] = s1[-1], s2[-1], n[-1]
        return self._emit(s1, s2, n)

    def remove_run(self, state, values):
        v = values.astype(np.float64)
        s1 = state["s1"] - np.cumsum(v)
        s2 = state["s2"] - np.cumsum(v * v)
        n = state["n"] - np.arange(1, len(v) + 1, dtype=np.int64)
        if len(v):
            state["s1"], state["s2"], state["n"] = s1[-1], s2[-1], n[-1]
        return self._emit(s1, s2, n)


class _HeapMinMax(AggExecutor):
    """min()/max() with expiry support via lazy-deletion heap
    (the reference keeps a LinkedList scan; a heap is O(log n))."""

    def __init__(self, arg_type: AttrType, is_max: bool):
        self.return_type = arg_type
        self.is_max = is_max

    def new_state(self):
        return {"heap": [], "dead": {}, "size": 0}

    def _sign(self, v):
        return -v if self.is_max else v

    def _top(self, state):
        heap, dead = state["heap"], state["dead"]
        while heap:
            v = heap[0]
            if dead.get(v, 0) > 0:
                heapq.heappop(heap)
                dead[v] -= 1
                if dead[v] == 0:
                    del dead[v]
            else:
                return -v if self.is_max else v
        return None

    def add_run(self, state, values):
        out = np.empty(len(values), dtype=np.float64)
        for i, v in enumerate(values):
            heapq.heappush(state["heap"], self._sign(float(v)))
            state["size"] += 1
            out[i] = self._top(state)
        return self._cast(out)

    def remove_run(self, state, values):
        out = np.empty(len(values), dtype=np.float64)
        for i, v in enumerate(values):
            sv = self._sign(float(v))
            state["dead"][sv] = state["dead"].get(sv, 0) + 1
            state["size"] -= 1
            top = self._top(state)
            out[i] = np.nan if top is None else top
        return self._cast(out)

    def _cast(self, out):
        if self.return_type in (AttrType.INT, AttrType.LONG) and not np.isnan(out).any():
            return out.astype(AttrType(self.return_type).np_dtype)
        return out


class MinMaxForeverAgg(AggExecutor):
    """minForever()/maxForever() — never expire
    (reference: MinForeverAttributeAggregatorExecutor)."""

    def __init__(self, arg_type: AttrType, is_max: bool):
        self.return_type = arg_type
        self.is_max = is_max

    def new_state(self):
        return {"v": None}

    def add_run(self, state, values):
        v = values.astype(np.float64)
        acc = np.maximum.accumulate(v) if self.is_max else np.minimum.accumulate(v)
        if state["v"] is not None:
            acc = np.maximum(acc, state["v"]) if self.is_max else np.minimum(acc, state["v"])
        if len(acc):
            state["v"] = acc[-1]
        return acc

    def remove_run(self, state, values):
        n = len(values)
        cur = np.nan if state["v"] is None else state["v"]
        return np.full(n, cur, dtype=np.float64)

    def reset(self, state: dict):
        # forever values survive window RESETs: the reference's reset()
        # returns the current value WITHOUT clearing state
        # (MinForeverAttributeAggregatorExecutor.java:179-181)
        pass


class DistinctCountAgg(AggExecutor):
    return_type = AttrType.LONG

    def new_state(self):
        return {"counts": {}}

    def add_run(self, state, values):
        counts = state["counts"]
        out = np.empty(len(values), dtype=np.int64)
        for i, v in enumerate(values):
            key = v.item() if isinstance(v, np.generic) else v
            counts[key] = counts.get(key, 0) + 1
            out[i] = len(counts)
        return out

    def remove_run(self, state, values):
        counts = state["counts"]
        out = np.empty(len(values), dtype=np.int64)
        for i, v in enumerate(values):
            key = v.item() if isinstance(v, np.generic) else v
            c = counts.get(key, 0) - 1
            if c <= 0:
                counts.pop(key, None)
            else:
                counts[key] = c
            out[i] = len(counts)
        return out


class BoolAndAgg(AggExecutor):
    """and() over bools (reference: AndAttributeAggregatorExecutor)."""

    return_type = AttrType.BOOL

    def new_state(self):
        return {"true": 0, "false": 0}

    def _emit_scalar(self, state):
        return state["false"] == 0

    def add_run(self, state, values):
        out = np.empty(len(values), dtype=bool)
        for i, v in enumerate(values):
            state["true" if v else "false"] += 1
            out[i] = self._emit_scalar(state)
        return out

    def remove_run(self, state, values):
        out = np.empty(len(values), dtype=bool)
        for i, v in enumerate(values):
            state["true" if v else "false"] -= 1
            out[i] = self._emit_scalar(state)
        return out


class BoolOrAgg(BoolAndAgg):
    def _emit_scalar(self, state):
        return state["true"] > 0


class UnionSetAgg(AggExecutor):
    """unionSet() — accumulates a set of values
    (reference: UnionSetAttributeAggregatorExecutor)."""

    return_type = AttrType.OBJECT

    def new_state(self):
        return {"counts": {}}

    def add_run(self, state, values):
        counts = state["counts"]
        out = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            key = v.item() if isinstance(v, np.generic) else v
            counts[key] = counts.get(key, 0) + 1
            out[i] = set(counts)
        return out

    def remove_run(self, state, values):
        counts = state["counts"]
        out = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            key = v.item() if isinstance(v, np.generic) else v
            c = counts.get(key, 0) - 1
            if c <= 0:
                counts.pop(key, None)
            else:
                counts[key] = c
            out[i] = set(counts)
        return out


def make_aggregator(name: str, arg_type: Optional[AttrType]) -> AggExecutor:
    if name == "sum":
        return SumAgg(arg_type or AttrType.DOUBLE)
    if name == "count":
        return CountAgg()
    if name == "avg":
        return AvgAgg()
    if name == "stdDev":
        return StdDevAgg()
    if name == "min":
        return _HeapMinMax(arg_type or AttrType.DOUBLE, is_max=False)
    if name == "max":
        return _HeapMinMax(arg_type or AttrType.DOUBLE, is_max=True)
    if name == "minForever":
        return MinMaxForeverAgg(arg_type or AttrType.DOUBLE, is_max=False)
    if name == "maxForever":
        return MinMaxForeverAgg(arg_type or AttrType.DOUBLE, is_max=True)
    if name == "distinctCount":
        return DistinctCountAgg()
    if name == "and":
        return BoolAndAgg()
    if name == "or":
        return BoolOrAgg()
    if name == "unionSet":
        return UnionSetAgg()
    raise SiddhiAppCreationError(f"unknown aggregator '{name}'")
