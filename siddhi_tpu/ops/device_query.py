"""Jitted device path for the general query pipeline.

The reference's bread-and-butter hot loop — ProcessStreamReceiver.receive
(query/input/ProcessStreamReceiver.java:99-179) pushing pooled events
through FilterProcessor (query/processor/filter/FilterProcessor.java:32),
a window processor (query/processor/stream/window/*) and
QuerySelector.process (query/selector/QuerySelector.java:76-99) with
per-group AttributeAggregatorExecutors — re-designed as ONE jit-compiled
step over columnar micro-batches:

- **filter**: the jax backend of the compiled expression tree produces a
  boolean mask over the batch (no per-event virtual calls);
- **windows**: fixed-capacity ring buffers in device memory.  Sliding
  aggregates (length/time) are computed with a static ``[B, W]`` window
  gather + membership mask + reduction — every output row in the batch
  is computed in parallel, no scan.  Passing rows are compacted with a
  prefix-sum scatter so filtered-out rows never occupy window slots;
- **group-by**: group keys are interned host-side to dense slot ids
  (exactly like the dense NFA's partition interning); per-group
  aggregator state lives as ``[G]`` device arrays updated with
  scatter-add/min/max, and within-batch running prefixes use a masked
  ``[B, B]`` same-group matmul that XLA maps onto the MXU;
- **tumbling windows** (lengthBatch/timeBatch): per-group accumulators
  plus a flush kernel emitting one row per touched group; the host
  wrapper splits incoming batches at pane boundaries so each step call
  stays a static-shape program.

Device-mode semantics (documented subset of the host engine — the
planner falls back to the host path otherwise, mirroring the dense NFA
contract):
 - single input stream; filters precede at most one window;
 - windows: none (running aggregates), length, time (sliding, per-event
   emission), lengthBatch, timeBatch (tumbling, per-flush emission);
 - aggregators: sum / count / avg / min / max / stdDev / minForever /
   maxForever / and / or (distinctCount and unionSet keep unbounded
   per-group value sets — documented host fallback);
 - filter / select / having expressions must be jax-traceable (numeric
   attrs, arithmetic/comparison/boolean ops) — checked at compile time
   by actually tracing them;
 - tumbling select items may reference only group keys and aggregates
   (the host engine's last-row-per-group attrs need per-attr registers);
 - time windows hold at most ``window_capacity`` passing events (the
   reference buffer is unbounded; overflow drops the oldest).

Partition mode (``partition with (key of S) begin ... end`` under
``@app:execution('tpu')``, reference:
partition/PartitionStreamReceiver.java:82-118):
 - the partition key arrives as an external per-row column, composes
   into the group axis for aggregation state, and scopes windows per
   key: each key owns a ``[W]`` ring-buffer row of a ``[n_wgroups, W]``
   device array (the per-instance window of the reference's cloned
   queries) — see ``_keyed_sliding_step``;
 - sliding windows expire PER ROW within a batch, preserving the
   reference's event-at-a-time semantics regardless of batch size (the
   host engine's batch path approximates time windows at the batch
   watermark);
 - tumbling windows and output rate limits need per-key pane/limiter
   state and fall back to per-key host instances;
 - idle keys are purged via ``purge_idle_keys`` (free-listed rows are
   zeroed and reused), driven by the partition's @purge annotation.

Numeric lanes (TPU-first dtype policy):
 - INT attributes ride int32 lanes — bit-exact;
 - FLOAT/DOUBLE attributes ride float32 lanes, and aggregation state
   accumulates in float32 (the MXU-native dtype) — a documented
   precision subset of the host engine's float64 numpy;
 - LONG attributes referenced by device-evaluated expressions (filters,
   aggregate arguments, computed select items, having) make the query
   ineligible until the int64 lane lands — float32 would silently round
   above 2^24.  LONG *is* fine as a group-by key or a bare select item:
   both are materialized host-side at native width (group keys are
   interned host-side; bare ``select attr`` items gather from the input
   batch, never touching a device lane).
 - emitted columns are cast back to the declared attribute types.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from siddhi_tpu.core.exceptions import (
    SiddhiAppCreationError,
    SiddhiAppRuntimeError,
)
from siddhi_tpu.planner.expr import (
    AGGREGATOR_NAMES,
    CompiledExpression,
    ExpressionCompiler,
    N_KEY,
    Scope,
    TS_KEY,
)
from siddhi_tpu.query_api import (
    AndOp,
    ArithmeticOp,
    AttrType,
    CompareOp,
    Expression,
    Filter,
    FunctionCall,
    InOp,
    IsNull,
    NotOp,
    OrOp,
    OutputAttribute,
    Query,
    SingleInputStream,
    Variable,
    WindowHandler,
)

SUPPORTED_AGGS = ("sum", "count", "avg", "min", "max", "stdDev",
                  "minForever", "maxForever", "and", "or")
# distinctCount / unionSet keep per-group value-count dicts (reference:
# DistinctCountAttributeAggregatorExecutor) — unbounded value sets have
# no fixed-shape device layout, so they are a documented host fallback
SUPPORTED_WINDOWS = (None, "length", "time", "lengthBatch", "timeBatch")

# aggregators whose window/running reduction is a masked SUM of the
# (transformed) argument lane: and/or reduce over the bool lane
_SUM_KINDS = ("sum", "avg", "stdDev", "and", "or")

PER_EVENT = "per_event"
PER_FLUSH = "per_flush"

# host-side chunking bound for the per-event step: the running and
# keyed-sliding kinds build [B, B] same-group masks, so an unbounded
# junction batch would allocate quadratically; chunks advance state
# sequentially, which is semantics-preserving for every kind
MAX_DEVICE_BATCH = 2048


@dataclass
class DeviceAgg:
    kind: str  # one of SUPPORTED_AGGS
    arg: Optional[CompiledExpression]  # None for count
    env_key: str


def _DevicePairCompiler(scope, pair_keys):
    """Compiler for device-evaluated expressions: LONG STREAM attributes
    (``pair_keys``) ride hi/lo int32 pair lanes (bit-exact comparisons
    at any magnitude); INT keeps its plain int32 lane; synthetic
    LONG-typed env keys (count() outputs) ride ordinary float32 lanes.
    Imported lazily to keep dense_nfa out of the module import path."""
    from siddhi_tpu.ops.dense_nfa import DenseExprCompiler
    from siddhi_tpu.planner.expr import ExpressionCompiler as _Plain

    class _C(DenseExprCompiler):
        PAIR_TYPES = (AttrType.LONG,)

        def _i64_parts(self, e, var_only=False):
            if isinstance(e, Variable):
                key, _t = self.scope.resolve(e)
                if key not in pair_keys:
                    return None
            return super()._i64_parts(e, var_only)

        def _c_Variable(self, e):
            key, t = self.scope.resolve(e)
            if t in self.PAIR_TYPES and key not in pair_keys:
                return _Plain._c_Variable(self, e)
            return super()._c_Variable(e)

    return _C(scope)


def _split_i64(v: np.ndarray):
    """int64 column -> (hi, lo) int32 lanes; lo is bias-signed so SIGNED
    int32 comparison of lo equals UNSIGNED comparison of the raw low
    word (ops/dense_nfa.py:91-105)."""
    v = np.asarray(v, dtype=np.int64)
    hi = (v >> 32).astype(np.int32)
    lo = ((v & 0xFFFFFFFF) - 2**31).astype(np.int32)
    return hi, lo


def _map_children(expr: Expression, fn) -> Expression:
    """Rebuild a composite expression node with ``fn`` applied to each
    child; leaves return unchanged.  The single structural walk shared
    by every AST pass in this module — add new composite node types
    HERE, not in the passes."""
    if isinstance(expr, ArithmeticOp):
        return ArithmeticOp(expr.op, fn(expr.left), fn(expr.right))
    if isinstance(expr, CompareOp):
        return CompareOp(expr.op, fn(expr.left), fn(expr.right))
    if isinstance(expr, AndOp):
        return AndOp(fn(expr.left), fn(expr.right))
    if isinstance(expr, OrOp):
        return OrOp(fn(expr.left), fn(expr.right))
    if isinstance(expr, NotOp):
        return NotOp(fn(expr.expr))
    if isinstance(expr, IsNull):
        return IsNull(fn(expr.expr))
    if isinstance(expr, FunctionCall):
        return FunctionCall(
            expr.namespace, expr.name, tuple(fn(a) for a in expr.args),
            expr.star)
    if isinstance(expr, InOp):
        return InOp(fn(expr.expr), expr.source_id)
    return expr


class _DeviceAggRewrite:
    """Replaces aggregator calls in select/having expressions with
    synthetic variables bound to device aggregation outputs (the device
    analog of the planner's AggregatorRewrite)."""

    def __init__(self, scope: Scope, compiler: ExpressionCompiler):
        self.scope = scope
        self.compiler = compiler
        self.aggs: List[DeviceAgg] = []

    def rewrite(self, expr: Expression) -> Expression:
        if (
            isinstance(expr, FunctionCall)
            and expr.namespace is None
            and expr.name in AGGREGATOR_NAMES
        ):
            if expr.name not in SUPPORTED_AGGS:
                raise SiddhiAppCreationError(
                    f"device query path does not support aggregator "
                    f"'{expr.name}'"
                    + (" (unbounded value sets need the host engine)"
                       if expr.name in ("distinctCount", "unionSet")
                       else ""))
            key = f"__dagg_{len(self.aggs)}"
            arg = None
            if expr.args:
                if len(expr.args) > 1:
                    raise SiddhiAppCreationError(
                        f"aggregator '{expr.name}' takes one argument")
                arg = self.compiler.compile(self.rewrite(expr.args[0]))
            elif expr.name != "count":
                raise SiddhiAppCreationError(
                    f"aggregator '{expr.name}' needs an argument")
            if expr.name in ("and", "or"):
                if arg is None or arg.type != AttrType.BOOL:
                    raise SiddhiAppCreationError(
                        f"aggregator '{expr.name}' needs a boolean argument")
                out_t = AttrType.BOOL
            elif expr.name == "count":
                out_t = AttrType.LONG
            else:
                out_t = AttrType.DOUBLE
            self.aggs.append(DeviceAgg(expr.name, arg, key))
            self.scope.add_bare(key, out_t)
            return Variable(attribute=key)
        if isinstance(expr, InOp):
            raise SiddhiAppCreationError(
                "device query path does not support table membership (IN)")
        return _map_children(expr, self.rewrite)


def _subst_aliases(expr: Expression, aliases: Dict[str, Expression]) -> Expression:
    """Replace bare Variable references to select aliases with the select
    item's (already aggregator-rewritten) expression.  An alias shadows a
    same-named input attribute, matching the host selector's scope order."""
    if isinstance(expr, Variable):
        if expr.stream_id is None and expr.attribute in aliases:
            return aliases[expr.attribute]
        return expr
    return _map_children(expr, lambda e: _subst_aliases(e, aliases))


def _pow2(n: int, floor: int = 16) -> int:
    return max(1 << (max(n, 1) - 1).bit_length(), floor)


class DeviceQueryEngine:
    """One single-input query compiled into jitted device steps.

    Usage::

        eng = compile_query(app_str, "q1", n_groups=1024)
        state = eng.init_state()
        state, rows = eng.process(state, cols, ts)   # rows: emitted dicts
    """

    #: span-label kind for the cycle tracer (observability/trace.py) —
    #: the runtime reads it at construction, so a wrapper engine (the
    #: sharded delegate) overrides what the trace calls its cycles
    engine_kind = "device"

    def __init__(
        self,
        query: Query,
        stream_def,
        n_groups: int = 1024,
        window_capacity: int = 1024,
        partition_mode: bool = False,
        n_wgroups: Optional[int] = None,
        defer_order_by: bool = False,
    ):
        import jax
        import jax.numpy as jnp

        self.jax, self.jnp = jax, jnp
        self.query = query
        self.stream_def = stream_def
        self.n_groups = n_groups
        # partitioned form (`partition with (key of S) begin ... end`
        # under execution('tpu')): the partition key arrives per batch as
        # an external column, composes into the group axis for per-key
        # aggregation state, and scopes windows per key (each key gets
        # its own ring-buffer row — the reference's per-instance window,
        # partition/PartitionStreamReceiver.java:82-118, re-designed as
        # [n_wgroups, W] device state instead of per-key Python objects)
        self.partition_mode = bool(partition_mode)
        self.n_wgroups = int(n_wgroups) if n_wgroups else n_groups
        # fault-injection harness (util/faults.py), wired by the planner
        # when @app:faults is present; consulted before each jitted step
        self.faults = None

        s = query.input_stream
        if not isinstance(s, SingleInputStream):
            raise SiddhiAppCreationError(
                "device query path needs a single input stream")
        self.stream_id = s.stream_id

        # -- handler chain: filters then at most one window ------------------
        self.filter_exprs: List[Expression] = []
        self.window_name: Optional[str] = None
        self.window_args: List = []
        seen_window = False
        for h in s.handlers:
            if isinstance(h, Filter):
                if seen_window:
                    raise SiddhiAppCreationError(
                        "device query path: filters must precede the window")
                self.filter_exprs.append(h.expression)
            elif isinstance(h, WindowHandler):
                if seen_window:
                    raise SiddhiAppCreationError(
                        "device query path supports at most one window")
                seen_window = True
                self.window_name = h.name
                self.window_args = list(h.args)
            else:
                raise SiddhiAppCreationError(
                    f"device query path: unsupported handler {type(h).__name__}")
        if self.window_name not in SUPPORTED_WINDOWS:
            raise SiddhiAppCreationError(
                f"device query path does not support window "
                f"'{self.window_name}'")
        self.mode = (
            PER_FLUSH if self.window_name in ("lengthBatch", "timeBatch")
            else PER_EVENT
        )

        # -- scope / expression compilation ----------------------------------
        # device lanes: INT rides int32 (bit-exact), FLOAT/DOUBLE ride
        # float32, LONG rides a hi/lo int32 PAIR usable in plain
        # comparisons (bit-exact at any magnitude — the dense NFA's
        # lane technique, ops/dense_nfa.py:91-105); LONG arithmetic /
        # aggregate arguments still fall back to the host engine.
        self._lane_dtype: Dict[str, np.dtype] = {
            a.name: (np.dtype(np.int32) if a.type == AttrType.INT
                     else np.dtype(np.bool_) if a.type == AttrType.BOOL
                     else np.dtype(np.float32))
            for a in stream_def.attributes
            if (a.type.is_numeric or a.type == AttrType.BOOL)
            and a.type != AttrType.LONG
        }
        self.attrs = list(self._lane_dtype)
        self.long_attrs = [a.name for a in stream_def.attributes
                           if a.type == AttrType.LONG]
        self.all_attrs = list(stream_def.attribute_names)
        scope = Scope()
        for a in stream_def.attributes:
            scope.add(s.alias or s.stream_id, a.name, a.name, a.type)
            if s.alias:
                scope.add(s.stream_id, a.name, a.name, a.type)
        # device-evaluated expressions: LONG stream attrs ride pair lanes
        compiler = _DevicePairCompiler(scope, set(self.long_attrs))
        # host-evaluated expressions (group keys, window constants):
        # native numpy width, any type
        host_compiler = ExpressionCompiler(scope)

        self.filters = [compiler.compile(e) for e in self.filter_exprs]

        # window parameter (constant)
        self.window_param: Optional[int] = None
        if self.window_name is not None:
            if not self.window_args:
                raise SiddhiAppCreationError(
                    f"window '{self.window_name}' needs an argument")
            c = host_compiler.compile(self.window_args[0])
            try:
                self.window_param = int(c.fn({}))
            except Exception as e:
                raise SiddhiAppCreationError(
                    f"window '{self.window_name}' argument must be constant"
                ) from e

        # group-by keys (exprs; interned host-side)
        sel = query.selector
        self.group_exprs: List[CompiledExpression] = [
            host_compiler.compile(g) for g in (sel.group_by or [])
        ]
        self.group_raw: List[Expression] = list(sel.group_by or [])
        # numeric group keys usable inside flush exprs
        self._numeric_group_keys = [
            i for i, g in enumerate(self.group_exprs)
            if g.type.is_numeric
        ]

        # select items: rewrite aggregators, classify outputs
        rewriter = _DeviceAggRewrite(scope, compiler)
        if sel.selection is None:
            # select * (selection=None IS the parser's select-all form):
            # every input attribute passes through at native width
            # (stream functions never reach the device chain, so the
            # flowing schema IS the stream definition)
            sel = type(sel)(
                selection=[
                    OutputAttribute(Variable(attribute=a.name))
                    for a in stream_def.attributes
                ],
                group_by=list(sel.group_by or []),
                having=sel.having,
                order_by=list(sel.order_by or []),
                limit=sel.limit,
                offset=sel.offset,
            )
        # out_spec entries: ("expr", compiled) | ("group_key", key_index)
        # | ("passthrough", attr_name) — passthroughs gather the input
        # column host-side at native width (any type, incl. LONG/STRING)
        self.out_spec: List[Tuple[str, object, str]] = []
        self._device_expr_raw: List[Expression] = []
        # select alias -> rewritten expression AST, so `having s > 100`
        # referencing `sum(v) as s` resolves (the host path registers
        # output attrs in scope, planner/query_planner.py:530-535; here
        # aliases substitute inline before compiling having)
        alias_map: Dict[str, Expression] = {}
        for oa in sel.selection:
            gk = self._as_group_key(oa.expression)
            if gk is not None:
                self.out_spec.append(("group_key", gk, oa.name))
                alias_map[oa.name] = oa.expression
                continue
            pt = self._as_passthrough(oa.expression, stream_def, s)
            if pt is not None:
                self.out_spec.append(("passthrough", pt, oa.name))
                alias_map[oa.name] = oa.expression
                continue
            rewritten = rewriter.rewrite(oa.expression)
            compiled = compiler.compile(rewritten)
            self.out_spec.append(("expr", compiled, oa.name))
            self._device_expr_raw.append(oa.expression)
            alias_map[oa.name] = rewritten
        self.aggs = rewriter.aggs
        # declared output type per lane (emitted columns are cast back)
        self.out_types: List[AttrType] = []
        for kind, v, _name in self.out_spec:
            if kind == "group_key":
                self.out_types.append(self.group_exprs[v].type)
            elif kind == "passthrough":
                self.out_types.append(stream_def.attribute_type(v))
            else:
                self.out_types.append(v.type)
        self._check_value_types(stream_def, s, sel)
        self.having = (
            compiler.compile(rewriter.rewrite(
                _subst_aliases(sel.having, alias_map)))
            if sel.having is not None else None
        )
        # order by / limit / offset are never evaluated by this engine.
        # The PLANNER path applies them in its host-side passthrough
        # selector over each emitted chunk (defer_order_by=True, same
        # pipeline position as the host engine's per-chunk
        # _order_limit); direct-API callers have no such selector, so
        # silently dropping the clauses would corrupt results
        if not defer_order_by and (
                sel.order_by or sel.limit is not None
                or sel.offset is not None):
            raise SiddhiAppCreationError(
                "device query engine: order by/limit/offset need the "
                "planner's host-side selector (SiddhiManager path) — "
                "the direct compile_query API does not apply them")
        if self.mode == PER_FLUSH:
            for kind, _v, name in self.out_spec:
                if kind == "passthrough":
                    raise SiddhiAppCreationError(
                        f"tumbling device query: select item '{name}' may "
                        "reference only group keys and aggregates")
                if kind == "expr" and not self._flush_expr_ok(_v):
                    raise SiddhiAppCreationError(
                        f"tumbling device query: select item '{name}' may "
                        "reference only group keys and aggregates")
        if self.mode == PER_EVENT and self.window_name is None and not self.aggs:
            self.kind = "filter"  # stateless filter/projection query
        elif self.mode == PER_EVENT and self.window_name is None:
            self.kind = "running"
        elif self.mode == PER_EVENT:
            self.kind = "sliding"
        else:
            self.kind = "tumbling"
        if self.partition_mode:
            if self.kind == "tumbling":
                raise SiddhiAppCreationError(
                    "partitioned tumbling windows need per-key pane "
                    "boundaries — per-key host instances used")
            if self.kind == "sliding":
                self.kind = "keyed_sliding"

        # window geometry
        if self.kind in ("sliding", "keyed_sliding"):
            self.W = (
                int(self.window_param) if self.window_name == "length"
                else int(window_capacity)
            )
            if self.W < 1:
                raise SiddhiAppCreationError("window size must be >= 1")
        else:
            self.W = 0

        self._trace_check()
        self._step_cache: Dict[str, Callable] = {}

        # host-side interning / pane bookkeeping.  In partition mode the
        # group key space is the composed tuple (partition_key, *group
        # keys); window groups (``wgrp``) intern the partition key alone.
        # Purged ids go to free lists for reuse (their state rows are
        # zeroed first) — the device analog of dropping idle
        # PartitionInstances.
        self._group_ids: Dict = {}
        self._group_vals: List = []
        self._group_free: List[int] = []
        self._group_last: Dict[int, int] = {}
        self._wgrp_ids: Dict = {}
        self._wgrp_vals: List = []
        self._wgrp_free: List[int] = []
        # per-id last-use times as ARRAYS (vectorized batch touch +
        # purge scan; a dict write per unique key was ~half the cost of
        # a warm partitioned batch)
        self._wgrp_last = np.zeros(self.n_wgroups, dtype=np.int64)
        self._wgrp_in_use = np.zeros(self.n_wgroups, dtype=bool)
        # sorted key index for the vectorized intern fast path (the
        # dense runtime's technique, core/dense_pattern.py:317); falls
        # back to dict probes on mixed/object key dtypes
        self._wgrp_sorted_keys: Optional[np.ndarray] = None
        self._wgrp_sorted_ids: Optional[np.ndarray] = None
        self._wgrp_vector = True
        self.base_ts: Optional[int] = None
        self._pane_end: Optional[int] = None  # timeBatch
        self._pane_fill = 0  # passing events in the open pane
        self._prev_pane_fill = 0  # previous pane's fill (idle detection)

    # -- compilation helpers -------------------------------------------------

    def _as_group_key(self, expr: Expression) -> Optional[int]:
        """Select item that IS a group-by key -> its key index."""
        if not isinstance(expr, Variable):
            return None
        for i, g in enumerate(self.group_raw):
            if isinstance(g, Variable) and g.attribute == expr.attribute:
                return i
        return None

    @staticmethod
    def _as_passthrough(expr: Expression, stream_def, s) -> Optional[str]:
        """Select item that is a bare input-attribute reference -> the
        attribute name (materialized host-side at native width)."""
        if not isinstance(expr, Variable):
            return None
        if expr.stream_id not in (None, s.stream_id, s.alias):
            return None
        if expr.attribute not in stream_def.attribute_names:
            return None
        return expr.attribute

    def _check_value_types(self, stream_def, s, sel):
        """Reject device-evaluated expressions (filters, computed select
        items incl. aggregate arguments, having) that use a LONG
        attribute OUTSIDE a plain comparison, or a LONG constant outside
        int32 range on a non-pair lane: LONG comparisons ride bit-exact
        hi/lo int32 pairs (any magnitude), but LONG arithmetic has no
        64-bit device lane and float32 would silently round above 2^24
        (the reference is per-type exact, executor/math/ &
        condition/compare/).  Group-by keys and bare select items stay
        host-side and may be any type."""
        from siddhi_tpu.query_api import Constant

        names = set(stream_def.attribute_names)
        ids = (None, s.stream_id, s.alias)

        def is_long_var(e):
            return (isinstance(e, Variable) and e.stream_id in ids
                    and e.attribute in names
                    and stream_def.attribute_type(e.attribute)
                    == AttrType.LONG)

        def walk(e):
            if isinstance(e, CompareOp) and (
                    is_long_var(e.left) or is_long_var(e.right)):
                # pair-compare subtree: the device compiler takes the
                # hi/lo path (or raises its own eligibility error when
                # the other side is not pair-able) — any magnitude is
                # bit-exact there
                return e
            if isinstance(e, Variable):
                if is_long_var(e):
                    raise SiddhiAppCreationError(
                        f"device query path: attribute '{e.attribute}' "
                        "is LONG and used outside a plain comparison; "
                        "its hi/lo lanes support comparisons only — "
                        "host engine used (LONG is fine as a group-by "
                        "key, bare select item, or comparison operand)")
                return e
            if (isinstance(e, Constant) and e.type == AttrType.LONG
                    and e.value is not None
                    and not -(2**31) <= int(e.value) < 2**31):
                raise SiddhiAppCreationError(
                    f"device query path: constant {e.value} exceeds the "
                    "int32 device lane — host engine used")
            return _map_children(e, walk)

        for f in self.filter_exprs:
            walk(f)
        for e in self._device_expr_raw:
            walk(e)
        if sel.having is not None:
            walk(sel.having)

    def _flush_expr_ok(self, compiled) -> bool:
        """Flush-time exprs can only read aggregate keys / numeric group
        keys (probed by tracing with exactly that env)."""
        try:
            self._trace_one(compiled, self._flush_env_shapes())
            return True
        except Exception:
            return False

    def _env_shapes(self, B: int = 8):
        import jax

        env = {
            a: jax.ShapeDtypeStruct((B,), self._lane_dtype[a])
            for a in self.attrs
        }
        i32 = jax.ShapeDtypeStruct((B,), np.int32)
        for a in self.long_attrs:
            env[a + "|hi"] = i32
            env[a + "|lo"] = i32
        env[TS_KEY] = i32
        env[N_KEY] = B
        for a in self.aggs:
            env[a.env_key] = jax.ShapeDtypeStruct(
                (B,), np.bool_ if a.kind in ("and", "or") else np.float32)
        return env

    def _flush_env_shapes(self, G: int = 8):
        import jax

        f32 = jax.ShapeDtypeStruct((G,), np.float32)
        env = {
            a.env_key: (jax.ShapeDtypeStruct((G,), np.bool_)
                        if a.kind in ("and", "or") else f32)
            for a in self.aggs
        }
        for i in self._numeric_group_keys:
            g = self.group_raw[i]
            if isinstance(g, Variable):
                env[g.attribute] = f32
        env[N_KEY] = G
        return env

    def _trace_one(self, compiled, shapes):
        import jax

        jax.eval_shape(lambda env: compiled.fn(env), shapes)

    def _trace_check(self):
        """Compile-time eligibility: every expression must be
        jax-traceable (no object-dtype ops, no host-only functions)."""
        shapes = self._env_shapes()
        try:
            for f in self.filters:
                self._trace_one(f, shapes)
            for a in self.aggs:
                if a.arg is not None:
                    self._trace_one(a.arg, shapes)
            for g in self.group_exprs:
                # group keys are evaluated host-side (interning), so any
                # type is fine — no trace needed
                pass
            if self.mode == PER_EVENT:
                for kind, v, _n in self.out_spec:
                    if kind == "expr":
                        self._trace_one(v, shapes)
                if self.having is not None:
                    self._trace_one(self.having, shapes)
            else:
                fshapes = self._flush_env_shapes()
                for kind, v, _n in self.out_spec:
                    if kind == "expr":
                        self._trace_one(v, fshapes)
                if self.having is not None:
                    self._trace_one(self.having, fshapes)
        except SiddhiAppCreationError:
            raise
        except Exception as e:
            raise SiddhiAppCreationError(
                f"query not device-eligible (expression not jax-traceable): {e}"
            ) from e

    # -- state ---------------------------------------------------------------

    def init_state(self):
        jnp = self.jnp
        return {k: jnp.asarray(v) for k, v in self.init_state_host().items()}

    def init_state_host(self):
        """NUMPY zero state (the sharded wrapper builds its shard-major
        layout from this without touching any device backend).  numpy's
        zeros/full/float32/... names match jnp's, so the builder body
        reads identically to a device-side one."""
        jnp = np
        A = max(len(self.aggs), 1)
        G = self.n_groups
        state = {}
        kinds = {a.kind for a in self.aggs}
        if self.kind == "sliding":
            W = self.W
            state["win_vals"] = jnp.zeros((W, A), dtype=jnp.float32)
            state["win_ts"] = jnp.zeros(W, dtype=jnp.int32)
            state["win_grp"] = jnp.zeros(W, dtype=jnp.int32)
            state["win_valid"] = jnp.zeros(W, dtype=bool)
        elif self.kind == "keyed_sliding":
            # per-key ring buffers: each partition key owns one [W] row
            Gw, W = self.n_wgroups, self.W
            state["win_vals"] = jnp.zeros((Gw, W, A), dtype=jnp.float32)
            state["win_ts"] = jnp.zeros((Gw, W), dtype=jnp.int32)
            state["win_grp"] = jnp.zeros((Gw, W), dtype=jnp.int32)
            state["win_valid"] = jnp.zeros((Gw, W), dtype=bool)
            state["win_count"] = jnp.zeros(Gw, dtype=jnp.int32)
        elif self.kind in ("running", "tumbling"):
            if kinds & set(_SUM_KINDS):
                state["acc_sum"] = jnp.zeros((G, A), dtype=jnp.float32)
            if "stdDev" in kinds:
                state["acc_sumsq"] = jnp.zeros((G, A), dtype=jnp.float32)
            # counts always kept: cheap, and avg/flush-valid need them
            state["acc_cnt"] = jnp.zeros((G, A), dtype=jnp.float32)
            if "min" in kinds:
                state["acc_min"] = jnp.full((G, A), jnp.inf, dtype=jnp.float32)
            if "max" in kinds:
                state["acc_max"] = jnp.full((G, A), -jnp.inf, dtype=jnp.float32)
            if self.kind == "tumbling":
                state["touched"] = jnp.zeros(G, dtype=bool)
                K = max(len(self._numeric_group_keys), 1)
                state["grp_keys"] = jnp.zeros((G, K), dtype=jnp.float32)
        # all-time accumulators (minForever/maxForever): per agg group,
        # NEVER reset by window expiry or tumbling flushes
        if self.kind in ("running", "tumbling", "sliding", "keyed_sliding"):
            if "minForever" in kinds:
                state["acc_minf"] = jnp.full((G, A), jnp.inf,
                                             dtype=jnp.float32)
            if "maxForever" in kinds:
                state["acc_maxf"] = jnp.full((G, A), -jnp.inf,
                                             dtype=jnp.float32)
        return state

    # -- steps ---------------------------------------------------------------

    def _base_env(self, cols, ts, B):
        env = {a: cols[a] for a in self.attrs if a in cols}
        for a in self.long_attrs:
            hk, lk = a + "|hi", a + "|lo"
            if hk in cols:
                env[hk] = cols[hk]
                env[lk] = cols[lk]
        env[TS_KEY] = ts
        env[N_KEY] = B
        return env

    def _filter_mask(self, env, valid):
        jnp = self.jnp
        m = valid
        for f in self.filters:
            m = m & jnp.asarray(f.fn(env)).astype(bool)
        return m

    def _arg_vals(self, env, B):
        """[B, A] float32 aggregate-argument values (count -> ones)."""
        jnp = self.jnp
        if not self.aggs:
            return jnp.ones((B, 1), dtype=jnp.float32)
        cols = []
        for a in self.aggs:
            if a.arg is None:
                cols.append(jnp.ones(B, dtype=jnp.float32))
            else:
                v = jnp.asarray(a.arg.fn(env)).astype(jnp.float32)
                cols.append(jnp.broadcast_to(v, (B,)))
        return jnp.stack(cols, axis=-1)

    def _emit(self, env_out, fmask, B):
        """Evaluate select items / having -> (out_valid, {name: [B]}).

        Each computed column keeps a dtype matching its declared type —
        INT expressions stay int32 end-to-end (bit-exact), BOOL stays
        bool; everything else is float32 — instead of rounding through
        one shared float32 matrix."""
        jnp = self.jnp
        out = {}
        for oi, (kind, v, name) in enumerate(self.out_spec):
            if kind in ("group_key", "passthrough"):
                continue  # materialized host-side
            col = jnp.asarray(v.fn(env_out))
            if v.type == AttrType.INT:
                col = col.astype(jnp.int32)
            elif v.type == AttrType.BOOL:
                col = col.astype(bool)
            else:
                col = col.astype(jnp.float32)
            out[name] = jnp.broadcast_to(col, (B,))
        if self.having is not None:
            fmask = fmask & jnp.asarray(self.having.fn(env_out)).astype(bool)
        return fmask, out

    def _finalize_aggs(self, env_out, wsum, wcnt, wsumsq=None, wmin=None,
                       wmax=None, fmin=None, fmax=None):
        """Map reduced moments to aggregator output lanes.  ``wsum`` /
        ``wcnt`` are the masked window (or running-total) sum and count
        per row; ``wsumsq`` the sum of squares (stdDev); ``wmin/wmax``
        the window min/max; ``fmin/fmax`` the all-time accumulators.
        and/or reduce over their bool argument lane: and = no false
        member (count == sum), or = some true member (sum > 0) — the
        reference's true/false counters
        (query/selector/attribute/aggregator/
        AndAttributeAggregatorExecutor.java) as masked sums."""
        jnp = self.jnp
        for ai, a in enumerate(self.aggs):
            k = a.kind
            if k == "sum":
                env_out[a.env_key] = wsum[:, ai]
            elif k == "count":
                env_out[a.env_key] = wcnt[:, 0]
            elif k == "avg":
                env_out[a.env_key] = wsum[:, ai] / jnp.maximum(wcnt[:, 0], 1.0)
            elif k == "stdDev":
                # population stddev from (sum, sumsq, n) — the host
                # StdDevAgg decomposition in float32
                nn = jnp.maximum(wcnt[:, 0], 1.0)
                mean = wsum[:, ai] / nn
                var = jnp.maximum(wsumsq[:, ai] / nn - mean * mean, 0.0)
                env_out[a.env_key] = jnp.sqrt(var)
            elif k == "min":
                env_out[a.env_key] = wmin[:, ai]
            elif k == "max":
                env_out[a.env_key] = wmax[:, ai]
            elif k == "minForever":
                env_out[a.env_key] = fmin[:, ai]
            elif k == "maxForever":
                env_out[a.env_key] = fmax[:, ai]
            elif k == "and":
                env_out[a.env_key] = (wcnt[:, 0] - wsum[:, ai]) < 0.5
            else:  # or
                env_out[a.env_key] = wsum[:, ai] > 0.5

    def _kinds(self):
        return {a.kind for a in self.aggs}

    def _prefix_minmax(self, argvals, grp, fmask, B, need_min, need_max):
        """Within-batch same-group running min/max including self
        ([B, A] each; None when not needed)."""
        jnp = self.jnp
        tri = jnp.tril(jnp.ones((B, B), dtype=bool))
        same = tri & (grp[:, None] == grp[None, :]) & fmask[None, :]
        big = jnp.float32(np.inf)
        pmin = pmax = None
        if need_min:
            pmin = jnp.min(
                jnp.where(same[:, :, None], argvals[None, :, :], big), axis=1)
        if need_max:
            pmax = jnp.max(
                jnp.where(same[:, :, None], argvals[None, :, :], -big), axis=1)
        return pmin, pmax

    def _forever_rows(self, state, argvals, grp, fmask, B,
                      pmin=None, pmax=None):
        """Per-row all-time min/max ([B, A]) = pre-batch accumulator
        combined with the within-batch same-group prefix (callers that
        already computed the prefix pass it in to avoid tracing the
        [B, B, A] reduction twice)."""
        jnp = self.jnp
        kinds = self._kinds()
        need_min = "minForever" in kinds and pmin is None
        need_max = "maxForever" in kinds and pmax is None
        if need_min or need_max:
            cmin, cmax = self._prefix_minmax(
                argvals, grp, fmask, B, need_min, need_max)
            pmin = pmin if pmin is not None else cmin
            pmax = pmax if pmax is not None else cmax
        fmin = fmax = None
        if "minForever" in kinds:
            fmin = jnp.minimum(state["acc_minf"][grp], pmin)
        if "maxForever" in kinds:
            fmax = jnp.maximum(state["acc_maxf"][grp], pmax)
        return fmin, fmax

    def _forever_scatter(self, state, new_state, argvals, grp, fmask):
        jnp = self.jnp
        upd = fmask[:, None]
        if "acc_minf" in state:
            new_state["acc_minf"] = state["acc_minf"].at[grp].min(
                jnp.where(upd, argvals, jnp.inf))
        if "acc_maxf" in state:
            new_state["acc_maxf"] = state["acc_maxf"].at[grp].max(
                jnp.where(upd, argvals, -jnp.inf))

    def _forever_block(self, state, argvals, grp, fmask, B, rows, grp_b):
        """Per-output-row all-time min/max for a row block ([nb, A]):
        the same-group prefix mask compares each selected output row
        (global index ``rows[i]``) against the WHOLE batch, so a block
        decomposition reduces exactly the rows the full-batch
        ``_forever_rows`` would."""
        jnp = self.jnp
        kinds = self._kinds()
        if not (kinds & {"minForever", "maxForever"}):
            return None, None
        le = rows[:, None] >= jnp.arange(B)[None, :]
        same = le & (grp_b[:, None] == grp[None, :]) & fmask[None, :]
        big = jnp.float32(np.inf)
        fmin = fmax = None
        if "minForever" in kinds:
            pmin = jnp.min(
                jnp.where(same[:, :, None], argvals[None, :, :], big), axis=1)
            fmin = jnp.minimum(state["acc_minf"][grp_b], pmin)
        if "maxForever" in kinds:
            pmax = jnp.max(
                jnp.where(same[:, :, None], argvals[None, :, :], -big), axis=1)
            fmax = jnp.maximum(state["acc_maxf"][grp_b], pmax)
        return fmin, fmax

    def _sliding_step(self, state, env, fmask, ts, grp, B,
                      r0=None, nb=None):
        """Global sliding-window step body.  ``r0``/``nb`` select a
        contiguous output-row block: the ring-buffer evolution (cheap,
        O(B + W)) is always computed over the WHOLE batch, but the
        O(B*W) window gather/reduction and the emit evaluation run only
        for rows [r0, r0+nb) — the sharded wrapper splits that work
        across the mesh's batch axis while keeping the ring replicated.
        The defaults (r0=None) cover the whole batch, i.e. the
        single-device step; the block decomposition is bit-identical
        because each output row's window reduction is unchanged.
        Returns (new_state, ov[nb], out {name: [nb]})."""
        jnp = self.jnp
        W = self.W
        A = max(len(self.aggs), 1)
        argvals = self._arg_vals(env, B)  # [B, A]
        pos = jnp.cumsum(fmask.astype(jnp.int32)) - 1  # [B]
        n_pass = jnp.sum(fmask.astype(jnp.int32))
        sidx = jnp.where(fmask, pos, B)  # dump lane B
        comp_vals = jnp.zeros((B + 1, A), jnp.float32).at[sidx].set(argvals)[:B]
        comp_ts = jnp.zeros(B + 1, jnp.int32).at[sidx].set(ts)[:B]
        comp_grp = jnp.zeros(B + 1, jnp.int32).at[sidx].set(grp)[:B]
        comp_valid = (jnp.zeros(B + 1, bool)
                      .at[sidx].set(jnp.ones(B, bool))[:B])
        cat_vals = jnp.concatenate([state["win_vals"], comp_vals], 0)
        cat_ts = jnp.concatenate([state["win_ts"], comp_ts], 0)
        cat_grp = jnp.concatenate([state["win_grp"], comp_grp], 0)
        cat_valid = jnp.concatenate([state["win_valid"], comp_valid], 0)
        dyn = self.jax.lax.dynamic_slice_in_dim
        if nb is None:
            nb = B
            rows = jnp.arange(B)
            blk = lambda x: x  # noqa: E731 — whole batch, no slicing
        else:
            rows = r0 + jnp.arange(nb)
            blk = lambda x: dyn(x, r0, nb, axis=0)  # noqa: E731
        pos_b = blk(pos)
        grp_b = blk(grp)
        ts_b = blk(ts)
        fmask_b = blk(fmask)
        env_b = {k: blk(v) for k, v in env.items() if k != N_KEY}
        env_b[N_KEY] = nb
        # window of output row i: concat positions pos[i]+1 .. pos[i]+W
        # (the W entries ending at the row itself)
        gidx = pos_b[:, None] + 1 + jnp.arange(W)[None, :]  # [nb, W]
        gidx = jnp.clip(gidx, 0, W + B - 1)
        w_vals = cat_vals[gidx]  # [nb, W, A]
        member = cat_valid[gidx] & (cat_grp[gidx] == grp_b[:, None])
        if self.window_name == "time":
            T = self.window_param
            member = member & (cat_ts[gidx] > (ts_b[:, None] - T))
        mf = member.astype(jnp.float32)[:, :, None]
        env_out = dict(env_b)
        kinds = self._kinds()
        wsum = jnp.sum(w_vals * mf, axis=1)  # [nb, A]
        wcnt = jnp.sum(mf, axis=1)  # [nb, 1]
        wsumsq = (jnp.sum(w_vals * w_vals * mf, axis=1)
                  if "stdDev" in kinds else None)
        m3 = member[:, :, None]
        wmin = (jnp.min(jnp.where(m3, w_vals, jnp.inf), axis=1)
                if "min" in kinds else None)
        wmax = (jnp.max(jnp.where(m3, w_vals, -jnp.inf), axis=1)
                if "max" in kinds else None)
        fmin, fmax = self._forever_block(state, argvals, grp, fmask, B,
                                         rows, grp_b)
        self._finalize_aggs(env_out, wsum, wcnt, wsumsq, wmin, wmax,
                            fmin, fmax)
        ov, out = self._emit(env_out, fmask_b, nb)
        # new buffer = last W entries ending at the batch's final
        # passing row: concat[n_pass : n_pass + W]
        start = jnp.clip(n_pass, 0, B)
        new_state = dict(state)
        new_state["win_vals"] = dyn(cat_vals, start, W, axis=0)
        new_state["win_ts"] = dyn(cat_ts, start, W, axis=0)
        new_state["win_grp"] = dyn(cat_grp, start, W, axis=0)
        new_state["win_valid"] = dyn(cat_valid, start, W, axis=0)
        self._forever_scatter(state, new_state, argvals, grp, fmask)
        return new_state, ov, out

    def make_step(self, jit: bool = True) -> Callable:
        """Per-event step (filter / running / sliding / keyed_sliding):

        step(state, cols {attr: [B] f32}, ts[B] i32 relative-ms,
             grp[B] i32, wgrp[B] i32 (window group; partition mode only),
             valid[B] bool)
          -> (state, out_valid[B], out_vals[B, n_out], n_match scalar i32)

        ``n_match`` is the async-emit count gate: the host fetches this
        ONE scalar per batch and skips the column fetch entirely when it
        is zero (the common case for selective filters).
        """
        key = ("step", jit)
        if key in self._step_cache:
            return self._step_cache[key]
        jnp = self.jnp
        A = max(len(self.aggs), 1)

        def step(state, cols, ts, grp, wgrp, valid):
            B = ts.shape[0]
            env = self._base_env(cols, ts, B)
            fmask = self._filter_mask(env, valid)

            if self.kind == "filter":
                env_out = env
                ov, out = self._emit(env_out, fmask, B)
                return state, ov, out

            argvals = self._arg_vals(env, B)  # [B, A]

            if self.kind == "running":
                # within-batch same-group prefix (includes self): the
                # [B, B] masked matmul rides the MXU
                tri = jnp.tril(jnp.ones((B, B), dtype=jnp.float32))
                same = (grp[:, None] == grp[None, :]) & fmask[None, :]
                m = tri * same.astype(jnp.float32)  # [B, B]
                masked_vals = argvals * fmask[:, None].astype(jnp.float32)
                psum = m @ masked_vals  # [B, A]
                pcnt = m @ fmask[:, None].astype(jnp.float32)  # [B, 1]
                kinds = self._kinds()
                prev_sum = state.get("acc_sum")
                wsum = ((prev_sum[grp] if prev_sum is not None else 0.0)
                        + psum)
                wcnt = state["acc_cnt"][grp][:, :1] + pcnt
                wsumsq = None
                if "acc_sumsq" in state:
                    wsumsq = (state["acc_sumsq"][grp]
                              + m @ (masked_vals * argvals))
                # one prefix pass covers min/max AND the forever pair
                wmin = wmax = None
                pmin, pmax = self._prefix_minmax(
                    argvals, grp, fmask, B,
                    bool(kinds & {"min", "minForever"}),
                    bool(kinds & {"max", "maxForever"}))
                if "min" in kinds:
                    wmin = jnp.minimum(state["acc_min"][grp], pmin)
                if "max" in kinds:
                    wmax = jnp.maximum(state["acc_max"][grp], pmax)
                fmin, fmax = self._forever_rows(state, argvals, grp,
                                                fmask, B, pmin, pmax)
                env_out = dict(env)
                self._finalize_aggs(env_out, wsum, wcnt, wsumsq, wmin,
                                    wmax, fmin, fmax)
                # state update (scatter; duplicate group rows combine)
                new_state = dict(state)
                upd = fmask[:, None]
                if "acc_sum" in state:
                    new_state["acc_sum"] = state["acc_sum"].at[grp].add(
                        jnp.where(upd, argvals, 0.0))
                if "acc_sumsq" in state:
                    new_state["acc_sumsq"] = state["acc_sumsq"].at[grp].add(
                        jnp.where(upd, argvals * argvals, 0.0))
                new_state["acc_cnt"] = state["acc_cnt"].at[grp].add(
                    jnp.where(upd, jnp.ones_like(argvals), 0.0))
                if "acc_min" in state:
                    new_state["acc_min"] = state["acc_min"].at[grp].min(
                        jnp.where(upd, argvals, jnp.inf))
                if "acc_max" in state:
                    new_state["acc_max"] = state["acc_max"].at[grp].max(
                        jnp.where(upd, argvals, -jnp.inf))
                self._forever_scatter(state, new_state, argvals, grp, fmask)
                ov, out = self._emit(env_out, fmask, B)
                return new_state, ov, out

            if self.kind == "keyed_sliding":
                return self._keyed_sliding_step(
                    state, env, fmask, ts, grp, wgrp, B)

            # sliding: compact passing rows, gather [B, W] windows
            return self._sliding_step(state, env, fmask, ts, grp, B)

        def step_counted(state, cols, ts, grp, wgrp, valid):
            new_state, ov, out = step(state, cols, ts, grp, wgrp, valid)
            n = jnp.sum((ov.astype(bool) & valid).astype(jnp.int32))
            return new_state, ov, out, n

        fn = (self.jax.jit(step_counted, donate_argnums=(0,)) if jit
              else step_counted)
        self._step_cache[key] = fn
        return fn

    def _keyed_sliding_step(self, state, env, fmask, ts, grp, wgrp, B):
        """Per-key sliding window (partition mode): each window group
        (partition key) owns one [W] ring-buffer row, so a row's window
        is ITS key's last W passing events — the reference's
        per-instance window (partition/PartitionStreamReceiver.java:
        82-118) as [n_wgroups, W] device state.  Aggregation masks
        further restrict to the composed (key, group-by) group.  All
        batch work is [B, B] / [B, W] masked reductions (the [B, B]
        matmul rides the MXU); state updates are unique-slot scatters."""
        jnp = self.jnp
        W = self.W
        # row count from the state, not self.n_wgroups: under the
        # sharded wrapper each shard sees only its slice of the window
        # groups (plus a scratch row), and every scatter below must pad
        # against the LOCAL row count
        Gw = state["win_count"].shape[0]
        argvals = self._arg_vals(env, B)  # [B, A]
        tril = jnp.tril(jnp.ones((B, B), dtype=bool))
        samew = (wgrp[:, None] == wgrp[None, :]) & fmask[None, :]
        # passing rank within the row's window group (includes self)
        r = jnp.sum(samew & tril, axis=1).astype(jnp.int32)  # [B]
        n_w = jnp.sum(samew, axis=1).astype(jnp.int32)  # whole-batch count
        # batch-side membership: among the last W passing events of the
        # row's window group
        mb = samew & tril & ((r[:, None] - r[None, :]) < W)
        # buffer-side membership: recency rank (0 = newest buffered)
        # shifted by the r batch arrivals that displace old entries
        b_vals = state["win_vals"][wgrp]  # [B, W, A]
        b_ts = state["win_ts"][wgrp]  # [B, W]
        b_grp = state["win_grp"][wgrp]  # [B, W]
        b_valid = state["win_valid"][wgrp]  # [B, W]
        cnt = state["win_count"][wgrp]  # [B]
        slots = jnp.arange(W)[None, :]
        rec = jnp.mod(cnt[:, None] - 1 - slots, W)
        mbuf = b_valid & ((rec + r[:, None]) < W)
        if self.window_name == "time":
            T = self.window_param
            mb = mb & (ts[None, :] > (ts[:, None] - T))
            mbuf = mbuf & (b_ts > (ts[:, None] - T))
        # aggregation masks: composed group within the key's window
        mba = mb & (grp[None, :] == grp[:, None])
        mbufa = mbuf & (b_grp == grp[:, None])
        f32 = jnp.float32
        kinds = self._kinds()
        bsum = mba.astype(f32) @ argvals  # [B, A]
        bcnt = jnp.sum(mba, axis=1).astype(f32)[:, None]  # [B, 1]
        usum = jnp.sum(b_vals * mbufa.astype(f32)[:, :, None], axis=1)
        ucnt = jnp.sum(mbufa, axis=1).astype(f32)[:, None]
        wsum = bsum + usum
        wcnt = bcnt + ucnt
        wsumsq = None
        if "stdDev" in kinds:
            wsumsq = (mba.astype(f32) @ (argvals * argvals)
                      + jnp.sum(b_vals * b_vals
                                * mbufa.astype(f32)[:, :, None], axis=1))
        env_out = dict(env)
        big = jnp.float32(np.inf)
        wmin = wmax = None
        if "min" in kinds:
            wmin = jnp.minimum(
                jnp.min(jnp.where(mba[:, :, None], argvals[None, :, :], big),
                        axis=1),
                jnp.min(jnp.where(mbufa[:, :, None], b_vals, big), axis=1))
        if "max" in kinds:
            wmax = jnp.maximum(
                jnp.max(jnp.where(mba[:, :, None], argvals[None, :, :], -big),
                        axis=1),
                jnp.max(jnp.where(mbufa[:, :, None], b_vals, -big), axis=1))
        fmin, fmax = self._forever_rows(state, argvals, grp, fmask, B)
        self._finalize_aggs(env_out, wsum, wcnt, wsumsq, wmin, wmax,
                            fmin, fmax)
        ov, out = self._emit(env_out, fmask, B)
        # state update: each kept passing row scatters to its ring slot
        # (slot = (count + r - 1) mod W).  Rows already displaced within
        # this batch, and padded/filtered rows, dump to the scratch row
        # Gw so no two real writes ever collide.
        keep = fmask & ((n_w - r) < W)
        slot = jnp.mod(cnt + r - 1, W)
        widx = jnp.where(keep, wgrp, Gw)

        def pad(x):
            return jnp.concatenate(
                [x, jnp.zeros((1,) + x.shape[1:], x.dtype)], axis=0)

        new_state = dict(state)
        new_state["win_vals"] = (
            pad(state["win_vals"]).at[widx, slot].set(argvals)[:Gw])
        new_state["win_ts"] = (
            pad(state["win_ts"]).at[widx, slot].set(ts)[:Gw])
        new_state["win_grp"] = (
            pad(state["win_grp"]).at[widx, slot].set(grp)[:Gw])
        new_state["win_valid"] = (
            pad(state["win_valid"]).at[widx, slot].set(True)[:Gw])
        new_state["win_count"] = (
            pad(state["win_count"])
            .at[jnp.where(fmask, wgrp, Gw)].add(1)[:Gw])
        self._forever_scatter(state, new_state, argvals, grp, fmask)
        return new_state, ov, out

    def make_acc_step(self, jit: bool = True) -> Callable:
        """Tumbling accumulate step:
        (state, cols, ts, grp, grp_key_vals[B,K], valid)
          -> (state, n_passing)."""
        key = ("acc", jit)
        if key in self._step_cache:
            return self._step_cache[key]
        jnp = self.jnp
        K = max(len(self._numeric_group_keys), 1)

        def acc(state, cols, ts, grp, gkv, valid):
            B = ts.shape[0]
            env = self._base_env(cols, ts, B)
            fmask = self._filter_mask(env, valid)
            argvals = self._arg_vals(env, B)
            upd = fmask[:, None]
            new_state = dict(state)
            if "acc_sum" in state:
                new_state["acc_sum"] = state["acc_sum"].at[grp].add(
                    jnp.where(upd, argvals, 0.0))
            if "acc_sumsq" in state:
                new_state["acc_sumsq"] = state["acc_sumsq"].at[grp].add(
                    jnp.where(upd, argvals * argvals, 0.0))
            new_state["acc_cnt"] = state["acc_cnt"].at[grp].add(
                jnp.where(upd, jnp.ones_like(argvals), 0.0))
            if "acc_min" in state:
                new_state["acc_min"] = state["acc_min"].at[grp].min(
                    jnp.where(upd, argvals, jnp.inf))
            if "acc_max" in state:
                new_state["acc_max"] = state["acc_max"].at[grp].max(
                    jnp.where(upd, argvals, -jnp.inf))
            self._forever_scatter(state, new_state, argvals, grp, fmask)
            new_state["touched"] = state["touched"].at[grp].max(fmask)
            # group-key registers: scatter only PASSING rows (filtered
            # rows go to a dump row G) — a same-batch passing+filtered
            # pair for one group would otherwise write two different
            # values in XLA-undefined order; every value written to a
            # real group row is the true (constant-per-group) key
            G = state["grp_keys"].shape[0]
            dump_idx = jnp.where(fmask, grp, G)
            padded = jnp.concatenate(
                [state["grp_keys"],
                 jnp.zeros((1,) + state["grp_keys"].shape[1:], jnp.float32)],
                axis=0)
            new_state["grp_keys"] = padded.at[dump_idx].set(
                gkv.astype(jnp.float32))[:G]
            return new_state, jnp.sum(fmask.astype(jnp.int32))

        fn = self.jax.jit(acc, donate_argnums=(0,)) if jit else acc
        self._step_cache[key] = fn
        return fn

    def make_flush_step(self, jit: bool = True,
                        n_rows: Optional[int] = None) -> Callable:
        """Tumbling flush: (state) -> (state, flush_valid[G],
        out[G, n_out], n_match scalar i32) — the count gates the host
        fetch exactly like make_step's.

        ``n_rows`` overrides the accumulator row count (default
        ``self.n_groups``): the sharded wrapper traces this body per
        shard over its local rows-per-shard slice (whose scratch row is
        never touched, so it never emits)."""
        key = ("flush", jit, n_rows)
        if key in self._step_cache:
            return self._step_cache[key]
        jnp = self.jnp
        G = self.n_groups if n_rows is None else int(n_rows)

        def flush(state):
            env = {N_KEY: G}
            self._finalize_aggs(
                env,
                state.get("acc_sum", state["acc_cnt"]),
                state["acc_cnt"][:, :1],
                state.get("acc_sumsq"),
                state.get("acc_min"),
                state.get("acc_max"),
                state.get("acc_minf"),
                state.get("acc_maxf"),
            )
            for ki, i in enumerate(self._numeric_group_keys):
                g = self.group_raw[i]
                if isinstance(g, Variable):
                    env[g.attribute] = state["grp_keys"][:, ki]
            valid = state["touched"]
            ov, out = self._emit(env, valid, G)
            # pane reset: sums/counts/min/max restart; the all-time
            # minForever/maxForever accumulators survive flushes
            new_state = dict(state)
            for k in ("acc_sum", "acc_cnt", "acc_sumsq"):
                if k in state:
                    new_state[k] = jnp.zeros_like(state[k])
            if "acc_min" in state:
                new_state["acc_min"] = jnp.full_like(state["acc_min"], jnp.inf)
            if "acc_max" in state:
                new_state["acc_max"] = jnp.full_like(state["acc_max"], -jnp.inf)
            new_state["touched"] = jnp.zeros_like(state["touched"])
            return new_state, ov, out, jnp.sum(ov.astype(jnp.int32))

        fn = self.jax.jit(flush, donate_argnums=(0,)) if jit else flush
        self._step_cache[key] = fn
        return fn

    # -- host wrapper --------------------------------------------------------

    # re-anchor before relative ms approach int32 range (~24.8 days of
    # stream time); headroom covers one batch + window horizon
    _REL_LIMIT = 2**31 - 2**24

    def _re_anchor(self, state, rel64: np.ndarray):
        """Shift base_ts forward so relative timestamps stay well inside
        int32 (they silently wrap after ~24.8 days otherwise — sliding
        time windows and timeBatch panes would corrupt).  Live window
        entries and the open pane boundary shift with it."""
        horizon = (
            int(self.window_param) if self.window_name in ("time", "timeBatch")
            else 0
        )
        delta = int(rel64.min()) - 1 - horizon
        # all representability checks BEFORE any mutation, so a caller
        # catching the error keeps a consistent (anchor, window-state)
        # pair for subsequent batches
        if delta <= 0 or int(rel64.max()) - delta >= 2**31:
            raise SiddhiAppRuntimeError(
                "device query: timestamp span of one batch plus the window "
                "horizon exceeds the int32 relative-time range")
        self.base_ts += delta
        rel64 = rel64 - delta
        if "win_ts" in state:
            state = dict(state)
            # entries older than the horizon go negative and stay
            # excluded; a delta beyond int32 means EVERY buffered entry
            # is expired, so the shift clamps (old values in [0, 2^31)
            # minus the clamp land in (-2^31, 1) — no wrap either way)
            shift = np.int32(min(delta, 2**31 - 1))
            state["win_ts"] = state["win_ts"] - shift
        if self._pane_end is not None:
            self._pane_end -= delta
        return state, rel64

    def _host_env(self, cols: Dict[str, np.ndarray], ts: np.ndarray,
                  n: int) -> Dict:
        env = {a: np.asarray(cols[a]) for a in self.all_attrs if a in cols}
        env[TS_KEY] = np.asarray(ts)
        env[N_KEY] = n
        return env

    def _intern_groups(self, cols: Dict[str, np.ndarray],
                       ts: np.ndarray, n: int,
                       pk: Optional[np.ndarray] = None,
                       now: Optional[int] = None) -> np.ndarray:
        """Evaluate group-key exprs host-side and intern to dense ids.
        In partition mode (``pk`` given) the interned key is the
        composed tuple ``(partition_key, *group_keys)``."""
        if not self.group_exprs and pk is None:
            return np.zeros(n, dtype=np.int32)
        env = self._host_env(cols, ts, n)
        key_cols = [np.broadcast_to(np.asarray(g.fn(env)), (n,))
                    for g in self.group_exprs]
        if pk is not None:
            key_cols = [np.broadcast_to(pk, (n,))] + key_cols
        if len(key_cols) == 1 and pk is None:
            try:
                # vectorized: factorize the batch once; one dict probe
                # per UNIQUE value instead of per event
                uniq, inv = np.unique(key_cols[0], return_inverse=True)
            except TypeError:  # unorderable (None in an object column)
                return self._intern_rows(key_cols, n, now, scalar=True)
            out_u = np.empty(len(uniq), dtype=np.int32)
            for i, k in enumerate(uniq.tolist()):
                out_u[i] = self._alloc_group(k, now)
            return out_u[inv].astype(np.int32, copy=False)
        # multi-column / composed keys: combine per-column factor codes
        # so the dict is probed once per UNIQUE combination, not per
        # row.  Falls back to the exact per-row probe when a column is
        # unorderable (None in an object column) or the radix product
        # would overflow int64 (which would silently merge distinct
        # combinations).
        try:
            code = np.zeros(n, dtype=np.int64)
            radix = 1
            for c in key_cols:
                u, inv = np.unique(c, return_inverse=True)
                radix *= len(u) + 1
                if radix > 2**62:
                    raise OverflowError("group-key radix product")
                code = code * (len(u) + 1) + inv
        except (TypeError, OverflowError):
            return self._intern_rows(key_cols, n, now)
        _uc, first, cinv = np.unique(
            code, return_index=True, return_inverse=True)
        out_u = np.empty(len(first), dtype=np.int32)
        for j, fi in enumerate(first.tolist()):
            k = tuple(c[fi].item() if hasattr(c[fi], "item") else c[fi]
                      for c in key_cols)
            out_u[j] = self._alloc_group(k, now)
        return out_u[cinv].astype(np.int32, copy=False)

    def _intern_rows(self, key_cols, n: int, now, scalar: bool = False
                     ) -> np.ndarray:
        """Exact per-row interning (the fallback for unorderable or
        radix-overflowing key columns)."""
        out = np.empty(n, dtype=np.int32)
        for i in range(n):
            parts = tuple(c[i].item() if hasattr(c[i], "item") else c[i]
                          for c in key_cols)
            out[i] = self._alloc_group(parts[0] if scalar else parts, now)
        return out

    @staticmethod
    def _alloc_id(k, ids: Dict, vals: List, free: List[int],
                  last: Dict, limit: int, what: str,
                  now: Optional[int]) -> int:
        """Shared free-listed id allocator for group/window-group
        interning (purged ids are reused after their rows are zeroed)."""
        gid = ids.get(k)
        if gid is None:
            if free:
                gid = free.pop()
                vals[gid] = k
            else:
                gid = len(vals)
                if gid >= limit:
                    raise SiddhiAppRuntimeError(what)
                vals.append(k)
            ids[k] = gid
        if now is not None:
            last[gid] = now
        return gid

    def _alloc_group(self, k, now: Optional[int] = None) -> int:
        return self._alloc_id(
            k, self._group_ids, self._group_vals, self._group_free,
            self._group_last, self.n_groups,
            f"device query: group cardinality exceeded "
            f"n_groups={self.n_groups}", now)

    _WGRP_CAP_MSG = (
        "device query: partition-key cardinality exceeded "
        "{cap} (raise @app:execution partitions or enable @purge)")

    def _intern_wgroups(self, pk: np.ndarray, now: int) -> np.ndarray:
        """Partition-key values -> dense window-group ids.

        Vectorized: one np.unique per batch; EXISTING keys resolve with
        one searchsorted against a sorted key index; only never-seen
        keys take the python allocation path; last-use stamps update as
        one array scatter.  Object/mixed key dtypes degrade permanently
        to exact per-unique dict probes (same contract as
        core/dense_pattern.py:317 intern_keys)."""
        arr = np.asarray(pk)
        if self._wgrp_vector:
            sk = self._wgrp_sorted_keys
            if arr.dtype.kind in ("O", "V"):
                self._wgrp_vector = False
            elif sk is not None and len(sk) and arr.dtype != sk.dtype:
                if np.can_cast(arr.dtype, sk.dtype, "safe"):
                    arr = arr.astype(sk.dtype)
                elif np.can_cast(sk.dtype, arr.dtype, "safe"):
                    self._wgrp_sorted_keys = sk.astype(arr.dtype)
                else:
                    self._wgrp_vector = False
        if not self._wgrp_vector:
            uniq, inv = np.unique(arr, return_inverse=True)
            out_u = np.empty(len(uniq), dtype=np.int32)
            for i, k in enumerate(uniq.tolist()):
                out_u[i] = self._alloc_wgrp(k, now)
            return out_u[inv].astype(np.int32, copy=False)

        uniq, inv = np.unique(arr, return_inverse=True)
        nu = len(uniq)
        out_u = np.empty(nu, dtype=np.int32)
        sk = self._wgrp_sorted_keys
        if sk is not None and len(sk):
            pos = np.searchsorted(sk, uniq)
            pos_c = np.minimum(pos, len(sk) - 1)
            found = sk[pos_c] == uniq
            out_u[found] = self._wgrp_sorted_ids[pos_c[found]]
            new_idx = np.flatnonzero(~found)
        else:
            new_idx = np.arange(nu)
        if len(new_idx):
            n_new = len(new_idx)
            take_free = min(len(self._wgrp_free), n_new)
            fresh = n_new - take_free
            if len(self._wgrp_vals) + fresh > self.n_wgroups:
                raise SiddhiAppRuntimeError(
                    self._WGRP_CAP_MSG.format(cap=self.n_wgroups))
            ids = np.empty(n_new, dtype=np.int32)
            if take_free:
                ids[:take_free] = self._wgrp_free[-take_free:][::-1]
                del self._wgrp_free[-take_free:]
            if fresh:
                base = len(self._wgrp_vals)
                ids[take_free:] = np.arange(base, base + fresh,
                                            dtype=np.int32)
                self._wgrp_vals.extend(uniq[new_idx][take_free:].tolist())
            new_keys = uniq[new_idx]
            for k, wid in zip(new_keys.tolist(), ids.tolist()):
                self._wgrp_ids[k] = wid
                self._wgrp_vals[wid] = k
            out_u[new_idx] = ids
            # merge the (sorted) new keys into the sorted index
            if sk is None or not len(sk):
                self._wgrp_sorted_keys = new_keys.copy()
                self._wgrp_sorted_ids = ids.copy()
            else:
                ins = np.searchsorted(sk, new_keys)
                self._wgrp_sorted_keys = np.insert(sk, ins, new_keys)
                self._wgrp_sorted_ids = np.insert(
                    self._wgrp_sorted_ids, ins, ids)
        self._wgrp_last[out_u] = now
        self._wgrp_in_use[out_u] = True
        return out_u[inv].astype(np.int32, copy=False)

    def _alloc_wgrp(self, k, now: int) -> int:
        # the shared allocator writes last[wid] = now, which indexes the
        # ndarray the same way it indexed the old dict
        wid = self._alloc_id(
            k, self._wgrp_ids, self._wgrp_vals, self._wgrp_free,
            self._wgrp_last, self.n_wgroups,
            self._WGRP_CAP_MSG.format(cap=self.n_wgroups), now)
        self._wgrp_in_use[wid] = True
        return wid

    def purge_idle_keys(self, state, now: int, idle_ms: Optional[int],
                        remap=None, wremap=None):
        """Reclaim device state rows of partition keys idle for
        ``idle_ms`` (the analog of PartitionRuntime dropping idle
        per-key instances; ids return to the free lists after their
        rows are zeroed).  ``remap`` maps logical group ids to state
        row ids and ``wremap`` window-group ids to ring-buffer row ids
        (the sharded wrapper's shard-major bijections; identity by
        default).  Returns ``(state, n_purged_keys)``."""
        if not self.partition_mode or idle_ms is None:
            return state, 0
        dead_w = np.flatnonzero(
            self._wgrp_in_use & (now - self._wgrp_last >= idle_ms)
        ).tolist()
        if not dead_w:
            return state, 0
        jnp = self.jnp
        state = dict(state)
        dead_pk = {self._wgrp_vals[w] for w in dead_w}
        if self.group_exprs:
            # composed groups die with their partition key (the host
            # instance dies whole); key-active groups stay even if the
            # group itself has been quiet
            dead_g = [gid for k, gid in self._group_ids.items()
                      if k[0] in dead_pk]
        else:
            dead_g = list(dead_w)  # grp aliases wgrp
        if dead_g:
            # group-axis accumulators (running totals + all-time
            # forever values) die with their partition key
            rows = np.asarray(dead_g, dtype=np.int64)
            if remap is not None:
                rows = remap(rows)
            gi = jnp.asarray(rows.astype(np.int32))
            for key in ("acc_sum", "acc_cnt", "acc_sumsq"):
                if key in state:
                    state[key] = state[key].at[gi].set(0.0)
            for key, init in (("acc_min", jnp.inf), ("acc_minf", jnp.inf),
                              ("acc_max", -jnp.inf), ("acc_maxf", -jnp.inf)):
                if key in state:
                    state[key] = state[key].at[gi].set(init)
        if self.kind == "keyed_sliding":
            wrows = np.asarray(dead_w, dtype=np.int64)
            if wremap is not None:
                wrows = wremap(wrows)
            wi = jnp.asarray(wrows.astype(np.int32))
            state["win_valid"] = state["win_valid"].at[wi].set(False)
            state["win_count"] = state["win_count"].at[wi].set(0)
        for w in dead_w:
            del self._wgrp_ids[self._wgrp_vals[w]]
            self._wgrp_vals[w] = None
            self._wgrp_free.append(w)
        self._wgrp_in_use[dead_w] = False
        if self._wgrp_sorted_keys is not None and len(self._wgrp_sorted_keys):
            keep = ~np.isin(self._wgrp_sorted_ids,
                            np.asarray(dead_w, dtype=np.int32))
            self._wgrp_sorted_keys = self._wgrp_sorted_keys[keep]
            self._wgrp_sorted_ids = self._wgrp_sorted_ids[keep]
        if self.group_exprs:
            for gid in dead_g:
                del self._group_ids[self._group_vals[gid]]
                self._group_vals[gid] = None
                self._group_free.append(gid)
                self._group_last.pop(gid, None)
        return state, len(dead_w)

    def host_lane_cols(self, cols, n: int) -> Dict[str, np.ndarray]:
        """Raw input columns -> device-lane numpy columns (lane-dtype
        casts + LONG hi/lo splits), un-padded — the sharded wrapper
        routes these per shard before device_put."""
        out: Dict[str, np.ndarray] = {}
        for k in self.attrs:
            lane = self._lane_dtype[k]
            out[k] = (np.asarray(cols[k])[:n].astype(lane, copy=False)
                      if k in cols else np.zeros(n, dtype=lane))
        for k in self.long_attrs:
            if k in cols:
                hi, lo = _split_i64(np.asarray(cols[k])[:n])
            else:
                hi = np.zeros(n, dtype=np.int32)
                lo = np.zeros(n, dtype=np.int32)
            out[k + "|hi"], out[k + "|lo"] = hi, lo
        return out

    def _pad(self, cols, rel, grp, n, wgrp=None):
        B = _pow2(n)
        valid = np.zeros(B, dtype=bool)
        valid[:n] = True
        c = {}
        for k in self.attrs:
            lane = self._lane_dtype[k]
            col = np.zeros(B, dtype=lane)
            if k in cols:
                col[:n] = np.asarray(cols[k])[:n].astype(lane)
            c[k] = col
        for k in self.long_attrs:
            hi = np.zeros(B, dtype=np.int32)
            lo = np.zeros(B, dtype=np.int32)
            if k in cols:
                h, l = _split_i64(np.asarray(cols[k])[:n])
                hi[:n], lo[:n] = h, l
            c[k + "|hi"] = hi
            c[k + "|lo"] = lo
        t = np.zeros(B, dtype=np.int32)
        t[:n] = rel[:n]
        g = np.zeros(B, dtype=np.int32)
        g[:n] = grp[:n]
        wg = np.zeros(B, dtype=np.int32)
        if wgrp is not None:
            wg[:n] = wgrp[:n]
        # ONE H2D put for the whole padded batch (a pytree device_put),
        # behind the ingest.put fault site — the single sanctioned
        # ingest transfer (core/ingest_stage.py, tests/test_ingest_guard)
        from siddhi_tpu.core.ingest_stage import staged_put

        c, t, g, wg, valid = staged_put(
            (c, t, g, wg, valid), faults=self.faults,
            stats=getattr(self, "ingest_stats", None))
        return c, t, g, wg, valid, B

    def _out_columns(self, vals, sel, gids, in_cols, in_sel,
                     host_env=None, key_cols=None,
                     gvals=None) -> Dict[str, np.ndarray]:
        """Assemble output columns (declared dtypes) for the selected
        rows.  ``vals``: {name: [*]} device column dict; ``sel``: row
        indices into it; ``gids``: group id per output row (None for the
        stateless filter kind — group keys are then evaluated host-side
        from ``host_env``); ``in_cols``/``in_sel``: input batch columns
        + row indices for passthrough items (None for flush outputs,
        which cannot have passthroughs).  ``gvals``: pre-captured group
        key value per output row — deferred emits pass this so a group
        id recycled between enqueue and drain cannot alias the keys."""
        cols: Dict[str, np.ndarray] = {}
        for oi, (kind, v, name) in enumerate(self.out_spec):
            t = self.out_types[oi]
            if kind == "group_key":
                if gids is None and gvals is None:
                    # no interned ids: use the precomputed key columns
                    # (or evaluate the key expr directly)
                    if key_cols is not None:
                        col = key_cols[v]
                    else:
                        n = host_env[N_KEY]
                        col = np.broadcast_to(
                            np.asarray(self.group_exprs[v].fn(host_env)),
                            (n,))
                    cols[name] = col[in_sel].astype(t.np_dtype, copy=False)
                    continue
                comp = (list(gvals) if gvals is not None
                        else [self._group_vals[int(g)] for g in gids])
                if self.partition_mode:
                    # composed tuple is (partition_key, *group_keys)
                    comp = [k[v + 1] for k in comp]
                else:
                    comp = [k[v] if isinstance(k, tuple) else k
                            for k in comp]
                cols[name] = (
                    np.asarray(comp, dtype=t.np_dtype) if comp
                    else np.empty(0, dtype=t.np_dtype))
            elif kind == "passthrough":
                cols[name] = np.asarray(in_cols[v])[in_sel].astype(
                    t.np_dtype, copy=False)
            else:
                cols[name] = vals[name][sel].astype(t.np_dtype)
        return cols

    def _empty_cols(self) -> Dict[str, np.ndarray]:
        return {
            name: np.empty(0, dtype=self.out_types[oi].np_dtype)
            for oi, (_k, _v, name) in enumerate(self.out_spec)
        }

    # group-key side channel of the MOST RECENT process_batch call
    # (host-format scalars/tuples, aligned with its output rows) — the
    # product runtime attaches it as batch.aux['group_keys'] so
    # per-group rate limiters work on device-lowered queries.  None
    # when the query has no group-by (or in partition mode, whose rate
    # limiters are rejected at plan time).
    last_group_keys: Optional[List] = None

    def _keys_for_gids(self, gids) -> List:
        return [self._group_vals[int(g)] for g in gids]

    def _concat_chunks(self, chunks) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
        """chunks: [(cols, ts_scalar, n_rows, keys|None)] -> (cols, ts);
        also sets ``last_group_keys`` from the chunk key lists."""
        chunks = [c for c in chunks if c[2]]
        if not chunks:
            self.last_group_keys = [] if self.group_exprs else None
            return self._empty_cols(), np.empty(0, dtype=np.int64)
        names = self.output_names
        out_cols = {
            nm: np.concatenate([c[0][nm] for c in chunks]) for nm in names
        }
        out_ts = np.concatenate(
            [np.full(c[2], c[1], dtype=np.int64) for c in chunks])
        if self.group_exprs:
            self.last_group_keys = [k for c in chunks for k in (c[3] or [])]
        else:
            self.last_group_keys = None
        return out_cols, out_ts

    def process_batch(self, state, cols: Dict[str, np.ndarray],
                      ts: np.ndarray,
                      part_keys: Optional[np.ndarray] = None):
        """Columnar host entry point: ``(state, out_cols, out_ts)`` with
        output columns cast back to the declared attribute types (the
        product runtime builds an EventBatch straight from these).
        ``part_keys`` (partition mode only): raw partition-key value per
        row.  Synchronous wrapper over the deferred path — one
        count-gated, coalesced fetch per call."""
        state, pending = self.process_batch_deferred(state, cols, ts,
                                                     part_keys)
        if pending is not None and pending.resolve() == 0:
            pending = None
        if pending is None:
            self.last_group_keys = (
                [] if self.group_exprs and not self.partition_mode else None)
            return state, self._empty_cols(), np.empty(0, dtype=np.int64)
        from siddhi_tpu.core.emit_queue import fetch_coalesced

        out_cols, out_ts, keys = pending.materialize(
            fetch_coalesced(pending.device_arrays()))
        self.last_group_keys = keys
        return state, out_cols, out_ts

    def process_batch_deferred(self, state, cols: Dict[str, np.ndarray],
                               ts: np.ndarray,
                               part_keys: Optional[np.ndarray] = None):
        """Async-emit entry point: run the jitted step(s) and KEEP the
        match outputs resident on device.  NOTHING crosses the device
        boundary here — even the per-chunk match-count scalar stays on
        device until ``DeferredDeviceEmit.resolve()`` fetches it (the
        ingest stage, core/ingest_stage.py, defers that fetch past the
        next batch's dispatch).  Empty input returns ``(state, None)``;
        otherwise a DeferredDeviceEmit whose ``resolve()`` /
        ``device_arrays()`` / ``materialize(host_arrays)`` triple the
        staging + pending-emit pipeline drains with one count fetch and
        one coalesced column transfer."""
        ts = np.asarray(ts, dtype=np.int64)
        n = len(ts)
        if n == 0:
            return state, None
        if self.partition_mode and part_keys is None:
            raise SiddhiAppRuntimeError(
                "partitioned device query needs per-row partition keys")
        pk = np.asarray(part_keys) if part_keys is not None else None
        pending = DeferredDeviceEmit(self)
        # the chunk bound exists for the [B, B] same-group masks of the
        # running/keyed-sliding kinds (and sliding's [B, W+B] gathers);
        # the stateless filter kind is purely per-row — one dispatch
        if n > MAX_DEVICE_BATCH and self.kind not in ("tumbling", "filter"):
            for i in range(0, n, MAX_DEVICE_BATCH):
                sl = slice(i, i + MAX_DEVICE_BATCH)
                state = self._deferred_chunk(
                    state, {k: np.asarray(v)[sl] for k, v in cols.items()},
                    ts[sl], pk[sl] if pk is not None else None, pending)
        else:
            state = self._deferred_chunk(state, cols, ts, pk, pending)
        return state, (pending if pending.chunks else None)

    def _deferred_chunk(self, state, cols, ts, pk, pending):
        """Process one <=MAX_DEVICE_BATCH slice; non-empty match outputs
        are appended to ``pending`` as device refs."""
        n = len(ts)
        if self.base_ts is None:
            self.base_ts = int(ts[0]) - 1
        rel64 = ts - self.base_ts
        if int(rel64.max()) >= self._REL_LIMIT:
            state, rel64 = self._re_anchor(state, rel64)
        rel = rel64.astype(np.int32)
        now = int(ts.max())
        if self.kind == "filter":
            # stateless: no interning at all (group-key select items are
            # evaluated host-side at materialize time) — unbounded key
            # cardinality
            grp = wgrp = np.zeros(n, dtype=np.int32)
        elif self.partition_mode:
            wgrp = self._intern_wgroups(pk, now)
            grp = (self._intern_groups(cols, ts, n, pk=pk, now=now)
                   if self.group_exprs else wgrp)
        else:
            wgrp = None
            grp = self._intern_groups(cols, ts, n)
        if self.kind in ("filter", "running", "sliding", "keyed_sliding"):
            step = self.make_step()
            c, t, g, wg, valid, B = self._pad(cols, rel, grp, n, wgrp)
            if self.faults is not None:
                self.faults.check("step.device")
            state, ov, out, n_match = step(state, c, t, g, wg, valid)
            # the count gate is DEFERRED: ``n_match`` stays a device
            # scalar until ``DeferredDeviceEmit.resolve()`` fetches it
            # (the ingest stage calls resolve only after the NEXT
            # batch's transfer + dispatch are in flight, which is where
            # the H2D/compute overlap comes from).  Group ids are kept
            # host-side so resolve can capture the key values for
            # surviving chunks — resolve always runs before any purge or
            # later interning could recycle a gid (runtimes flush the
            # ingest stage first at every such barrier).
            gids = (grp[:n].copy()
                    if self.group_exprs and self.kind != "filter" else None)
            pending.chunks.append({
                "kind": "device", "ov": ov, "out": dict(out),
                "names": list(out), "n": n, "count": n_match,
                "gids": gids, "ts": ts,
                "cols": {k: np.asarray(v) for k, v in cols.items()},
            })
            return state
        state, out_cols, out_ts = self._process_tumbling(
            state, cols, rel, grp, n)
        if len(out_ts):
            pending.chunks.append({
                "kind": "host", "cols": out_cols, "ts": out_ts,
                "keys": self.last_group_keys,
            })
        return state

    def process(self, state, cols: Dict[str, np.ndarray], ts: np.ndarray,
                part_keys: Optional[np.ndarray] = None):
        """Host entry point.  Returns ``(state, rows)`` where rows are
        emitted output dicts in emission order."""
        state, out_cols, out_ts = self.process_batch(state, cols, ts,
                                                     part_keys)
        names = self.output_names
        rows = [
            {nm: out_cols[nm][i] for nm in names}
            for i in range(len(out_ts))
        ]
        return state, rows

    # -- tumbling host logic -------------------------------------------------

    def _gk_vals(self, grp: np.ndarray, n: int) -> np.ndarray:
        K = max(len(self._numeric_group_keys), 1)
        out = np.zeros((n, K), dtype=np.float32)
        for ki, i in enumerate(self._numeric_group_keys):
            for r in range(n):
                k = self._group_vals[int(grp[r])]
                v = k[i] if isinstance(k, tuple) else k
                out[r, ki] = np.float32(v)
        return out

    def _flush_cols(self, state):
        flush = self.make_flush_step()
        state, ov, out, n_match = flush(state)
        # explicit count-gate fetch: int(device_scalar) is an IMPLICIT
        # transfer and would trip jax.transfer_guard('disallow')
        if int(self.jax.device_get(n_match)) == 0:
            # count gate: empty pane — no group/output column fetched
            return state, self._empty_cols(), 0, (
                [] if self.group_exprs else None)
        gidx = np.flatnonzero(np.asarray(ov))
        out_np = {k: np.asarray(col) for k, col in out.items()}
        out_cols = self._out_columns(out_np, gidx, gidx, None, None)
        keys = self._keys_for_gids(gidx) if self.group_exprs else None
        return state, out_cols, len(gidx), keys

    def _advance_pane(self):
        """Post-flush timeBatch pane bookkeeping (mirrors the host
        TimeBatchWindow): boundaries advance by T while panes stay
        non-empty; after two consecutive empty panes the window goes
        idle and re-anchors at the next event."""
        if self._pane_fill == 0 and self._prev_pane_fill == 0:
            self._pane_end = None
        else:
            self._pane_end += int(self.window_param)
            self._prev_pane_fill = self._pane_fill
            self._pane_fill = 0

    def pane_wakeup(self) -> Optional[int]:
        """Absolute ms at which the open timeBatch pane closes (the
        scheduler hook driving timer flushes, the host TimeBatchWindow's
        Scheduler.notifyAt analog); None when nothing is pending."""
        if (self.window_name != "timeBatch" or self._pane_end is None
                or self.base_ts is None):
            return None
        return self.base_ts + self._pane_end

    def flush_due(self, state, now: int):
        """Timer-driven flush: close every pane whose boundary <= now.
        Returns (state, out_cols, out_ts)."""
        chunks = []
        while True:
            w = self.pane_wakeup()
            if w is None or w > now:
                break
            state, fcols, nf, keys = self._flush_cols(state)
            chunks.append((fcols, w, nf, keys))
            self._advance_pane()
        out_cols, out_ts = self._concat_chunks(chunks)
        return state, out_cols, out_ts

    def _acc_segment(self, state, cols, rel, grp, idx) -> Tuple[object, int]:
        acc = self.make_acc_step()
        n = len(idx)
        c, t, g, _wg, valid, B = self._pad(
            {k: np.asarray(v)[idx] for k, v in cols.items()},
            rel[idx], grp[idx], n)
        gkv = np.zeros((B, max(len(self._numeric_group_keys), 1)),
                       dtype=np.float32)
        gkv[:n] = self._gk_vals(grp[idx], n)
        state, n_pass = acc(state, c, t, g, self.jnp.asarray(gkv), valid)
        # explicit count-gate fetch (transfer_guard-safe, see _flush_cols)
        return state, int(self.jax.device_get(n_pass))

    def _pane_sweep(self, state, cols, rel, grp, n, acc_segment,
                    flush_pane):
        """Shared tumbling pane control flow: walk one batch, feed
        intra-pane segments to ``acc_segment(state, cols, rel, grp,
        idx) -> (state, n_pass)`` and close each crossed boundary via
        ``flush_pane(state, abs_ts) -> state``.  The single-device path
        and the sharded wrapper drive the SAME sweep with their own
        accumulate/flush steps, so pane placement (``_pane_end``,
        lengthBatch fill counts — host scalars either way) cannot
        diverge between them."""
        if self.window_name == "timeBatch":
            # pane bookkeeping mirrors the host TimeBatchWindow: the
            # first event anchors the boundary, boundaries advance by T
            # while panes stay non-empty, and the window goes idle
            # (re-anchoring at the next event) once a pane and its
            # predecessor are both empty.  Flushes are stamped with the
            # pane boundary time, matching the timer-driven path.
            T = int(self.window_param)
            i = 0
            while i < n:
                if self._pane_end is None:
                    self._pane_end = int(rel[i]) + T
                    self._pane_fill = 0
                    self._prev_pane_fill = 0
                # events belonging to the current pane: ts < pane_end
                j = int(np.searchsorted(rel[i:], self._pane_end,
                                        side="left")) + i
                if j > i:
                    state, n_pass = acc_segment(
                        state, cols, rel, grp, np.arange(i, j))
                    self._pane_fill += n_pass
                    i = j
                if i < n:  # boundary crossed by remaining events
                    state = flush_pane(state, self.base_ts + self._pane_end)
                    self._advance_pane()
            return state
        # lengthBatch: need passing counts to place flush boundaries,
        # so probe the filter mask first (host-visible)
        L = int(self.window_param)
        fmask = self._host_filter_mask(cols, rel, n)
        i = 0
        while i < n:
            remaining = L - self._pane_fill
            pass_pos = np.flatnonzero(fmask[i:])
            if len(pass_pos) < remaining:
                state, _ = acc_segment(
                    state, cols, rel, grp, np.arange(i, n))
                self._pane_fill += len(pass_pos)
                break
            j = i + int(pass_pos[remaining - 1]) + 1
            state, _ = acc_segment(state, cols, rel, grp,
                                   np.arange(i, j))
            state = flush_pane(state, self.base_ts + int(rel[j - 1]))
            self._pane_fill = 0
            i = j
        return state

    def _process_tumbling(self, state, cols, rel, grp, n):
        chunks = []  # (cols, abs_ts, n_rows, keys|None)

        def flush_pane(st, when):
            st, fcols, nf, keys = self._flush_cols(st)
            chunks.append((fcols, when, nf, keys))
            return st

        state = self._pane_sweep(state, cols, rel, grp, n,
                                 self._acc_segment, flush_pane)
        out_cols, out_ts = self._concat_chunks(chunks)
        return state, out_cols, out_ts

    def _host_filter_mask(self, cols, rel, n) -> np.ndarray:
        env = {a: np.asarray(cols[a]) for a in self.all_attrs if a in cols}
        for a in self.long_attrs:  # pair-compiled filters read hi/lo
            if a in cols:
                env[a + "|hi"], env[a + "|lo"] = _split_i64(
                    np.asarray(cols[a])[:n])
        env[TS_KEY] = np.asarray(rel)
        env[N_KEY] = n
        m = np.ones(n, dtype=bool)
        for f in self.filters:
            m = m & np.broadcast_to(np.asarray(f.fn(env)).astype(bool), (n,))
        return m

    # -- snapshot of host-side bookkeeping (device state arrays are
    # snapshotted by the product runtime that owns them) ---------------------

    def host_snapshot(self) -> Dict:
        return {
            "base_ts": self.base_ts,
            "group_ids": dict(self._group_ids),
            "group_vals": list(self._group_vals),
            "group_free": list(self._group_free),
            "group_last": dict(self._group_last),
            "wgrp_ids": dict(self._wgrp_ids),
            "wgrp_vals": list(self._wgrp_vals),
            "wgrp_free": list(self._wgrp_free),
            "wgrp_last": self._wgrp_last.copy(),
            "wgrp_in_use": self._wgrp_in_use.copy(),
            "pane_end": self._pane_end,
            "pane_fill": self._pane_fill,
            "prev_pane_fill": self._prev_pane_fill,
        }

    def host_restore(self, s: Dict):
        self.base_ts = s["base_ts"]
        self._group_ids = dict(s["group_ids"])
        self._group_vals = list(s["group_vals"])
        self._group_free = list(s.get("group_free", []))
        self._group_last = dict(s.get("group_last", {}))
        self._wgrp_ids = dict(s.get("wgrp_ids", {}))
        self._wgrp_vals = list(s.get("wgrp_vals", []))
        self._wgrp_free = list(s.get("wgrp_free", []))
        last = s.get("wgrp_last")
        self._wgrp_last = np.zeros(self.n_wgroups, dtype=np.int64)
        self._wgrp_in_use = np.zeros(self.n_wgroups, dtype=bool)
        if isinstance(last, dict):
            # legacy dict-format snapshot: convert so restored keys
            # stay visible to the idle purge
            for wid, t in last.items():
                self._wgrp_last[wid] = t
                self._wgrp_in_use[wid] = True
        elif last is not None:
            self._wgrp_last = np.asarray(last, dtype=np.int64).copy()
            in_use = s.get("wgrp_in_use")
            if in_use is not None:
                self._wgrp_in_use = np.asarray(in_use, dtype=bool).copy()
        # rebuild the sorted intern index from the restored key map.
        # np.asarray over MIXED python key types silently stringifies
        # (int 7 and '7' would alias in searchsorted), so mixed-type
        # key sets pin the exact dict fallback instead.
        self._wgrp_sorted_keys = None
        self._wgrp_sorted_ids = None
        self._wgrp_vector = True
        if self._wgrp_ids:
            if len({type(k) for k in self._wgrp_ids}) > 1:
                self._wgrp_vector = False
            else:
                try:
                    keys = np.asarray(list(self._wgrp_ids.keys()))
                    if keys.dtype.kind in ("O", "V"):
                        raise TypeError("object keys")
                    order = np.argsort(keys)
                    self._wgrp_sorted_keys = keys[order]
                    self._wgrp_sorted_ids = np.asarray(
                        list(self._wgrp_ids.values()),
                        dtype=np.int32)[order]
                except Exception:
                    self._wgrp_vector = False
        self._pane_end = s["pane_end"]
        self._pane_fill = s["pane_fill"]
        self._prev_pane_fill = s["prev_pane_fill"]

    # -- introspection -------------------------------------------------------

    @property
    def output_names(self) -> List[str]:
        return [name for _k, _v, name in self.out_spec]


class DeferredDeviceEmit:
    """Device-resident match outputs of one ``process_batch_deferred``
    call (one junction batch; possibly several >MAX_DEVICE_BATCH-row
    chunks).  ``resolve()`` fetches the deferred count gates (the only
    blocking point of the whole ingest path — the ingest stage times it
    to land AFTER the next batch's dispatch); the pending-emit queue
    (core/emit_queue.py) then fetches ``device_arrays()`` with one
    coalesced transfer and hands the host copies back to
    ``materialize``; the result is byte-identical to what the
    synchronous ``process_batch`` would have returned."""

    __slots__ = ("engine", "chunks", "_total")

    def __init__(self, engine):
        self.engine = engine
        self.chunks: List[dict] = []
        self._total: Optional[int] = None

    def probe(self):
        """A device scalar whose readiness marks step completion for
        this batch (the ingest stage's overlap/stall evidence); None
        when every chunk is host-side."""
        for ch in self.chunks:
            if ch["kind"] in ("device", "flush"):
                return ch["count"]
        return None

    def resolve(self) -> int:
        """Fetch the per-chunk count gates (one ``device_get``, scalars
        only), prune zero-match chunks so their columns are never
        transferred, and capture group-key values for the survivors
        (host-side, from the intern tables — safe because every gid
        purge/restore point flushes the ingest stage, and thus resolves,
        first).  Idempotent; returns the total match count."""
        if self._total is not None:
            return self._total
        dev = [(i, ch["count"]) for i, ch in enumerate(self.chunks)
               if ch["kind"] in ("device", "flush")]
        counts = {}
        if dev:
            import jax

            host = jax.device_get([c for _i, c in dev])
            counts = {i: int(c) for (i, _d), c in zip(dev, host)}
        eng = self.engine
        keep = []
        total = 0
        for i, ch in enumerate(self.chunks):
            if ch["kind"] == "host":
                total += len(ch["ts"])
                keep.append(ch)
                continue
            c = counts[i]
            if c == 0:
                continue  # count gate: zero-match pane/batch — no
                # column ever fetched
            total += c
            if ch["kind"] == "flush":
                # sharded pane flush: the matching group ids are only
                # known once ``ov`` is on the host, so key capture
                # happens in materialize.  Safe without the gvals
                # snapshot: tumbling never runs in partition mode, so
                # its group ids are never purge-recycled.
                keep.append(ch)
                continue
            gids = ch.pop("gids", None)
            ch["gvals"] = (eng._keys_for_gids(gids)
                           if gids is not None else None)
            keep.append(ch)
        self.chunks = keep
        self._total = total
        return total

    def device_arrays(self) -> List:
        arrs: List = []
        for ch in self.chunks:
            if ch["kind"] not in ("device", "flush"):
                continue
            arrs.append(ch["ov"])
            arrs.extend(ch["out"][nm] for nm in ch["names"])
        return arrs

    def materialize(self, host_arrays):
        """``host_arrays``: fetched copies aligned with
        ``device_arrays()``.  Returns ``(out_cols, out_ts, keys)`` —
        the synchronous result triple (keys = the group-key side
        channel, None when the query carries none)."""
        eng = self.engine
        pos = 0
        parts = []  # (out_cols, out_ts, keys|None)
        for ch in self.chunks:
            if ch["kind"] == "host":
                parts.append((ch["cols"], ch["ts"], ch["keys"]))
                continue
            if ch["kind"] == "flush":
                # sharded pane flush: rows are shard-major
                # (owner * rows_per_shard + local); recover the global
                # group id and emit in ascending-gid order, exactly the
                # single-device ``_flush_cols`` ordering
                raw_ov = np.asarray(host_arrays[pos])
                pos += 1
                out_np = {}
                for nm in ch["names"]:
                    out_np[nm] = np.asarray(host_arrays[pos])
                    pos += 1
                rows = np.flatnonzero(raw_ov)
                rps = ch["rows_per_shard"]
                gid = (rows % rps) * ch["n_shards"] + rows // rps
                order = np.argsort(gid, kind="stable")
                sel, gids = rows[order], gid[order]
                out_cols = eng._out_columns(out_np, sel, gids, None, None)
                keys = (eng._keys_for_gids(gids)
                        if eng.group_exprs else None)
                parts.append((out_cols,
                              np.full(len(sel), ch["stamp"],
                                      dtype=np.int64),
                              keys))
                continue
            n = ch["n"]
            # sharded chunks carry a routed-slot map instead of plain
            # front-padding: ``pos`` maps input row -> routed slot
            sel = ch.get("pos")
            raw_ov = np.asarray(host_arrays[pos])
            ov_np = raw_ov[sel] if sel is not None else raw_ov[:n]
            pos += 1
            out_np = {}
            for nm in ch["names"]:
                raw_col = np.asarray(host_arrays[pos])
                out_np[nm] = raw_col[sel] if sel is not None else raw_col[:n]
                pos += 1
            idx = np.flatnonzero(ov_np)
            cols, ts = ch["cols"], ch["ts"]
            if eng.kind == "filter":
                host_env = eng._host_env(cols, ts, n)
                key_cols = ([np.broadcast_to(
                    np.asarray(g.fn(host_env)), (n,))
                    for g in eng.group_exprs]
                    if eng.group_exprs else None)
                out_cols = eng._out_columns(
                    out_np, idx, None, cols, idx, host_env=host_env,
                    key_cols=key_cols)
                if key_cols and not eng.partition_mode:
                    from siddhi_tpu.core.query import format_group_keys

                    keys = format_group_keys(key_cols, idx)
                else:
                    keys = None
            else:
                gvals = ch["gvals"]
                sel_vals = ([gvals[int(i)] for i in idx]
                            if gvals is not None else None)
                out_cols = eng._out_columns(out_np, idx, None, cols, idx,
                                            gvals=sel_vals)
                keys = (sel_vals
                        if eng.group_exprs and not eng.partition_mode
                        else None)
            parts.append((out_cols, ts[idx], keys))
        return self._concat_parts(parts)

    def _concat_parts(self, parts):
        eng = self.engine
        parts = [p for p in parts if len(p[1])]
        if not parts:
            return (eng._empty_cols(), np.empty(0, dtype=np.int64),
                    [] if eng.group_exprs and not eng.partition_mode
                    else None)
        names = eng.output_names
        out_cols = {
            nm: np.concatenate([p[0][nm] for p in parts]) for nm in names
        }
        out_ts = np.concatenate(
            [np.asarray(p[1], dtype=np.int64) for p in parts])
        key_lists = [p[2] for p in parts]
        if any(k is not None for k in key_lists):
            keys = [k for kl in key_lists for k in (kl or [])]
        else:
            keys = None
        return out_cols, out_ts, keys


# ---------------------------------------------------------------------------
# High-level compile API (mirrors ops.dense_nfa.compile_pattern)
# ---------------------------------------------------------------------------


def compile_query(
    app_str: str,
    query_name: Optional[str] = None,
    n_groups: int = 1024,
    window_capacity: int = 1024,
    partition_mode: bool = False,
    n_wgroups: Optional[int] = None,
) -> DeviceQueryEngine:
    """Compile a SiddhiQL single-stream query into a DeviceQueryEngine."""
    from siddhi_tpu.compiler import SiddhiCompiler
    from siddhi_tpu.query_api.annotation import find_annotation

    app = SiddhiCompiler.parse(app_str)
    query = None
    for i, q in enumerate(app.queries):
        info = find_annotation(q.annotations, "info")
        nm = (info.element("name") if info else None) or f"query_{i}"
        if query_name is None or nm == query_name:
            query = q
            break
    if query is None:
        raise SiddhiAppCreationError(f"query '{query_name}' not found")
    s = query.input_stream
    if not isinstance(s, SingleInputStream):
        raise SiddhiAppCreationError(
            "compile_query needs a single-input-stream query")
    d = app.stream_definitions.get(s.stream_id)
    if d is None:
        raise SiddhiAppCreationError(f"stream '{s.stream_id}' is not defined")
    return DeviceQueryEngine(
        query, d, n_groups=n_groups, window_capacity=window_capacity,
        partition_mode=partition_mode, n_wgroups=n_wgroups)
