"""Associative-scan NFA: sequence parallelism for a single hot key.

The dense engine (ops/dense_nfa.py) parallelizes over PARTITIONS; events
of one partition are inherently sequential there (collision rounds), so
a single hot key processes one event per jitted step.  This module is
the long-context answer SURVEY §5 calls for: NFA transitions of a
linear pattern chain compose ASSOCIATIVELY, so one key's event stream
advances in O(log n) scan depth instead of n sequential steps —
``jax.lax.associative_scan`` over per-event transition maps, the CEP
analog of sequence parallelism.

Design (max-plus affine algebra):
- state vector ``v[j]`` = start timestamp of the YOUNGEST partial match
  that has consumed pattern events ``1..j`` (−inf = none pending); lane
  0 is the constant-0 lane that carries per-event timestamps into the
  algebra (affine resets as one extra matrix column).
- each event ``e`` becomes an (S x S) max-plus matrix ``M_e`` over
  entries {0, −inf, ts_e}: advancing from node j−1 needs ``f_j(e)``;
  an instance LEAVES its node when it advances (Siddhi pattern
  semantics, StreamPostStateProcessor.java:64-83); an ``every`` head
  arms a fresh start per matching event.
- ``M_e`` compose under max-plus matmul — associative — so prefix
  states come from one ``associative_scan``.
- ``within`` prunes ONLY at emission: keeping the max (youngest) start
  per node is exact, because any chain whose completion lies within W
  of its start was within W at every intermediate event too (event
  times are monotone), and any chain beyond W dies at the final check.

Exactness contract: for an (optionally ``every``-headed) linear chain
whose filters reference only the CURRENT event (no captures), the
per-node youngest-start abstraction is exact — same-node instances are
interchangeable — so the DETECTION output (which events complete a
match, with the youngest qualifying start) equals the host engine's.
The host/dense engines emit one match per pending chain and carry
captures; this engine emits one detection per completing event.  Use it
for the hot-key tail the partition axis cannot split.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from siddhi_tpu.core.exceptions import SiddhiAppCreationError
from siddhi_tpu.planner.expr import ExpressionCompiler, N_KEY, Scope
from siddhi_tpu.query_api import (
    AttrType,
    EveryStateElement,
    NextStateElement,
    SingleInputStream,
    StateInputStream,
    StreamStateElement,
)

NEG = -1e30  # −inf stand-in (float32-safe)


def _chain_nodes(st: StateInputStream) -> Tuple[List, bool]:
    """Flatten ``every a=S[...] -> b=S[...] -> ...`` into its
    StreamStateElements; raises outside the linear-chain subset."""
    nodes: List[StreamStateElement] = []
    every_head = False

    def walk(el, at_head):
        nonlocal every_head
        if isinstance(el, NextStateElement):
            walk(el.element, at_head)
            walk(el.next, False)
            return
        if isinstance(el, EveryStateElement):
            if not at_head or nodes:
                raise SiddhiAppCreationError(
                    "scan NFA: only a leading 'every' is supported")
            every_head = True
            walk(el.element, False)
            return
        if isinstance(el, StreamStateElement):
            nodes.append(el)
            return
        raise SiddhiAppCreationError(
            f"scan NFA: unsupported state element {type(el).__name__} "
            "(linear chains only — counts/logical/absent need the dense "
            "or host engine)")

    walk(st.state, True)
    if len(nodes) < 2:
        raise SiddhiAppCreationError("scan NFA: chain needs >= 2 nodes")
    return nodes, every_head


class ScanPatternEngine:
    """One hot key's linear pattern chain as an associative scan.

    Usage::

        eng = compile_scan_pattern(app_str, "q")
        state = eng.init_state()            # [S] start-ts vector
        state, idx, starts = eng.process(state, cols, ts)
        # idx: indices of events that completed a match (detections)
    """

    def __init__(self, st: StateInputStream, stream_def):
        import jax
        import jax.numpy as jnp

        self.jax, self.jnp = jax, jnp
        nodes, self.every_head = _chain_nodes(st)
        if not self.every_head:
            raise SiddhiAppCreationError(
                "scan NFA: a non-'every' head arms exactly once, which "
                "is history-dependent — use the dense/host engines")
        self.within_ms = st.within_ms  # None = unbounded
        self.n_nodes = len(nodes)
        if self.n_nodes > 32:
            raise SiddhiAppCreationError("scan NFA: > 32 chain nodes")

        sid = nodes[0].stream.stream_id
        for nd in nodes:
            if nd.stream.stream_id != sid:
                raise SiddhiAppCreationError(
                    "scan NFA: one hot stream only (multi-stream chains "
                    "need the dense engine)")
        self.stream_id = sid
        self.stream_def = stream_def

        # filters see ONLY the current event (capture references would
        # break same-node interchangeability — the exactness contract)
        scope = Scope()
        for a in stream_def.attributes:
            scope.add(sid, a.name, a.name, a.type)
        compiler = ExpressionCompiler(scope)
        self.filters = []
        for nd in nodes:
            s = nd.stream
            if not isinstance(s, SingleInputStream):
                raise SiddhiAppCreationError("scan NFA: plain stream nodes")
            exprs = [h.expression for h in s.handlers
                     if type(h).__name__ == "Filter"]
            if len(exprs) != len(s.handlers):
                raise SiddhiAppCreationError(
                    "scan NFA: only filters on chain nodes")
            compiled = [compiler.compile(e) for e in exprs]
            for c in compiled:
                if c.type != AttrType.BOOL:
                    raise SiddhiAppCreationError(
                        "scan NFA: filters must be boolean")
            self.filters.append(compiled)

        self._lane_dtype: Dict[str, np.dtype] = {
            a.name: (np.dtype(np.int32) if a.type == AttrType.INT
                     else np.dtype(np.float32))
            for a in stream_def.attributes
            if (a.type.is_numeric or a.type == AttrType.BOOL)
            and a.type != AttrType.LONG
        }
        self.base_ts: Optional[int] = None
        self._trace_check()
        self._scan_fn = None

    # S = n_nodes: lanes 0..S-1 — lane 0 constant, lanes 1..S-1 the
    # youngest start having consumed nodes 1..j.  The final node S
    # completes at emission and never occupies a lane.

    def _trace_check(self):
        import jax

        B = 8
        # NO timestamp key: a filter reading eventTimestamp() would see
        # base-rebased relative float32 time here, silently diverging
        # from the host engine — its KeyError rejects it instead
        env = {
            a: jax.ShapeDtypeStruct((B,), dt)
            for a, dt in self._lane_dtype.items()
        }
        env[N_KEY] = B
        try:
            for fs in self.filters:
                for c in fs:
                    jax.eval_shape(lambda e, c=c: c.fn(e), env)
        except Exception as e:
            raise SiddhiAppCreationError(
                f"scan NFA: filter not device-evaluable (timestamp "
                f"functions / host-only ops need the dense or host "
                f"engine): {e}") from e

    def init_state(self):
        S = self.n_nodes
        v = np.full(S, NEG, dtype=np.float32)
        v[0] = 0.0  # constant lane
        return self.jnp.asarray(v)

    def _filter_matrix(self, env, n):
        """[n, S] boolean: f_j holds for event i (f_0 unused)."""
        jnp = self.jnp
        cols = [jnp.ones(n, dtype=bool)]  # placeholder for index 0
        for fs in self.filters:
            m = jnp.ones(n, dtype=bool)
            for c in fs:
                m = m & jnp.broadcast_to(
                    jnp.asarray(c.fn(env)).astype(bool), (n,))
            cols.append(m)
        return jnp.stack(cols, axis=1)  # [n, S+1]; col j = f_j

    def make_scan(self):
        """Jitted (state[S], cols{attr: [n]}, ts[n] f32-rel) ->
        (state', match[n] bool, start[n] f32)."""
        if self._scan_fn is not None:
            return self._scan_fn
        jax, jnp = self.jax, self.jnp
        S = self.n_nodes
        every = self.every_head
        W = self.within_ms

        def maxplus(a, b):
            # compose: apply a (earlier) then b -> b ⊗ a, batched
            return jnp.max(b[..., :, :, None] + a[..., None, :, :],
                           axis=-2)

        def scan(v0, cols, ts):
            n = ts.shape[0]
            env = dict(cols)  # no TS_KEY: _trace_check rejected ts use
            env[N_KEY] = n
            F = self._filter_matrix(env, n)  # [n, S+1]; col j = f_j
            # per-event max-plus matrices M [n, S, S] over lanes
            # 0..S-1 (lane 0 constant; lane j = consumed events 1..j)
            M = jnp.full((n, S, S), NEG, dtype=jnp.float32)
            M = M.at[:, 0, 0].set(0.0)  # constant lane persists
            # arm a fresh chain per f_1 event ('every' head)
            M = M.at[:, 1, 0].set(jnp.where(F[:, 1], ts, NEG))
            for j in range(1, S):
                # an instance at lane j advances out by consuming event
                # j+1 (j+1 == S is completion) — it LEAVES either way
                M = M.at[:, j, j].set(jnp.where(~F[:, j + 1], 0.0, NEG))
                if j + 1 < S:
                    M = M.at[:, j + 1, j].set(
                        jnp.where(F[:, j + 1], 0.0, NEG))
            # prefix products P_i = M_i ⊗ ... ⊗ M_1 in O(log n) depth
            P = jax.lax.associative_scan(maxplus, M, axis=0)
            after = jnp.max(P + v0[None, None, :], axis=-1)  # [n, S]
            before = jnp.concatenate([v0[None, :], after[:-1]], axis=0)
            # completion: event i matches f_S with a chain at lane S-1
            start = before[:, S - 1]
            match = F[:, S] & (start > NEG / 2)
            if W is not None:
                match = match & (start > ts - W)
            return after[-1], match, start

        self._scan_fn = jax.jit(scan)
        return self._scan_fn

    def process(self, state, cols: Dict[str, np.ndarray], ts: np.ndarray):
        """Host entry: (state, match_event_indices, match_starts_ms).

        The base is REBASED every batch so relative times stay small:
        float32's 24-bit mantissa is millisecond-exact only below ~4.7h
        of relative time, and carried starts shift with the base.  With
        ``within W``, carried starts stay < W + batch span old, so
        exactness holds while W + span < ~4.7h; without ``within``,
        detection stays exact (only liveness is read) and reported
        start times degrade to ~span/2^24 relative rounding."""
        jnp = self.jnp
        ts = np.asarray(ts, dtype=np.int64)
        n = len(ts)
        if n == 0:
            return state, np.empty(0, np.int64), np.empty(0, np.int64)
        new_base = int(ts[0]) - 1
        if self.base_ts is None:
            self.base_ts = new_base
        elif new_base > self.base_ts:
            delta = np.float32(new_base - self.base_ts)
            s = np.asarray(state)
            live = s > NEG / 2
            live[0] = False  # constant lane stays 0
            s = np.where(live, s - delta, s).astype(np.float32)
            state = jnp.asarray(s)
            self.base_ts = new_base
        rel = (ts - self.base_ts).astype(np.float32)
        dev_cols = {}
        for a, dt in self._lane_dtype.items():
            if a in cols:
                dev_cols[a] = jnp.asarray(
                    np.asarray(cols[a])[:n].astype(dt, copy=False))
        scan = self.make_scan()
        state, match, start = scan(state, dev_cols, jnp.asarray(rel))
        idx = np.flatnonzero(np.asarray(match))
        starts = (np.asarray(start)[idx].astype(np.int64)
                  + self.base_ts)
        return state, idx, starts


def compile_scan_pattern(app_str: str,
                         query_name: Optional[str] = None
                         ) -> ScanPatternEngine:
    """Compile a SiddhiQL linear pattern into a ScanPatternEngine."""
    from siddhi_tpu.compiler import SiddhiCompiler
    from siddhi_tpu.query_api.annotation import find_annotation

    app = SiddhiCompiler.parse(app_str)
    query = None
    for i, q in enumerate(app.queries):
        info = find_annotation(q.annotations, "info")
        nm = (info.element("name") if info else None) or f"query_{i}"
        if query_name is None or nm == query_name:
            query = q
            break
    if query is None:
        raise SiddhiAppCreationError(f"query '{query_name}' not found")
    st = query.input_stream
    if not isinstance(st, StateInputStream):
        raise SiddhiAppCreationError("compile_scan_pattern needs a pattern")
    nodes, _ = _chain_nodes(st)
    d = app.stream_definitions.get(nodes[0].stream.stream_id)
    if d is None:
        raise SiddhiAppCreationError("pattern stream is not defined")
    return ScanPatternEngine(st, d)
