"""Builtin stream functions: the reference core ships exactly two —
`#pol2Cart(theta, rho[, z])` (Pol2CartStreamFunctionProcessor.java:149,
appends cartesian x/y[/z] columns) and `#log(...)`
(LogStreamProcessor.java, passthrough event logging).

A stream-function object takes ``(compiled_args, attribute_names)``,
exposes optional ``output_attributes`` (appended to the flowing stream
schema by the planner) and ``process(batch, now) -> batch`` which must
add those columns.
"""

from __future__ import annotations

import logging
from typing import List

import numpy as np

from siddhi_tpu.core.exceptions import SiddhiAppCreationError
from siddhi_tpu.extension.registry import extension
from siddhi_tpu.extension.validator import Param, REPEAT
from siddhi_tpu.query_api import Attribute, AttrType

log = logging.getLogger("siddhi_tpu")

_NUM = (AttrType.INT, AttrType.LONG, AttrType.FLOAT, AttrType.DOUBLE)


@extension("stream_function", "pol2Cart")
class Pol2CartStreamFunction:
    """Appends x/y (and passes z through) computed from polar inputs:
    x = rho*cos(radians(theta)), y = rho*sin(radians(theta))."""

    PARAMETERS = (Param("theta", _NUM), Param("rho", _NUM),
                  Param("z", _NUM))
    OVERLOADS = (("theta", "rho"), ("theta", "rho", "z"))

    def __init__(self, args, attribute_names):
        if len(args) not in (2, 3):
            raise SiddhiAppCreationError(
                "#pol2Cart() takes (theta, rho) or (theta, rho, z)")
        self.args = args
        self.output_attributes: List[Attribute] = [
            Attribute("x", AttrType.DOUBLE),
            Attribute("y", AttrType.DOUBLE),
        ]
        if len(args) == 3:
            self.output_attributes.append(Attribute("z", AttrType.DOUBLE))

    def process(self, batch, now):
        from siddhi_tpu.core.event import EventBatch
        from siddhi_tpu.core.query import build_env

        env = build_env(batch)
        n = len(batch)
        theta = np.broadcast_to(
            np.asarray(self.args[0].fn(env), dtype=np.float64), (n,))
        rho = np.broadcast_to(
            np.asarray(self.args[1].fn(env), dtype=np.float64), (n,))
        rad = np.radians(theta)
        # a NEW batch: the junction hands the SAME EventBatch to every
        # receiver, so mutating columns/names in place would leak the
        # appended schema into sibling queries
        cols = dict(batch.columns)
        cols["x"] = rho * np.cos(rad)
        cols["y"] = rho * np.sin(rad)
        if len(self.args) == 3:
            cols["z"] = np.broadcast_to(
                np.asarray(self.args[2].fn(env), dtype=np.float64),
                (n,)).copy()
        names = list(batch.attribute_names) + [
            a.name for a in self.output_attributes
            if a.name not in batch.attribute_names]
        out = EventBatch(batch.stream_id, names, cols,
                         batch.timestamps, batch.types)
        out.aux.update(batch.aux)
        return out


@extension("stream_function", "log")
class LogStreamFunction:
    """Passthrough event logging (reference LogStreamProcessor):
    `#log()`, `#log('message')`, `#log('priority', 'message')`."""

    PARAMETERS = (Param("priority", (AttrType.STRING,)),
                  Param("log.message", (AttrType.STRING,)),
                  Param("is.event.logged", (AttrType.BOOL,)))
    OVERLOADS = ((), ("log.message",),
                 ("priority", "log.message"),
                 ("priority", "log.message", "is.event.logged"))

    _LEVELS = {"info": logging.INFO, "debug": logging.DEBUG,
               "warn": logging.WARNING, "error": logging.ERROR,
               "trace": logging.DEBUG, "fatal": logging.CRITICAL}

    def __init__(self, args, attribute_names):
        self.args = args
        self.attribute_names = attribute_names

    def process(self, batch, now):
        from siddhi_tpu.core.query import build_env

        env = build_env(batch)
        vals = []
        for a in self.args:
            v = np.asarray(a.fn(env)).reshape(-1)
            vals.append(str(v[0]) if len(v) else "")
        level = logging.INFO
        message = ""
        log_events = True
        if len(vals) == 1:
            message = vals[0]
        elif len(vals) >= 2:
            level = self._LEVELS.get(vals[0].lower(), logging.INFO)
            message = vals[1]
            if len(vals) >= 3:
                log_events = vals[2].lower() == "true"
        if log.isEnabledFor(level):  # row dump is O(rows x cols): lazy
            if log_events:
                rows = [
                    [batch.columns[nm][i] for nm in batch.attribute_names]
                    for i in range(len(batch))
                ]
                log.log(level, "%s : %d events: %s",
                        message or batch.stream_id, len(batch), rows)
            else:
                log.log(level, "%s : %d events",
                        message or batch.stream_id, len(batch))
        return batch
