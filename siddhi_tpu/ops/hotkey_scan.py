"""Batched hot-key associative-scan NFA: the skew router's kernel.

``ops/nfa_scan.py`` proves the algebra for ONE key: linear-chain NFA
transitions compose under max-plus matmul, so a single key's events
advance in O(log n) scan depth.  This module makes that batch-capable
for the hybrid skew router (core/hotkey_router.py): H promoted hot keys
ride a ``[H, n_pad]`` leading axis through ONE jitted
``associative_scan`` per junction cycle, while cold keys stay on the
dense partition path.

Two scans ride one ``associative_scan`` call as a pytree:

- the max-plus matrix ``M`` of nfa_scan.py carries the per-lane
  YOUNGEST pending start (liveness: does a chain complete here);
- a counting matrix ``T`` with the same support carries the NUMBER of
  pending chains per lane under ordinary matmul (componentwise
  associative with max-plus, so one scan serves both).

The count scan is what upgrades the sample engine's "one detection per
completing event" to the host engine's exact multiplicity: in the
eligible chain class (every-headed linear chain, capture-free
current-event filters, selects referencing ONLY the final node, no
``within``) same-node chains are interchangeable AND their emitted rows
are identical, so emitting ``count_before[S-1]`` copies of the
final-node row at each completing event is bit-identical to the host
engine's one-row-per-pending-chain emission.  ``within`` stays gated
OUT here (partial expiry would need per-chain starts, not a count —
the simultaneous-DFA enumeration of arXiv 1512.09228 is the planned
lift); counts are float32 and exact below 2**24 pending chains per
lane, far past the dense engine's instance-lane capacity.

Padding discipline: slots and events beyond the cycle's real work carry
an all-False filter row, which makes BOTH per-event matrices the
identity (M = diag(0) over max-plus, T = I), so padded lanes are
no-ops by construction — no masking epilogue.

State handoff (promotion/demotion) converts between a dense partition
row (``active``/``first_ts`` instance lanes, ops/dense_nfa.py
``init_state_host`` layout) and the scan's per-lane (youngest start,
count) pair: dense node ``j`` holds chains that consumed pattern
events ``1..j`` — exactly scan lane ``j``.  Promotion takes the
youngest active start and the lane population; demotion re-arms
``min(count, I)`` instance lanes (the dense capacity contract — the
excess is counted in the row's ``overflow``) at the youngest start,
which is exact for emissions because starts are unobservable in the
eligible class (no ``within``, no non-final selects).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from siddhi_tpu.core.exceptions import SiddhiAppCreationError
from siddhi_tpu.planner.expr import N_KEY
from siddhi_tpu.query_api import StateInputStream

from .nfa_scan import NEG, ScanPatternEngine

# counts ride float32 add/matmul lanes: exact while < 2**24
COUNT_EXACT_MAX = 1 << 24


class HotKeyScanEngine:
    """H hot-key slots of one linear chain, advanced by one jitted
    batched scan per junction cycle.

    Wraps a ``ScanPatternEngine`` for chain validation and filter
    compilation (its constructor raises ``SiddhiAppCreationError`` with
    the reason for every ineligible shape — the router's fallback
    reasons), then adds the slot axis, the counting scan and the dense
    handoff converters.  State is ``{"v": [H, S] f32, "c": [H, S] f32}``
    — youngest start (relative to ``base_ts``) and pending-chain count
    per lane; lane 0 is the constant lane (v=0, c=1).
    """

    def __init__(self, st: StateInputStream, stream_def, n_slots: int):
        if st.type == StateInputStream.SEQUENCE:
            raise SiddhiAppCreationError(
                "hotkey scan: sequence (consecutive-event) semantics — "
                "the scan keep-transition implements pattern semantics")
        if st.within_ms is not None:
            raise SiddhiAppCreationError(
                "hotkey scan: 'within' needs per-chain starts for "
                "partial expiry; the count abstraction cannot express it")
        base = ScanPatternEngine(st, stream_def)
        self.base = base
        self.jax, self.jnp = base.jax, base.jnp
        self.n_nodes = base.n_nodes
        self.stream_id = base.stream_id
        self.n_slots = int(n_slots)
        self.base_ts: Optional[int] = None
        self._step_fn = None
        # @app:kernels: fuse the max-plus + counting chains into one
        # Pallas kernel (siddhi_tpu/kernels/scan_chain.py) instead of
        # materializing M/T and scanning twice.  Set by
        # planner/kernels.py; flipping it requires resetting _step_fn.
        self.use_kernel = False

    # -- state ---------------------------------------------------------------

    def init_state(self) -> Dict:
        H, S = self.n_slots, self.n_nodes
        v = np.full((H, S), NEG, dtype=np.float32)
        v[:, 0] = 0.0
        c = np.zeros((H, S), dtype=np.float32)
        c[:, 0] = 1.0
        return {"v": self.jnp.asarray(v), "c": self.jnp.asarray(c)}

    def slot_init_rows(self) -> Tuple[np.ndarray, np.ndarray]:
        """Host template of one empty slot (promotion writes start from
        this, demotion resets to it)."""
        S = self.n_nodes
        v = np.full(S, NEG, dtype=np.float32)
        v[0] = 0.0
        c = np.zeros(S, dtype=np.float32)
        c[0] = 1.0
        return v, c

    # -- dense handoff -------------------------------------------------------

    def dense_row_to_slot(self, active: np.ndarray, first_ts: np.ndarray,
                          dense_base: int, scan_base: int
                          ) -> Tuple[np.ndarray, np.ndarray]:
        """One dense partition row (host ``active`` [S, I] bool,
        ``first_ts`` [S, I] int32 rel ``dense_base``) -> scan slot rows
        (v, c) relative to ``scan_base``.  Dense node j == scan lane j;
        every-start engines keep node 0 as the implicit virgin, so only
        lanes 1..S-1 carry chains."""
        v, c = self.slot_init_rows()
        S = self.n_nodes
        for j in range(1, S):
            lanes = active[j]
            nj = int(lanes.sum())
            if nj:
                youngest = int(first_ts[j][lanes].max()) + int(dense_base)
                v[j] = np.float32(youngest - scan_base)
                c[j] = np.float32(nj)
        return v, c

    def slot_to_dense_row(self, v: np.ndarray, c: np.ndarray,
                          scan_base: int, dense_base: int, n_instances: int
                          ) -> Tuple[np.ndarray, np.ndarray, int]:
        """Scan slot rows -> one dense partition row: re-arm
        ``min(count, I)`` instance lanes per node at the youngest start;
        the excess is returned as the row's overflow increment (the
        dense capacity contract for dropped pending chains)."""
        S, I = self.n_nodes, int(n_instances)
        active = np.zeros((S, I), dtype=bool)
        first_ts = np.zeros((S, I), dtype=np.int32)
        dropped = 0
        for j in range(1, S):
            if v[j] <= NEG / 2:
                continue
            cnt = int(round(float(c[j])))
            if cnt <= 0:
                continue
            youngest = int(round(float(v[j]))) + int(scan_base)
            # rel-0 means "unset" in the dense layout; a start exactly at
            # the dense base clamps forward 1ms, which cannot change any
            # emission (starts are unobservable in the eligible class)
            rel = max(youngest - int(dense_base), 1)
            k = min(cnt, I)
            active[j, :k] = True
            first_ts[j, :k] = np.int32(rel)
            dropped += cnt - k
        return active, first_ts, dropped

    # -- jitted batched step -------------------------------------------------

    def _filter_matrix(self, env, H, n):
        """[H, n, S+1] boolean; col j = f_j (col 0 placeholder)."""
        jnp = self.jnp
        cols = [jnp.ones((H, n), dtype=bool)]
        for fs in self.base.filters:
            m = jnp.ones((H, n), dtype=bool)
            for c in fs:
                m = m & jnp.broadcast_to(
                    jnp.asarray(c.fn(env)).astype(bool), (H, n))
            cols.append(m)
        return jnp.stack(cols, axis=2)

    def make_step(self):
        """Jitted (state, cols{attr: [H,n]}, ts_rel [H,n] f32,
        valid [H,n] bool, delta f32) ->
        (state', emit [H,n] f32 row counts, n_rows i32 scalar).

        ``delta`` shifts carried live starts for the cycle's base
        rebase ON DEVICE — state never round-trips to host for
        re-anchoring (the sample engine's host-side shift would be a
        per-cycle sync)."""
        if self._step_fn is not None:
            return self._step_fn
        jax, jnp = self.jax, self.jnp
        S = self.n_nodes

        if self.use_kernel:
            from siddhi_tpu.kernels.scan_chain import fused_scan

            def kstep(state, cols, ts_rel, valid, delta):
                v, c = state["v"], state["c"]
                live = v > NEG / 2
                live = live.at[:, 0].set(False)
                v = jnp.where(live, v - delta, v)
                H, n = ts_rel.shape
                env = dict(cols)
                env[N_KEY] = n
                F = self._filter_matrix(env, H, n) & valid[:, :, None]
                nv, nc, emit = fused_scan(
                    jax, jnp, F.astype(jnp.float32), ts_rel, v, c, NEG)
                n_rows = jnp.sum(emit).astype(jnp.int32)
                return {"v": nv, "c": nc}, emit, n_rows

            self._step_fn = jax.jit(kstep)
            return self._step_fn

        def combine(a, b):
            Ma, Ta = a
            Mb, Tb = b
            # apply a (earlier) then b: max-plus b ⊗ a; counts Tb @ Ta.
            # HIGHEST keeps the count matmul in true f32 on TPU (bf16
            # MXU inputs would corrupt counts past 256)
            return (
                jnp.max(Mb[..., :, :, None] + Ma[..., None, :, :], axis=-2),
                jnp.matmul(Tb, Ta, precision=jax.lax.Precision.HIGHEST),
            )

        def step(state, cols, ts_rel, valid, delta):
            v, c = state["v"], state["c"]
            live = v > NEG / 2
            live = live.at[:, 0].set(False)  # constant lane stays 0
            v = jnp.where(live, v - delta, v)
            H, n = ts_rel.shape
            env = dict(cols)
            env[N_KEY] = n
            F = self._filter_matrix(env, H, n) & valid[:, :, None]
            M = jnp.full((H, n, S, S), NEG, dtype=jnp.float32)
            M = M.at[:, :, 0, 0].set(0.0)
            M = M.at[:, :, 1, 0].set(jnp.where(F[:, :, 1], ts_rel, NEG))
            T = jnp.zeros((H, n, S, S), dtype=jnp.float32)
            T = T.at[:, :, 0, 0].set(1.0)
            T = T.at[:, :, 1, 0].set(F[:, :, 1].astype(jnp.float32))
            for j in range(1, S):
                adv = F[:, :, j + 1]
                M = M.at[:, :, j, j].set(jnp.where(adv, NEG, 0.0))
                T = T.at[:, :, j, j].set((~adv).astype(jnp.float32))
                if j + 1 < S:
                    M = M.at[:, :, j + 1, j].set(jnp.where(adv, 0.0, NEG))
                    T = T.at[:, :, j + 1, j].set(adv.astype(jnp.float32))
            PM, PT = jax.lax.associative_scan(combine, (M, T), axis=1)
            after_v = jnp.max(PM + v[:, None, None, :], axis=-1)
            after_c = jnp.einsum(
                "hnij,hj->hni", PT, c,
                precision=jax.lax.Precision.HIGHEST)
            before_v = jnp.concatenate(
                [v[:, None, :], after_v[:, :-1, :]], axis=1)
            before_c = jnp.concatenate(
                [c[:, None, :], after_c[:, :-1, :]], axis=1)
            start = before_v[:, :, S - 1]
            matched = F[:, :, S] & (start > NEG / 2)
            emit = jnp.where(matched, before_c[:, :, S - 1], 0.0)
            n_rows = jnp.sum(emit).astype(jnp.int32)
            return ({"v": after_v[:, -1, :], "c": after_c[:, -1, :]},
                    emit, n_rows)

        self._step_fn = jax.jit(step)
        return self._step_fn

    # -- host packing helpers ------------------------------------------------

    def rebase(self, cycle_min_ts: int) -> float:
        """Advance ``base_ts`` to just below the cycle's earliest event;
        returns the f32 delta the jitted step must shift carried live
        starts by (0.0 on the first cycle or when time stands still)."""
        new_base = int(cycle_min_ts) - 1
        if self.base_ts is None:
            self.base_ts = new_base
            return 0.0
        if new_base > self.base_ts:
            delta = float(new_base - self.base_ts)
            self.base_ts = new_base
            return delta
        return 0.0

    def pack_cycle(self, slot_pos, cols: Dict[str, np.ndarray],
                   ts: np.ndarray) -> Tuple[Dict[str, np.ndarray], dict]:
        """Pack per-slot event subsets into the fixed ``[H, n_pad]``
        layout.  ``slot_pos``: {slot: positions into the junction batch
        (ascending)}.  Returns (host arrays for one staged_put, meta for
        the deferred emit).  ``n_pad`` is pow2-bucketed so the jitted
        step sees a bounded shape variety."""
        H = self.n_slots
        n_max = max(len(p) for p in slot_pos.values())
        n_pad = max(1 << max(n_max - 1, 1).bit_length(), 16)
        min_ts = min(int(ts[p[0]]) for p in slot_pos.values())
        delta = self.rebase(min_ts)
        ts_pad = np.full((H, n_pad), min_ts, dtype=np.int64)
        valid = np.zeros((H, n_pad), dtype=bool)
        packed: Dict[str, np.ndarray] = {}
        lane_dtype = self.base._lane_dtype
        for a, dt in lane_dtype.items():
            if a in cols:
                packed[a] = np.zeros((H, n_pad), dtype=dt)
        for slot, pos in slot_pos.items():
            k = len(pos)
            ts_pad[slot, :k] = ts[pos]
            valid[slot, :k] = True
            for a in packed:
                packed[a][slot, :k] = cols[a][pos].astype(
                    lane_dtype[a], copy=False)
        rel = (ts_pad - self.base_ts).astype(np.float32)
        put = dict(packed)
        put["__ts_rel"] = rel
        put["__valid"] = valid
        put["__delta"] = np.full((), delta, dtype=np.float32)
        meta = {"slot_pos": slot_pos, "n_pad": n_pad}
        return put, meta

    def dispatch(self, state, put_dev: Dict):
        """Run the jitted step on device-resident packed arrays (the
        router stages them through ``staged_put``).  Returns
        (state', emit_dev [H, n_pad], n_rows_dev scalar)."""
        ts_rel = put_dev.pop("__ts_rel")
        valid = put_dev.pop("__valid")
        delta = put_dev.pop("__delta")
        return self.make_step()(state, put_dev, ts_rel, valid, delta)
